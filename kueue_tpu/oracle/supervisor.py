"""Oracle supervisor: structured degradation for device/executor
faults instead of an unstructured crash (the Arax posture — an
accelerator failure is a survivable, retryable event).

Three layers, all digest-neutral (they decide WHERE a cycle is
decided, never WHAT it decides — both paths are proven
byte-identical):

  * **retry with backoff + jitter** — a transport-level executor call
    (cycle_step / classical_targets) that raises RemoteOracleError is
    retried up to ``max_attempts`` times, sleeping
    ``jitter · min(cap, base·2^attempt)`` between attempts. The jitter
    fraction is DETERMINISTIC (a CRC over the call site and attempt
    ordinal, not a PRNG, and never an input to any decision) so replay
    stays bit-stable while a fleet of engines still decorrelates.
  * **circuit breaker** — after ``threshold`` consecutive failed calls
    the breaker OPENS: try_cycle is refused up front (fallback reason
    ``breaker-open``) and every cycle runs the host decision path,
    which burns no retry time and no socket timeouts. Demotion is
    visible as labeled metrics (oracle_breaker_state,
    oracle_breaker_transitions_total) and, because breaker-open cycles
    are fallback cycles, in the ``fallback_cycle_ratio`` SLO burn rate
    (obs/slo.py) that also drives admission shedding.
  * **probing re-promotion** — after ``cooldown_cycles`` engine cycles
    the breaker goes HALF-OPEN: one cycle probes the device. Success
    closes the breaker (full re-promotion); failure re-opens with the
    cooldown doubled (capped at 8x).

Cooldown is measured in engine cycles, not wall time, so the whole
state machine is a deterministic function of the fault sequence —
replayable and chaos-testable (oracle-crash-storm in replay/faults.py).
"""

from __future__ import annotations

import time
import zlib
from typing import Optional

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
_STATE_CODE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


def _jitter01(*parts) -> float:
    """Deterministic uniform-ish fraction in [0, 1): CRC-32 of the
    call coordinates. Not a PRNG on purpose — no hidden state, no
    draw-order coupling, digest-neutral by construction."""
    raw = zlib.crc32(":".join(str(p) for p in parts).encode("utf-8"))
    return (raw & 0xFFFFFFFF) / 4294967296.0


class OracleSupervisor:
    """Owns retry + breaker state for one OracleBridge."""

    def __init__(self, metrics=None, salt: str = "",
                 max_attempts: int = 3,
                 backoff_base: float = 0.02,
                 backoff_cap: float = 1.0,
                 threshold: int = 3,
                 cooldown_cycles: int = 8,
                 sleep=time.sleep):
        self.metrics = metrics
        self.salt = salt
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.threshold = max(1, int(threshold))
        self.cooldown_cycles = max(1, int(cooldown_cycles))
        self._sleep = sleep
        self.state = CLOSED
        self.consecutive_failures = 0
        self.total_retries = 0
        self.total_failures = 0
        self.demotions = 0
        self.repromotions = 0
        self._cooldown = self.cooldown_cycles
        self._reopen_at: Optional[int] = None  # cycle seq gating probe
        self._export_state()

    # -- the retry wrapper --

    def call(self, site: str, fn, *args, **kwargs):
        """Run one executor call with retry+backoff. Raises the final
        RemoteOracleError after ``max_attempts`` tries (the breaker
        bookkeeping happens in record_failure, called by the bridge's
        error path so non-transport errors count too)."""
        from kueue_tpu.oracle.service import RemoteOracleError

        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except RemoteOracleError:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                self.total_retries += 1
                self._count("oracle_retry_total", (site,))
                delay = _jitter01(self.salt, site, self.total_retries,
                                  attempt) * min(
                    self.backoff_cap,
                    self.backoff_base * (2.0 ** attempt))
                if delay > 0:
                    self._sleep(delay)

    # -- the breaker --

    def allow_cycle(self, seq: int) -> bool:
        """Gate at the top of try_cycle. False = stay demoted (host
        path); True from OPEN means this cycle is the half-open
        probe."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._reopen_at is not None and seq >= self._reopen_at:
                self._transition(HALF_OPEN, "probe window")
                return True
            return False
        return True  # HALF_OPEN: the probe cycle itself

    def record_success(self) -> None:
        """An executor call answered: the device is back."""
        self.consecutive_failures = 0
        if self.state != CLOSED:
            self.repromotions += 1
            self._cooldown = self.cooldown_cycles
            self._transition(CLOSED, "probe succeeded")

    def record_failure(self, seq: int) -> None:
        """A call exhausted its retries (or the cycle died on a device
        fault). In HALF_OPEN the failed probe re-opens with the
        cooldown doubled; in CLOSED ``threshold`` consecutive failures
        demote to the host path."""
        self.consecutive_failures += 1
        self.total_failures += 1
        if self.state == HALF_OPEN:
            self._cooldown = min(self._cooldown * 2,
                                 self.cooldown_cycles * 8)
            self.demotions += 1
            self._reopen_at = seq + self._cooldown
            self._transition(OPEN, "probe failed")
        elif (self.state == CLOSED
              and self.consecutive_failures >= self.threshold):
            self.demotions += 1
            self._reopen_at = seq + self._cooldown
            self._transition(OPEN,
                             f"{self.consecutive_failures} consecutive "
                             f"failures")

    def demote(self, seq: int, reason: str = "external demotion") -> None:
        """Force the breaker OPEN from outside its own failure
        accounting — the cycle watchdog (obs/watchdog.py) and the
        degradation ladder (ha/ladder.py) demote the device path
        through here. Probing re-promotion is unchanged: after the
        cooldown a half-open probe re-closes on success. Already-OPEN
        just extends the probe window (no double-counted demotion)."""
        if self.state == OPEN:
            self._reopen_at = max(self._reopen_at or 0,
                                  seq + self._cooldown)
            return
        self.demotions += 1
        self._reopen_at = seq + self._cooldown
        self._transition(OPEN, reason)

    def _transition(self, to: str, reason: str) -> None:
        if to == self.state:
            return
        self._count("oracle_breaker_transitions_total",
                    (self.state, to))
        self.state = to
        self._export_state()

    # -- observability --

    def _export_state(self) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.gauge("oracle_breaker_state").set(
                (), _STATE_CODE[self.state])
        except KeyError:
            pass

    def _count(self, family: str, labels: tuple) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.counter(family).inc(labels)
        except KeyError:
            pass

    def status(self) -> dict:
        return {
            "state": self.state,
            "consecutiveFailures": self.consecutive_failures,
            "totalRetries": self.total_retries,
            "totalFailures": self.total_failures,
            "demotions": self.demotions,
            "repromotions": self.repromotions,
            "cooldownCycles": self._cooldown,
            "reopenAt": self._reopen_at,
        }
