"""Wire format for the oracle serving boundary: length-prefixed frames
carrying a JSON header plus raw tensor bytes.

This is the process-boundary form of the tensor schema
(tensor/schema.py — "this schema is the system's real API"): the control
plane ships dense snapshot tensors to a standalone oracle process and
receives verdict tensors back (SURVEY §7: "decision core as a JAX/TPU
service", reference apply semantics scheduler.go:856-910). gRPC is not
available in this environment, so framing is a 4-byte big-endian length
followed by:

    [4B header_len][header JSON][tensor bytes...]

The header carries op name, static kwargs, and per-tensor
(name, dtype, shape, byte offset/length) entries; tensor payloads are
C-contiguous numpy buffers concatenated in header order.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any

import numpy as np

_LEN = struct.Struct(">I")


def pack(op: str, tensors: dict[str, np.ndarray],
         meta: dict[str, Any]) -> bytes:
    entries = []
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(np.asarray(arr))
        b = arr.tobytes()
        entries.append({"name": name, "dtype": str(arr.dtype),
                        "shape": list(arr.shape), "off": offset,
                        "len": len(b)})
        blobs.append(b)
        offset += len(b)
    header = json.dumps({"op": op, "meta": meta,
                         "tensors": entries}).encode("utf-8")
    body = _LEN.pack(len(header)) + header + b"".join(blobs)
    return _LEN.pack(len(body)) + body


def unpack(body: bytes):
    (hlen,) = _LEN.unpack_from(body, 0)
    header = json.loads(body[4:4 + hlen].decode("utf-8"))
    base = 4 + hlen
    tensors = {}
    for e in header["tensors"]:
        buf = body[base + e["off"]:base + e["off"] + e["len"]]
        tensors[e["name"]] = np.frombuffer(
            buf, dtype=np.dtype(e["dtype"])).reshape(e["shape"]).copy()
    return header["op"], tensors, header["meta"]


def send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(payload)


def recv_msg(sock: socket.socket) -> bytes:
    head = _recv_exact(sock, 4)
    (n,) = _LEN.unpack(head)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)
