"""Topology ungater: hand each gated pod its topology domain.

Reference: pkg/controller/tas/topology_ungater.go. Once a workload has a
TopologyAssignment, its pods start gated (the jobframework injects the
``kueue.x-k8s.io/topology`` scheduling gate); the ungater removes the
gate and pins each pod to one domain — by rank when the pod set carries a
pod-index label (readRanksIfAvailable :446, rankToDomainID expansion), or
greedily by filling domains in assignment order while accounting for
already-running pods (assignGatedPodsToDomainsGreedy :403).

In our standalone framework a "pod" is the light record below; the engine
uses this to drive per-pod placement for the execution mimic and tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.tas.snapshot import TopologyAssignment

TOPOLOGY_GATE = "kueue.x-k8s.io/topology"


@dataclass
class PodStub:
    """The slice of corev1.Pod the ungater needs."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)
    gated: bool = True
    # Domain values already pinned on ungated pods (node selector).
    domain_values: Optional[tuple[str, ...]] = None


def rank_to_domain(assignment: TopologyAssignment) -> list[tuple[str, ...]]:
    """rankToDomainID: expand the assignment into a rank-indexed list of
    domains (domains in assignment order, each repeated ``count`` times)."""
    out: list[tuple[str, ...]] = []
    for dom in assignment.domains:
        out.extend([tuple(dom.values)] * dom.count)
    return out


def assign_pods_to_domains(
    assignment: TopologyAssignment,
    pods: list[PodStub],
    pod_index_label: Optional[str] = None,
    offset: int = 0,
) -> list[tuple[PodStub, tuple[str, ...]]]:
    """assignGatedPodsToDomains :376: rank-based placement when every
    gated pod carries a valid in-range index label, greedy otherwise.
    Returns (pod, domain_values) for the pods to ungate."""
    ranks = rank_to_domain(assignment)
    max_rank = len(ranks)
    if pod_index_label is not None:
        by_rank: dict[int, PodStub] = {}
        ok = True
        for pod in pods:
            if not pod.gated:
                continue
            raw = pod.labels.get(pod_index_label)
            if raw is None or not raw.isdigit():
                ok = False
                break
            rank = int(raw) - offset
            if not (0 <= rank < max_rank) or rank in by_rank:
                ok = False
                break
            by_rank[rank] = pod
        if ok:
            return [(pod, ranks[rank])
                    for rank, pod in sorted(by_rank.items())]
    return _assign_greedy(assignment, pods)


def _assign_greedy(assignment: TopologyAssignment, pods: list[PodStub]
                   ) -> list[tuple[PodStub, tuple[str, ...]]]:
    """assignGatedPodsToDomainsGreedy :403: fill each domain up to its
    count, skipping capacity already taken by ungated pods."""
    gated = [p for p in pods if p.gated]
    ungated_per_domain: dict[tuple, int] = {}
    for p in pods:
        if not p.gated and p.domain_values is not None:
            ungated_per_domain[tuple(p.domain_values)] = \
                ungated_per_domain.get(tuple(p.domain_values), 0) + 1
    out: list[tuple[PodStub, tuple[str, ...]]] = []
    for dom in assignment.domains:
        already = ungated_per_domain.get(tuple(dom.values), 0)
        room = max(dom.count - already, 0)
        take = min(room, len(gated) - len(out))
        for _ in range(take):
            out.append((gated[len(out)], tuple(dom.values)))
    return out
