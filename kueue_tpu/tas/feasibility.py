"""Batched TAS feasibility pre-pass.

One device launch per flavor forest decides fit/no-fit — with the exact
notFitMessage argument — for every qualifying pending pod set in the
cycle, before nomination walks entries one by one. The scheduler's
oversubscribed steady state re-tries the same unplaceable workloads each
cycle (scheduler.go:614 nominate -> flavorassigner TAS block); the host
pays a full phase-1 + descent per entry for each of those failures,
while the batch pays one sort-free kernel (ops/tas.tas_feasibility) for
all of them.

Exactness: a qualifying request's selection outcome is fully determined
by phase-1 counts (findLevelWithFitDomains :1377 — required: top-domain
slice state at the requested level; preferred: any level's top fit, else
the level-0 greedy sum; unconstrained: the requested level's sum), and
the leaderless descent below a successful selection cannot fail (each
parent's state is the sum of its children's). So the verdict may REJECT
without running placement; successes still run the real placement for
the actual assignment. Requests with leaders, pod-set groups, elastic
previous slices, node-selector leaf filtering, replacement domains, or
the balanced-placement gate fall back to the sequential path
unconditionally.

The live-usage verdict additionally requires that no TAS usage was
removed from the forest since the batch ran (within a cycle usage only
grows as entries are assumed — except around elastic slice simulation,
which disqualifies itself); the simulate-empty verdict is valid for the
whole cycle.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from kueue_tpu.api.types import PodSetTopologyRequest, TopologyMode
from kueue_tpu.config import features

_MODE_NUM = {TopologyMode.REQUIRED: 0, TopologyMode.PREFERRED: 1,
             TopologyMode.UNCONSTRAINED: 2}


def enabled() -> bool:
    return os.environ.get("KUEUE_TPU_TAS_FEAS", "1") != "0"


# Process-wide count of feasibility launches that raised and fell back
# to the per-entry host path (each is also emitted as a
# "tas-feas-fallback" trace event with the exception text).
FALLBACKS = 0


@dataclass(frozen=True)
class Verdict:
    fit_used: bool
    arg_used: int
    fit_empty: bool
    arg_empty: int


def request_signature(pod_set, single_pod_requests, count):
    from kueue_tpu.tas.snapshot import slice_topology_constraints
    tr = pod_set.topology_request
    mode = tr.mode if tr is not None else None
    return (mode, tr.level if tr else None,
            slice_topology_constraints(tr), int(count),
            tuple(sorted(single_pod_requests.items())),
            tuple(sorted((pod_set.node_selector or {}).items())),
            tuple(pod_set.tolerations or ()),
            tuple(tuple(term) for term in (pod_set.node_affinity or ())))


def _qualify(snap, pod_set, single, count):
    """Returns (slice_level_idx, req_level_idx, mode_num, slice_size,
    excluded_leaf_values) or None when the request needs the sequential
    path. Anchored on snapshot.resolve_request so the batch can never
    disagree with the host walk on what a request means; leaf-level
    matchNode filtering (selectors, taints, affinity) feeds the kernel
    as a per-request mask instead of disqualifying the request."""
    if not snap.level_keys:
        return None
    from kueue_tpu.tas.snapshot import TASPodSetRequest
    tr = pod_set.topology_request
    mode = _MODE_NUM.get(tr.mode) if tr is not None else 2
    if mode is None:
        return None
    if (features.enabled("TASBalancedPlacement") and mode == 1):
        return None
    if tr is not None and tr.pod_set_group_name:
        return None
    state, reason = snap.resolve_request(
        TASPodSetRequest(pod_set, single, count), has_leader=False)
    if state is None:
        return None
    if state.slice_size_at_level:
        return None  # multi-layer rounding: host path only
    excluded = snap._match_excluded(pod_set)
    return (state.slice_level_idx, state.requested_level_idx,
            2 if state.unconstrained else mode, state.slice_size,
            frozenset(excluded))


def collect_requests(wl, cq_snapshot):
    """(snap, sig, pod_set, single, count, params) tuples for every
    (TAS flavor x pod set) pair of a pending head that the batch can
    decide. The assigned flavor isn't known before flavor assignment,
    so every candidate TAS flavor of the CQ is covered."""
    if wl.obj.replaced_workload_slice is not None:
        return []
    if getattr(wl.obj.status, "unhealthy_nodes", ()):
        return []
    out = []
    # Identity dedup in tas_flavors insertion order: several flavor
    # names can share a forest, and set() iteration order would vary
    # run-to-run (D1 — launch order feeds the decision digest).
    for snap in {id(s): s for s in
                 cq_snapshot.tas_flavors.values()}.values():
        for i, ps in enumerate(wl.obj.pod_sets):
            single = wl.total_requests[i].single_pod_requests()
            params = _qualify(snap, ps, single, ps.count)
            if params is None:
                continue
            sig = request_signature(ps, single, ps.count)
            out.append((snap, sig, ps, single, ps.count, params))
    return out


def precompute(heads, snapshot) -> None:
    """Run one feasibility launch per flavor forest for the cycle's
    pending heads and park the verdicts on each snap
    (``_feas`` / ``_feas_removals``). Two gates keep the dispatch from
    costing more than it saves: below ``KUEUE_TPU_TAS_FEAS_MIN``
    (default 12) qualifying head requests the batch can't amortize the
    launch, and below ``KUEUE_TPU_TAS_FEAS_MIN_LEAVES`` (default 2048)
    leaves the numpy host phase-1 per head is cheaper than the launch.
    Cost model (measured on the bench worlds, CPU backend): one launch
    at 5,120 leaves costs ~11 ms (kernel + transfers + marshalling)
    and saves ~1.3 ms per head it short-circuits, so it needs roughly
    ten rejected heads to break even — a churn steady state (30
    homogeneous retried heads/cycle) clears that 3x; a draining world
    (8 CQ heads, most of which fit and run the real placement anyway)
    never does, and at 640 leaves the host descent is so cheap the
    launch can never win (the round-4 640-node regression). The
    instance threshold counts request INSTANCES, not distinct
    signatures, because the savings scale with the retries."""
    if not enabled():
        return
    min_batch = int(os.environ.get("KUEUE_TPU_TAS_FEAS_MIN", "12"))
    min_leaves = int(os.environ.get("KUEUE_TPU_TAS_FEAS_MIN_LEAVES",
                                    "2048"))
    by_snap: dict[int, tuple[object, dict, list[int]]] = {}
    for w in heads:
        cqs = snapshot.cluster_queue(w.cluster_queue)
        if cqs is None or not cqs.tas_flavors:
            continue
        for snap, sig, ps, single, count, params in \
                collect_requests(w, cqs):
            if len(snap.leaves) < min_leaves:
                continue
            _, reqs, n = by_snap.setdefault(id(snap), (snap, {}, [0]))
            reqs.setdefault(sig, (single, count, params))
            n[0] += 1
    for snap, reqs, n in by_snap.values():
        snap._feas = None
        snap._feas_reason = ""
        if n[0] >= min_batch:
            try:
                snap._feas = _launch(snap, reqs)
                snap._feas_removals = getattr(snap, "_usage_removals", 0)
            except Exception as exc:  # noqa: BLE001 — pre-pass is optional
                # The pre-pass is an optimization: a failed launch must
                # never fail the cycle. But it must not fail SILENTLY
                # either — a permanently-broken batch quietly costs the
                # host descent per retried head forever. Label the
                # fallback where operators look (cycle trace + counter).
                snap._feas = None
                reason = f"{type(exc).__name__}: {exc}"
                snap._feas_reason = reason
                global FALLBACKS
                FALLBACKS += 1
                from kueue_tpu.obs import hooks as _obs
                _obs.emit("tas-feas-fallback",
                          getattr(snap, "topology_name", "") or "tas",
                          reason=reason, requests=n[0])


def _launch(snap, reqs: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from kueue_tpu.ops import tas as tops
    from kueue_tpu.tas.device import (
        _cols_for,
        _free_matrix,
        _structure,
        _usage_matrix,
    )

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

    struct = _structure(snap)
    sigs = list(reqs)
    all_per_pod = []
    for sig in sigs:
        single, _count, _params = reqs[sig]
        pp = dict(single)
        pp["pods"] = pp.get("pods", 0) + 1
        all_per_pod.append(pp)
    union: dict[str, int] = {}
    for pp in all_per_pod:
        union.update(pp)
    cols = _cols_for(struct, union, {})
    col_of = {res: i for i, res in enumerate(cols)}

    free = _free_matrix(struct, cols)
    usage = _usage_matrix(snap, struct, cols)

    B = len(sigs)
    Bp = 1 << (B - 1).bit_length()  # pow2 pad bounds recompiles
    S = len(cols)
    M = struct["m"]
    leaves_list = struct["leaves"]
    per_pod = np.zeros((Bp, S), np.int64)
    count = np.ones(Bp, np.int64)
    slice_size = np.ones(Bp, np.int64)
    slice_level = np.zeros(Bp, np.int64)
    req_level = np.zeros(Bp, np.int64)
    mode = np.zeros(Bp, np.int64)
    leaf_mask = np.ones((Bp, M), bool)
    any_excluded = False
    for b, sig in enumerate(sigs):
        single, cnt_b, (slice_idx, req_idx, mode_n, ss, excluded) = \
            reqs[sig]
        for res, v in all_per_pod[b].items():
            if res in col_of:
                per_pod[b, col_of[res]] = min(v, 1 << 60)
        count[b] = cnt_b
        slice_size[b] = ss
        slice_level[b] = slice_idx
        req_level[b] = req_idx
        mode[b] = mode_n
        if excluded:
            any_excluded = True
            for i, leaf in enumerate(leaves_list):
                if leaf.values in excluded:
                    leaf_mask[b, i] = False
    # Padding rows: count 1, zero requests -> fit trivially, harmless.

    jnp_cache = struct.setdefault("jnp_cache", {})
    if "consts" not in jnp_cache:
        jnp_cache["consts"] = (
            jnp.asarray(struct["has_pods_cap"]),
            jnp.asarray(struct["valid"]), jnp.asarray(struct["vrank"]),
            jnp.asarray(struct["parent"]))
    j_pods_cap, j_valid, _j_vrank, j_parent = jnp_cache["consts"]
    cols_key = tuple(cols)
    j_free = jnp_cache.get(("free", cols_key))
    if j_free is None:
        j_free = jnp.asarray(free)
        jnp_cache[("free", cols_key)] = j_free
    if not np.any(usage):
        j_usage = jnp_cache.get(("zeros", usage.shape))
        if j_usage is None:
            j_usage = jnp_cache[("zeros", usage.shape)] = jnp.zeros(
                usage.shape, jnp.int64)
    else:
        ukey = (getattr(snap, "_usage_version", 0), cols_key)
        cached_u = getattr(snap, "_j_usage_cache", None)
        if cached_u is not None and cached_u[0] == ukey:
            j_usage = cached_u[1]
        else:
            j_usage = jnp.asarray(usage)
            snap._j_usage_cache = (ukey, j_usage)

    if any_excluded:
        j_leaf_mask = jnp.asarray(leaf_mask)
    else:
        j_leaf_mask = jnp_cache.get(("ones_mask", leaf_mask.shape))
        if j_leaf_mask is None:
            j_leaf_mask = jnp_cache[("ones_mask", leaf_mask.shape)] = \
                jnp.ones(leaf_mask.shape, bool)
    fit, arg = jax.device_get(tops.tas_feasibility(
        j_free, j_usage, jnp.asarray(per_pod),
        jnp.asarray(count), jnp.asarray(slice_size),
        jnp.asarray(slice_level), jnp.asarray(req_level),
        jnp.asarray(mode), j_leaf_mask, j_valid, j_parent, j_pods_cap,
        num_levels=struct["nl"], max_domains=struct["m"],
        pods_col=col_of["pods"]))
    return {sig: Verdict(bool(fit[0, b]), int(arg[0, b]),
                         bool(fit[1, b]), int(arg[1, b]))
            for b, sig in enumerate(sigs)}


def lookup(tas_snap, request):
    """The verdict for a nominate-time request, or None. Callers use
    ``fit_used`` only when ``used_valid(tas_snap)`` still holds."""
    verdicts = getattr(tas_snap, "_feas", None)
    if not verdicts:
        return None
    if request.previous_assignment is not None:
        return None
    sig = request_signature(request.pod_set,
                            request.single_pod_requests, request.count)
    return verdicts.get(sig)


def used_valid(tas_snap) -> bool:
    """Live-usage verdicts assume usage only grew since the batch ran;
    any removal (elastic slice simulation, second-pass replacement)
    invalidates them for the rest of the cycle."""
    return getattr(tas_snap, "_usage_removals", 0) == \
        getattr(tas_snap, "_feas_removals", 0)
