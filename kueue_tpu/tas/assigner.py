"""TAS x flavor-assignment glue: after quota-level flavor assignment,
compute topology placements and adjust the assignment mode.

Reference: pkg/scheduler/flavorassigner/tas_flavorassigner.go and the TAS
block of assignFlavors (flavorassigner.go:783-821):
  * Fit assignment -> try real placement; failure downgrades the pod set
    to Preempt;
  * Preempt assignment -> re-try with simulate-empty; failure downgrades
    to NoFit; success keeps Preempt and records the reservation
    assignment (scheduler.go:836-847).
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.scheduler.flavorassigner import Assignment, Mode
from kueue_tpu.tas.snapshot import TASPodSetRequest
from kueue_tpu.workload_info import WorkloadInfo


def workload_tas_requests(assignment: Assignment, wl: WorkloadInfo,
                          cq_snapshot, previous_slice=None
                          ) -> dict[str, list]:
    """Group the workload's TAS-needing pod sets by assigned TAS flavor
    (flavorassigner.Assignment.WorkloadsTopologyRequests). An elastic
    scale-up/-down slice carries its predecessor's per-pod-set topology
    assignments (``previous_slice``, captured by the cycle before the
    old slice is simulated out of the snapshot) so placement is
    delta-only (tas_elastic_workloads.go:35)."""
    prev_by_ps: dict[str, object] = {}
    old_adm = (previous_slice.obj.status.admission
               if previous_slice is not None
               and previous_slice.obj.status.admission else None)
    if old_adm is not None:
        prev_by_ps = {psa.name: psa.topology_assignment
                      for psa in old_adm.pod_set_assignments
                      if psa.topology_assignment is not None}
    requests: dict[str, list] = {}
    for i, psa in enumerate(assignment.pod_sets):
        ps = wl.obj.pod_sets[i]
        flavor = next((fa.name for fa in psa.flavors.values()
                       if fa.name in cq_snapshot.tas_flavors), None)
        if flavor is None:
            continue
        if ps.topology_request is None and not _tas_only(cq_snapshot):
            continue
        psr = wl.total_requests[i]
        single = psr.single_pod_requests()
        requests.setdefault(flavor, []).append(
            (psa, TASPodSetRequest(
                ps, single, psa.count,
                previous_assignment=prev_by_ps.get(ps.name))))
    return requests


def _tas_only(cq_snapshot) -> bool:
    return bool(cq_snapshot.tas_flavors) and set(
        cq_snapshot.tas_flavors) >= {
        fq.name for rg in cq_snapshot.spec.resource_groups
        for fq in rg.flavors}


def find_assignments(cq_snapshot, tas_requests: dict[str, list],
                     simulate_empty: bool = False, workload=None):
    """Run placement per flavor, accumulating assumed usage between pod
    sets of the same workload
    (clusterqueue_snapshot.go:207 FindTopologyAssignmentsForWorkload;
    grouping/leader/replacement handled by
    FindTopologyAssignmentsForFlavor, tas_flavor_snapshot.go:642).
    Returns (results {psa_name: TopologyAssignment}, failure_reason)."""
    results = {}
    for flavor in sorted(tas_requests):
        tas_snap = cq_snapshot.tas_flavors[flavor]
        pairs = tas_requests[flavor]
        flavor_results, reason = tas_snap.find_topology_assignments_for_flavor(
            [request for _, request in pairs], workload=workload,
            simulate_empty=simulate_empty)
        if reason:
            failed = next((psa.name for psa, request in pairs
                           if psa.name not in flavor_results),
                          pairs[0][0].name)
            return None, (failed, reason)
        results.update(flavor_results)
    return results, None


def _precomputed_failure(tas_requests: dict[str, list], cq_snapshot,
                         simulate_empty: bool):
    """A batched-feasibility rejection for the request the sequential
    path would fail on FIRST (the first order group of the first sorted
    flavor), or None to run the real placement. Exact: the verdict
    carries the notFitMessage argument the host descent would report."""
    from kueue_tpu.tas import feasibility

    flavor = sorted(tas_requests)[0]
    pairs = tas_requests[flavor]
    psa, request = pairs[0]
    tr = request.pod_set.topology_request
    if tr is not None and tr.pod_set_group_name:
        return None  # the first group may pair a leader
    snap = cq_snapshot.tas_flavors[flavor]
    vd = feasibility.lookup(snap, request)
    if vd is None:
        return None
    from kueue_tpu.tas.snapshot import slice_topology_constraints
    constraints = slice_topology_constraints(tr)
    slice_size = constraints[0][1] if constraints else 1
    if slice_size <= 0:
        return None
    sc = request.count // slice_size

    def message(arg):
        # Identical string to the host walk: stats built lazily from the
        # same (request, forest) inputs (snapshot._exclusion_stats).
        per_pod = dict(request.single_pod_requests)
        per_pod["pods"] = per_pod.get("pods", 0) + 1
        stats = snap._exclusion_stats(request.pod_set, per_pod,
                                      simulate_empty, {}, ())
        return snap._not_fit_message(arg, sc, slice_size, stats)

    if simulate_empty:
        if vd.fit_empty:
            return None
        return psa.name, message(vd.arg_empty)
    if vd.fit_used or not feasibility.used_valid(snap):
        return None
    return psa.name, message(vd.arg_used)


def apply_tas_pass(assignment: Assignment, wl: WorkloadInfo,
                   cq_snapshot, previous_slice=None) -> None:
    """The flavorassigner.go:783-821 TAS block."""
    from kueue_tpu.obs import hooks as _obs

    tas_requests = workload_tas_requests(assignment, wl, cq_snapshot,
                                         previous_slice=previous_slice)
    if not tas_requests:
        return
    if _obs.CURRENT is None:
        _apply_tas_pass(assignment, wl, cq_snapshot, tas_requests)
        return
    before = assignment.representative_mode()
    try:
        _apply_tas_pass(assignment, wl, cq_snapshot, tas_requests)
    finally:
        # The feasibility verdict, as the span tree records it: the
        # mode transition the topology pass imposed plus which podsets
        # got a concrete placement.
        _obs.emit(
            "tas", wl.key, before=before.name,
            after=assignment.representative_mode().name,
            placed=sorted(psa.name for psa in assignment.pod_sets
                          if psa.topology_assignment is not None))


def _apply_tas_pass(assignment: Assignment, wl: WorkloadInfo,
                    cq_snapshot, tas_requests) -> None:
    if assignment.representative_mode() == Mode.FIT:
        failure = _precomputed_failure(tas_requests, cq_snapshot,
                                       simulate_empty=False)
        results = None
        if failure is None:
            results, failure = find_assignments(cq_snapshot, tas_requests)
        if failure is not None:
            ps_name, reason = failure
            for psa in assignment.pod_sets:
                if psa.name == ps_name:
                    psa.reasons.append(reason)
            assignment.update_mode(ps_name, Mode.PREEMPT)
        else:
            for psa in assignment.pod_sets:
                if psa.name in results:
                    psa.topology_assignment = results[psa.name]
    if assignment.representative_mode() == Mode.PREEMPT:
        failure = _precomputed_failure(tas_requests, cq_snapshot,
                                       simulate_empty=True)
        if failure is not None:
            ps_name, _ = failure
            assignment.update_mode(ps_name, Mode.NO_FIT)
            return
        results, failure = find_assignments(
            cq_snapshot, tas_requests, simulate_empty=True)
        if failure is not None:
            ps_name, _ = failure
            assignment.update_mode(ps_name, Mode.NO_FIT)
        else:
            # Quota may fit in aggregate while placement is fragmented:
            # keep Preempt and record the simulated reservation.
            for psa in assignment.pod_sets:
                if psa.name in results:
                    psa.topology_assignment = results[psa.name]


def tas_usage_of_assignment(assignment: Assignment, wl: WorkloadInfo,
                            cq_snapshot) -> list:
    """(flavor, values, single_pod_requests, count) tuples for the
    assignment's topology placements (Assignment.ComputeTASNetUsage)."""
    out = []
    for i, psa in enumerate(assignment.pod_sets):
        if psa.topology_assignment is None:
            continue
        flavor = next((fa.name for fa in psa.flavors.values()
                       if fa.name in cq_snapshot.tas_flavors), None)
        if flavor is None:
            continue
        single = wl.total_requests[i].single_pod_requests()
        for dom in psa.topology_assignment.domains:
            out.append((flavor, tuple(dom.values), single, dom.count))
    return out
