"""Topology-aware scheduling (TAS): the gang-placement kernel.

Sequential correctness-oracle implementation of the reference's
pkg/cache/scheduler/tas_flavor_snapshot.go (KEP 2724) — the direct analog
of placing jobs onto TPU pod slices over ICI (within-domain) and DCN
(across domains).

Algorithm (tas_flavor_snapshot.go:933-945):
  Phase 1 (fillInCounts :1750): per leaf domain, compute how many pods fit
  in free capacity (plus leader-aware variants stateWithLeader /
  sliceStateWithLeader / leaderState, fillLeafCounts :1864); bubble counts
  up the topology tree (fillInCountsHelper :1906); at the slice level
  convert pod counts to whole-slice counts.
  Phase 2 (findTopologyAssignment :946): pick the assignment level — the
  requested level for `required`, climbing up for `preferred`, the whole
  forest for `unconstrained`; then descend level-by-level, each time
  sorting child domains (BestFit: sliceState desc, state asc, values asc —
  sortedDomains :1722; LeastFreeCapacity ascending for unconstrained) and
  taking a minimal prefix, with a best-fit optimization for the final
  domain (findBestFitDomainForSlices).

Covered here: required/preferred/unconstrained modes, pod-set slices
(single slice level), leader+workers co-placement (findLeaderAndWorkers
:729, consumeWithLeadersGeneric :1510), balanced placement
(tas_balanced_placement.go, see balanced.py), unhealthy-node replacement
(findReplacementAssignment :747, deleteDomain :884, staleness :878),
taint/selector node filtering, TAS usage accounting.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from kueue_tpu.api.types import (
    PodSet,
    PodSetTopologyRequest,
    Taint,
    Toleration,
    Topology,
    TopologyMode,
)
from kueue_tpu.config import features

HOSTNAME_LABEL = "kubernetes.io/hostname"

_INF = 1 << 60


@dataclass
class Node:
    """A capacity-bearing leaf (the reference uses corev1.Node; we are
    standalone). ``capacity`` is per-resource milli-units."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)
    capacity: dict[str, int] = field(default_factory=dict)
    taints: tuple[Taint, ...] = ()
    ready: bool = True
    # corev1.NodeSpec.Unschedulable (kubectl cordon): excluded from the
    # forest like not-ready nodes (tas_nodes_cache.go node filtering).
    unschedulable: bool = False


@dataclass
class TopologyDomainAssignment:
    values: tuple[str, ...]  # level values root->leaf
    count: int


@dataclass
class TopologyAssignment:
    levels: tuple[str, ...]
    domains: tuple[TopologyDomainAssignment, ...]


def merge_topology_assignments(a: TopologyAssignment,
                               b: TopologyAssignment
                               ) -> TopologyAssignment:
    """mergeTopologyAssignments: sum counts per domain, sorted by
    domain values (the canonical order)."""
    counts: dict[tuple, int] = {}
    for ta in (a, b):
        for dom in ta.domains:
            counts[tuple(dom.values)] = \
                counts.get(tuple(dom.values), 0) + dom.count
    return TopologyAssignment(
        levels=a.levels,
        domains=tuple(TopologyDomainAssignment(values, count)
                      for values, count in sorted(counts.items())))


def truncate_assignment(prev: TopologyAssignment,
                        count: int) -> TopologyAssignment:
    """utiltas.TruncateAssignment: keep the first ``count`` pods in
    domain order (scale-down removes from the tail)."""
    kept = []
    remaining = count
    for dom in prev.domains:
        if remaining <= 0:
            break
        take = min(dom.count, remaining)
        kept.append(TopologyDomainAssignment(dom.values, take))
        remaining -= take
    return TopologyAssignment(levels=prev.levels, domains=tuple(kept))


class _Domain:
    __slots__ = ("id", "values", "parent", "children", "state",
                 "slice_state", "state_with_leader",
                 "slice_state_with_leader", "leader_state",
                 "free_capacity", "tas_usage", "node_name",
                 "node_labels", "node_taints")

    def __init__(self, domain_id, values):
        self.id = domain_id
        self.values = values
        self.parent: Optional[_Domain] = None
        self.children: list[_Domain] = []
        self.state = 0  # pods that fit (phase-1), then assigned count
        self.slice_state = 0
        self.state_with_leader = 0
        self.slice_state_with_leader = 0
        self.leader_state = 0
        self.free_capacity: dict[str, int] = {}
        self.tas_usage: dict[str, int] = {}
        self.node_name: Optional[str] = None
        # Leaf-only node metadata for matchNode (tas_flavor_snapshot.go
        # :1830 — taints, full label set for selectors/affinity).
        self.node_labels: dict[str, str] = {}
        self.node_taints: tuple = ()

    def clear_state(self):
        """tas_balanced_placement.go clearState."""
        self.state = 0
        self.slice_state = 0
        self.state_with_leader = 0
        self.slice_state_with_leader = 0
        self.leader_state = 0
        for c in self.children:
            c.clear_state()

    def clear_leader_capacity(self):
        """tas_balanced_placement.go clearLeaderCapacity."""
        self.state_with_leader = 0
        self.slice_state_with_leader = 0
        self.leader_state = 0
        for c in self.children:
            c.clear_leader_capacity()


def clone_domains(domains: list[_Domain]) -> list[_Domain]:
    """Deep-clone a forest of domains (tas_balanced_placement.go
    cloneDomains) so what-if pruning never mutates phase-1 state."""
    def clone(d: _Domain, parent) -> _Domain:
        c = _Domain(d.id, d.values)
        c.parent = parent
        c.state = d.state
        c.slice_state = d.slice_state
        c.state_with_leader = d.state_with_leader
        c.slice_state_with_leader = d.slice_state_with_leader
        c.leader_state = d.leader_state
        c.free_capacity = d.free_capacity
        c.tas_usage = d.tas_usage
        c.node_name = d.node_name
        c.children = [clone(ch, c) for ch in d.children]
        return c
    return [clone(d, None) for d in domains]


def slice_topology_constraints(tr) -> tuple:
    """util/tas/tas.go:116 (PodSetSliceRequiredTopologyConstraints):
    normalize the multi-layer list and the legacy single-layer fields to
    ((level_label_or_None, size), ...), outermost first. A ``None``
    level means the topology's lowest level (our historical API allowed
    ``slice_size`` alone; the resolver substitutes the leaf level)."""
    if tr is None:
        return ()
    extra = tuple(getattr(tr, "slice_constraints", ()) or ())
    if extra:
        return tuple((str(t), int(s)) for t, s in extra)
    if tr.slice_level is None and not tr.slice_size:
        return ()
    return ((tr.slice_level, int(tr.slice_size or 0)),)


def _taint_to_string(t) -> str:
    """corev1.Taint.ToString (k8s.io/api/core/v1/taint.go:28)."""
    if not t.effect:
        return t.key if not t.value else f"{t.key}={t.value}:"
    if not t.value:
        return f"{t.key}:{t.effect}"
    return f"{t.key}={t.value}:{t.effect}"


def _node_affinity_term_matches(term, labels: dict) -> bool:
    """One requiredDuringScheduling nodeSelectorTerm against a node's
    FULL label set (component-helpers nodeaffinity.NodeSelector.Match —
    unlike the flavor-restricted matcher in scheduler/flavorassigner.py,
    absent keys fail In/Exists here). ``term`` is ((key, op, values),...);
    all expressions must match."""
    for key, op, values in term:
        val = labels.get(key)
        if op == "In":
            if val is None or val not in values:
                return False
        elif op == "NotIn":
            if val is not None and val in values:
                return False
        elif op == "Exists":
            if val is None:
                return False
        elif op == "DoesNotExist":
            if val is not None:
                return False
        elif op in ("Gt", "Lt"):
            try:
                n = int(val)
                bound = int(values[0])
            except (TypeError, ValueError, IndexError):
                return False
            if op == "Gt" and not n > bound:
                return False
            if op == "Lt" and not n < bound:
                return False
        else:
            return False
    return True


class ExclusionStats:
    """tas_flavor_snapshot.go:496 (ExclusionStats): why nodes were
    excluded during placement, rendered into the notFitMessage tail."""

    __slots__ = ("taints", "node_selector", "affinity", "topology_domain",
                 "resources", "total_nodes")

    def __init__(self):
        self.taints: dict[str, int] = {}
        self.node_selector = 0
        self.affinity = 0
        self.topology_domain = 0
        self.resources: dict[str, int] = {}
        self.total_nodes = 0

    def has_exclusions(self) -> bool:
        return (self.node_selector > 0 or self.affinity > 0
                or self.topology_domain > 0 or bool(self.taints)
                or bool(self.resources))

    def format_reasons(self) -> str:
        """formatReasons :551 — entries string-sorted after rendering."""
        reasons = []
        if self.node_selector > 0:
            reasons.append(f"nodeSelector: {self.node_selector}")
        if self.affinity > 0:
            reasons.append(f"affinity: {self.affinity}")
        if self.topology_domain > 0:
            reasons.append(f"topologyDomain: {self.topology_domain}")
        for taint in sorted(self.taints):
            reasons.append(f'taint "{taint}": {self.taints[taint]}')
        for res in sorted(self.resources):
            reasons.append(f'resource "{res}": {self.resources[res]}')
        return ", ".join(sorted(reasons))


@dataclass
class TASPodSetRequest:
    pod_set: PodSet
    single_pod_requests: dict[str, int]
    count: int
    # Elastic workload slices: the admitted predecessor's assignment —
    # scale-up places only the delta, scale-down truncates
    # (tas_elastic_workloads.go:35 handleElasticWorkload).
    previous_assignment: Optional["TopologyAssignment"] = None


@dataclass
class _AssignState:
    """findTopologyAssignmentState (the per-call scratch)."""
    count: int
    slice_size: int
    requested_level_idx: int
    slice_level_idx: int
    required: bool
    unconstrained: bool
    leader_count: int = 0
    # unconstrained under the TASProfileMixed gate → LeastFreeCapacity
    # ordering (tas_flavor_snapshot.go:1498 useLeastFreeCapacityAlgorithm)
    least_free: bool = False
    # level idx -> inner slice size (buildSliceSizeAtLevel :1174)
    slice_size_at_level: dict = field(default_factory=dict)
    # the normalized constraint list when multi-layer is active (drives
    # multiLayerNotFitMessage :2004)
    multi_layer: tuple = ()
    # lazy ExclusionStats builder, memoized per call
    stats_fn: Optional[object] = None
    _stats_memo: Optional[object] = None

    def stats(self):
        if self._stats_memo is None and self.stats_fn is not None:
            self._stats_memo = self.stats_fn()
        return self._stats_memo


class _Phase1Memo:
    """Phase-1 fill + per-level sort order, shared across the heads of
    one cycle. The fillInCounts pass depends on the request's per-pod
    shape, slice geometry, and exclusions — but NOT on its count or
    requested level — so the nominate loop's (typically homogeneous)
    heads can share one fill and one sort per level. Between placements
    only the previous head's descent mutations are reverted: phase 2
    touches nothing outside the per-level candidate lists (selection
    is pure, _update_counts_to_minimum and the descent loops clamp only
    domains handed to them), so the undo log is candidate-list-sized.

    The memo survives usage mutations: every write to a leaf's
    tas_usage while a memo is live lands the leaf in ``stale``
    (_apply_deltas / commit_usage), and the next hit repairs exactly
    those leaves' counts plus their ancestor sums (_p1_repair) instead
    of refilling the forest. That lets the hybrid device cycle — which
    never opens an undo scope on the prototype — reuse one fill across
    cycles, paying only for the handful of leaves each cycle's
    admissions touched.

    Leaderless only: with no leader, state_with_leader ≡ state and
    slice_state_with_leader ≡ slice_state at every domain (fillLeafCounts
    sets them equal at leaves and the bubble's min-diff term is zero),
    which also makes _sorted_with_leader order coincide with _sorted —
    the cached per-level sort serves both call sites."""

    __slots__ = ("key", "undo", "sorts", "stale", "_seen")

    def __init__(self, key: tuple):
        self.key = key
        self.undo: list = []
        self.sorts: dict = {}
        self.stale: set = set()
        self._seen: set = set()

    def restore(self) -> None:
        undo = self.undo
        if not undo:
            return
        for d, state, slice_state, slice_swl, leader_state in undo:
            d.state = state
            d.slice_state = slice_state
            d.slice_state_with_leader = slice_swl
            d.leader_state = leader_state
        undo.clear()
        self._seen.clear()

    def save_list(self, domains: list) -> None:
        """Log the pre-descent state of every domain the next descent
        step may write: _update_counts_to_minimum mutates only members
        of the list handed to it (commit / leader_state clears /
        best-fit swaps all pick from that list), and the slice re-anchor
        loop writes only the current fit set's children — so logging
        each level's candidate list is exact, where the old
        whole-subtree save paid for every descendant of the fit domains
        (~10x the touched set on block-level fits). Deduped per scope:
        a domain surviving several levels keeps its FIRST (pre-descent)
        state."""
        seen = self._seen
        save = self.undo.append
        for d in domains:
            i = id(d)
            if i not in seen:
                seen.add(i)
                save((d, d.state, d.slice_state,
                      d.slice_state_with_leader, d.leader_state))


class TASFlavorSnapshot:
    """tas_flavor_snapshot.go:115."""

    def __init__(self, topology: Topology,
                 flavor_tolerations: tuple[Toleration, ...] = ()):
        self.topology_name = topology.name
        self.level_keys = [lv.node_label for lv in topology.levels]
        self.flavor_tolerations = flavor_tolerations
        self.is_lowest_level_node = (
            bool(self.level_keys) and self.level_keys[-1] == HOSTNAME_LABEL)
        self.domains: dict[tuple, _Domain] = {}
        self.leaves: dict[tuple, _Domain] = {}
        self.roots: dict[tuple, _Domain] = {}
        self.domains_per_level: list[dict[tuple, _Domain]] = [
            {} for _ in self.level_keys]
        # Structure version for the device-path encoding cache
        # (tas/device.py): bumped whenever the forest or capacities
        # change shape.
        self._version = 0

    # -- per-cycle undo scope (the zero-copy snapshot share) --
    #
    # Round 4 forked the whole forest per scheduling cycle (fork(), ~6 ms
    # at 640 leaves / ~16 ms at 5,120) and re-installed every live usage
    # aggregate on the copy. Round 5 replaces that with the reference's
    # own revert-closure pattern (snapshot.go:77 SimulateWorkloadRemoval):
    # the live prototype carries the admitted usage, a cycle opens an
    # undo scope, every in-cycle mutation logs its DELTA, and closing the
    # scope reverts in O(touched leaves). Cache write-through commits
    # (admissions applied after the cycle) bypass the log via
    # commit_usage().

    def begin_cycle(self) -> None:
        """Open an undo scope. A dangling scope (a reader snapshot that
        never closed) is force-closed first — its log is empty, so the
        force-close is free and self-healing."""
        if getattr(self, "_txn", None) is not None:
            self.end_cycle()
        self._txn = []
        self._txn_dirty = False
        self._txn_base_version = getattr(self, "_usage_version", 0)
        self._txn_base_removals = getattr(self, "_usage_removals", 0)

    def end_cycle(self) -> None:
        """Revert the scope's delta log (reverse order) and restore the
        usage-version bookkeeping so pre-cycle encodes stay valid. If a
        commit interleaved (``_txn_dirty``), versions move forward
        instead and the matrix caches are dropped — going backward would
        alias a stale cache entry onto restored-but-different state."""
        txn = getattr(self, "_txn", None)
        if txn is None:
            return
        for leaf, deltas in reversed(txn):
            usage = leaf.tas_usage
            for res, d in deltas.items():
                left = usage.get(res, 0) - d
                if left:
                    usage[res] = left
                else:
                    usage.pop(res, None)
        if self._txn_dirty:
            self._usage_version = getattr(self, "_usage_version", 0) + 1
            self._usage_removals = getattr(self, "_usage_removals", 0) + 1
            self._usage_matrix_cache = None
            self._j_usage_cache = None
        elif txn:
            base = self._txn_base_version
            mc = getattr(self, "_usage_matrix_cache", None)
            if mc:
                for k in [k for k in mc if k[0] != base]:
                    mc.pop(k)
            jc = getattr(self, "_j_usage_cache", None)
            if jc is not None and jc[0][0] != base:
                self._j_usage_cache = None
            self._usage_version = base
            self._usage_removals = self._txn_base_removals
        self._txn = None
        self._feas = None
        self._place_memo = None
        self._stats_memo = None
        self._p1 = None

    def commit_usage(self, values: tuple, deltas: dict[str, int]) -> None:
        """Write-through from the live cache's admitted-side accounting
        (scheduler_cache._account_tas): NOT delta-logged, so the change
        survives end_cycle(). ``deltas`` are pre-aggregated (pod slots
        included), negative for removals."""
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        self._usage_version = getattr(self, "_usage_version", 0) + 1
        if any(v < 0 for v in deltas.values()):
            self._usage_removals = getattr(self, "_usage_removals", 0) + 1
        if getattr(self, "_txn", None) is not None:
            self._txn_dirty = True
        p1 = getattr(self, "_p1", None)
        if p1 is not None:
            p1.stale.add(leaf)
        self._touch_used(leaf)
        usage = leaf.tas_usage
        for res, d in deltas.items():
            left = usage.get(res, 0) + d
            if left:
                usage[res] = left
            else:
                usage.pop(res, None)

    # -- construction (tas_flavor.go / tas_nodes_cache.go) --

    def fork(self) -> "TASFlavorSnapshot":
        """Full per-call copy of the forest (structure shared, usage
        copied): used by what-if probes that outlive a cycle scope
        (bench crossover measurement, tests). The serving path no longer
        forks per cycle — see begin_cycle()."""
        new = TASFlavorSnapshot.__new__(TASFlavorSnapshot)
        new.topology_name = self.topology_name
        new.level_keys = self.level_keys
        new.flavor_tolerations = self.flavor_tolerations
        new.is_lowest_level_node = self.is_lowest_level_node
        new._version = self._version
        new.domains = {}
        new.leaves = {}
        new.roots = {}
        new.domains_per_level = [{} for _ in self.level_keys]

        # Iterative, level by level (parents first — _ensure_domain
        # inserts children before parents, so plain insertion order
        # won't do); direct slot assignment skips __init__ overhead.
        domains = new.domains
        mk = _Domain.__new__
        for level_table in self.domains_per_level:
            for values, d in level_table.items():
                c = mk(_Domain)
                c.id = d.id
                c.values = values
                c.state = 0
                c.slice_state = 0
                c.state_with_leader = 0
                c.slice_state_with_leader = 0
                c.leader_state = 0
                c.free_capacity = d.free_capacity  # shared, read-only
                c.tas_usage = dict(d.tas_usage) if d.tas_usage else {}
                c.node_name = d.node_name
                c.node_labels = d.node_labels  # shared, read-only
                c.node_taints = d.node_taints
                c.children = []
                parent = d.parent
                if parent is None:
                    c.parent = None
                else:
                    c.parent = domains[parent.values]
                    c.parent.children.append(c)
                domains[values] = c
                new.domains_per_level[len(values) - 1][values] = c
                if not d.children:
                    new.leaves[values] = c
        for values in self.roots:
            new.roots[values] = domains[values]
        used = getattr(self, "_used_leaves", None)
        if used:
            new._used_leaves = set(used)
        new._usage_version = getattr(self, "_usage_version", 0)
        new._any_taints = getattr(self, "_any_taints", False)
        # The device encoding (tas/device.py _structure) can remap its
        # cached arrays through the prototype instead of re-deriving.
        new._struct_donor = self
        return new

    def add_node(self, node: Node,
                 non_tas_usage: Optional[dict[str, int]] = None) -> None:
        if not node.ready or node.unschedulable:
            return
        self._version += 1
        values = tuple(node.labels.get(k, "") for k in self.level_keys)
        if "" in values:
            return  # node not labeled for this topology
        leaf = self._ensure_domain(values)
        leaf.node_name = node.name
        leaf.node_labels = dict(node.labels)
        sched_taints = tuple(t for t in node.taints
                             if t.effect in ("NoSchedule", "NoExecute"))
        leaf.node_taints = leaf.node_taints + sched_taints
        if sched_taints:
            self._any_taints = True
        for res, cap in node.capacity.items():
            used = (non_tas_usage or {}).get(res, 0)
            leaf.free_capacity[res] = leaf.free_capacity.get(res, 0) \
                + max(0, cap - used)

    def remove_node(self, node: Node) -> None:
        """Node deletion / NotReady transition (tas_nodes_cache.go): the
        leaf domain disappears, making assignments on it stale."""
        values = tuple(node.labels.get(k, "") for k in self.level_keys)
        leaf = self.leaves.pop(values, None)
        if leaf is None:
            return
        self._version += 1
        self.domains.pop(values, None)
        self.domains_per_level[len(values) - 1].pop(values, None)
        if leaf.parent is not None:
            leaf.parent.children.remove(leaf)

    def _ensure_domain(self, values: tuple) -> _Domain:
        domain = self.domains.get(values)
        if domain is not None:
            return domain
        domain = _Domain(values, values)
        self.domains[values] = domain
        level = len(values) - 1
        self.domains_per_level[level][values] = domain
        if level == len(self.level_keys) - 1:
            self.leaves[values] = domain
        if level == 0:
            self.roots[values] = domain
        else:
            parent = self._ensure_domain(values[:-1])
            domain.parent = parent
            parent.children.append(domain)
        return domain

    # -- usage accounting (updateTASUsage) --

    def _touch_used(self, leaf) -> None:
        """Track leaves carrying TAS usage so dense encoders iterate
        the used subset, not the whole (possibly pod-slice-scale)
        forest."""
        used = getattr(self, "_used_leaves", None)
        if used is None:
            used = self._used_leaves = set()
        used.add(leaf.values)

    def _apply_deltas(self, leaf, deltas: dict[str, int]) -> None:
        """Apply a usage delta to one leaf, logging it for revert when a
        cycle's undo scope is open (begin_cycle)."""
        self._usage_version = getattr(self, "_usage_version", 0) + 1
        p1 = getattr(self, "_p1", None)
        if p1 is not None:
            p1.stale.add(leaf)
        self._touch_used(leaf)
        txn = getattr(self, "_txn", None)
        if txn is not None:
            txn.append((leaf, deltas))
        usage = leaf.tas_usage
        for res, d in deltas.items():
            usage[res] = usage.get(res, 0) + d

    def add_usage(self, values: tuple, requests: dict[str, int],
                  count: int) -> None:
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        deltas = {res: per_pod * count for res, per_pod in requests.items()}
        # Each placed pod occupies a pod slot regardless of its resource
        # requests (tas_flavor_snapshot.go:321 updateTASUsage adds
        # ResourcePods: count on top of the scaled requests).
        deltas["pods"] = deltas.get("pods", 0) + count
        self._apply_deltas(leaf, deltas)

    def remove_usage(self, values: tuple, requests: dict[str, int],
                     count: int) -> None:
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        # Removals can make stale "doesn't fit" conclusions wrong; the
        # feasibility pre-pass keys its live-usage verdicts on this.
        self._usage_removals = getattr(self, "_usage_removals", 0) + 1
        deltas = {res: -per_pod * count for res, per_pod in requests.items()}
        deltas["pods"] = deltas.get("pods", 0) - count
        self._apply_deltas(leaf, deltas)

    def install_usage(self, values: tuple, usage: dict[str, int]) -> None:
        """Add PRE-AGGREGATED usage (already scaled by pod counts, pods
        slots included) to a leaf — the one-pass form the live cache's
        incremental aggregates feed through build_snapshot."""
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        self._apply_deltas(leaf, dict(usage))

    def fits(self, domain_requests) -> bool:
        """clusterqueue_snapshot.go:137 TAS part: every requested domain has
        the free capacity."""
        for values, requests, count in domain_requests:
            leaf = self.leaves.get(tuple(values))
            if leaf is None:
                return False
            for res, per_pod in requests.items():
                free = leaf.free_capacity.get(res, 0) - \
                    leaf.tas_usage.get(res, 0)
                if per_pod * count > free:
                    return False
        return True

    # -- the placement entry points --

    def find_topology_assignments_for_flavor(
        self,
        requests: list[TASPodSetRequest],
        workload=None,
        simulate_empty: bool = False,
        assumed_usage: Optional[dict[tuple, dict[str, int]]] = None,
    ) -> tuple[dict[str, TopologyAssignment], str]:
        """FindTopologyAssignmentsForFlavor (tas_flavor_snapshot.go:642):
        group pod sets by topology group, pick leader+workers per group
        (findLeaderAndWorkers :729), route to the replacement path when the
        workload reports unhealthy nodes. Returns ({name: assignment},
        failure_reason); partial results on failure."""
        assumed = assumed_usage if assumed_usage is not None else {}
        groups: dict[str, list[TASPodSetRequest]] = {}
        order: list[str] = []
        for idx, tr in enumerate(requests):
            key = (tr.pod_set.topology_request.pod_set_group_name
                   if tr.pod_set.topology_request else None) or str(idx)
            if key not in groups:
                order.append(key)
            groups.setdefault(key, []).append(tr)

        unhealthy = tuple(getattr(
            getattr(workload, "status", None), "unhealthy_nodes", ()) or ())

        results: dict[str, TopologyAssignment] = {}
        for key in order:
            trs = groups[key]
            if unhealthy:
                for tr in trs:
                    existing = _existing_assignment(workload,
                                                   tr.pod_set.name)
                    if existing is None:
                        continue
                    if features.enabled(
                            "SkipReassignmentForPodOwnedWorkloads") \
                            and owned_by_single_pod(workload):
                        # The pod cannot relocate and the Workload cannot
                        # outlive it; keep the existing assignment so
                        # admit clears UnhealthyNodes without diverging
                        # from the node the pod actually runs on
                        # (tas_flavor_snapshot.go:679).
                        results[tr.pod_set.name] = existing
                        continue
                    new_assignment, repl, reason = \
                        self.find_replacement_assignment(
                            tr, existing, unhealthy, assumed)
                    if reason:
                        return results, reason
                    results[tr.pod_set.name] = new_assignment
                    _add_assumed(assumed, repl, tr)
                continue
            leader, workers = _find_leader_and_workers(trs)
            if (workers.previous_assignment is not None
                    and features.enabled(
                        "ElasticJobsViaWorkloadSlicesWithTAS")):
                # Delta-only elastic placement is its own sub-gate
                # (kube_features.go ElasticJobsViaWorkloadSlicesWithTAS);
                # off = the replacement places from scratch.
                applied, elastic, reason = self._handle_elastic_workload(
                    workers, leader, assumed,
                    simulate_empty=simulate_empty)
                if applied:
                    if reason:
                        return results, reason
                    results.update(elastic)
                    continue
            assignments, reason = self.find_topology_assignments(
                workers, leader, simulate_empty=simulate_empty,
                assumed_usage=assumed)
            if reason:
                return results, reason
            for tr in trs:
                ta = assignments.get(tr.pod_set.name)
                if ta is not None:
                    results[tr.pod_set.name] = ta
                    _add_assumed(assumed, ta, tr)
        return results, ""

    def _handle_elastic_workload(
        self, workers: TASPodSetRequest,
        leader: Optional[TASPodSetRequest],
        assumed: dict, simulate_empty: bool = False,
    ) -> tuple[bool, dict[str, "TopologyAssignment"], str]:
        """tas_elastic_workloads.go:35 (handleElasticWorkload): keep the
        previous slice's pods fixed — scale-up places only the delta and
        merges, scale-down truncates, same-count reuses. Returns
        (applied, results, failure_reason); applied=False falls back to
        standard placement (stale previous assignment)."""
        prev = workers.previous_assignment
        stale, _domain = self.is_topology_assignment_stale(prev)
        if stale:
            return False, {}, ""
        prev_count = sum(d.count for d in prev.domains)
        results: dict[str, TopologyAssignment] = {}
        if workers.count > prev_count:
            # handleScaleUp (:67): previous pods consume capacity, only
            # the delta is placed fresh, then merged.
            from dataclasses import replace as _replace

            delta = _replace(workers, count=workers.count - prev_count,
                             previous_assignment=None)
            _add_assumed(assumed, prev, workers)
            assignments, reason = self.find_topology_assignments(
                delta, leader, simulate_empty=simulate_empty,
                assumed_usage=assumed)
            if reason:
                return True, {}, reason
            merged = merge_topology_assignments(
                assignments[workers.pod_set.name], prev)
            results[workers.pod_set.name] = merged
            _add_assumed(assumed, assignments[workers.pod_set.name],
                         workers)
            if leader is not None:
                lta = assignments.get(leader.pod_set.name)
                if lta is not None:
                    results[leader.pod_set.name] = lta
                    _add_assumed(assumed, lta, leader)
            return True, results, ""
        if workers.count < prev_count:
            # handleScaleDown (:105): truncate, keep placement.
            kept = truncate_assignment(prev, workers.count)
        else:
            kept = prev
        results[workers.pod_set.name] = kept
        _add_assumed(assumed, kept, workers)
        if leader is not None and leader.previous_assignment is not None:
            results[leader.pod_set.name] = leader.previous_assignment
            _add_assumed(assumed, leader.previous_assignment, leader)
        return True, results, ""

    def find_topology_assignment(
        self,
        request: TASPodSetRequest,
        simulate_empty: bool = False,
        assumed_usage: Optional[dict[tuple, dict[str, int]]] = None,
    ) -> tuple[Optional[TopologyAssignment], str]:
        """Single-pod-set compatibility wrapper over
        find_topology_assignments."""
        assignments, reason = self.find_topology_assignments(
            request, None, simulate_empty=simulate_empty,
            assumed_usage=assumed_usage)
        if reason:
            return None, reason
        return assignments[request.pod_set.name], ""

    def find_topology_assignments(
        self,
        workers: TASPodSetRequest,
        leader: Optional[TASPodSetRequest] = None,
        simulate_empty: bool = False,
        assumed_usage: Optional[dict[tuple, dict[str, int]]] = None,
        required_replacement_domain: tuple = (),
    ) -> tuple[Optional[dict[str, TopologyAssignment]], str]:
        """tas_flavor_snapshot.go:946 (findTopologyAssignment). Returns
        ({pod_set_name: assignment}, failure_reason).

        The device placement program (ops/tas.tas_place via
        tas/device.py) is the serving path for LARGE forests; this
        sequential implementation below is the small-forest fast path,
        the fallback, and the differential-test oracle
        (tests/test_tas_device.py). Per-placement device dispatch costs
        ~1-10ms regardless of problem size, so offload only wins once
        the per-level domain count clears a threshold (measured: the
        host path is ~2x faster at the reference's 640-node scale);
        the measured crossover persisted by tas/calibration.py (or the
        KUEUE_TPU_DEVICE_TAS_MIN override) sets the switch point."""
        # Within-usage-version memo: an oversubscribed cycle retries
        # many heads with identical (signature, selector) requests — the
        # placement outcome is a pure function of (request, usage state),
        # so repeats are dict hits instead of phase-1 + descent reruns.
        # Only leaderless, ungrouped, unaccumulated calls qualify (the
        # assumed-usage dict threads state between a workload's pod
        # sets). Keyed on _usage_version: any usage mutation invalidates.
        memo_key = None
        if (leader is None and not assumed_usage
                and not required_replacement_domain
                and workers.previous_assignment is None):
            from kueue_tpu.tas.feasibility import request_signature
            ver = getattr(self, "_usage_version", 0)
            memo = getattr(self, "_place_memo", None)
            if memo is None or memo[0] != ver or len(memo[1]) > 4096:
                memo = (ver, {})
                self._place_memo = memo
            memo_key = (
                request_signature(workers.pod_set,
                                  workers.single_pod_requests,
                                  workers.count),
                workers.pod_set.name, bool(simulate_empty),
                tuple(sorted(workers.pod_set.node_selector.items())))
            hit = memo[1].get(memo_key)
            if hit is not None:
                return hit
        out = None
        if features.enabled("DeviceTAS"):
            from kueue_tpu.tas import device
            if device.worth_offloading(self):
                out = device.try_find(
                    self, workers, leader, simulate_empty, assumed_usage,
                    required_replacement_domain)
                if out is NotImplemented:
                    out = None
        if out is None:
            out = self.find_topology_assignments_host(
                workers, leader, simulate_empty, assumed_usage,
                required_replacement_domain)
        if memo_key is not None:
            memo[1][memo_key] = out
        return out

    def resolve_request(self, workers: TASPodSetRequest,
                        has_leader: bool) -> tuple:
        """Shared request resolution (findTopologyAssignment :978-1032):
        slice size, requested/slice level indices, mode flags, the
        multi-layer slice-size map. Returns (state, reason) — state is an
        _AssignState on success. Used by the host path, the device
        adapter, and the feasibility batch so the three can never
        disagree on what a request means."""
        tr = workers.pod_set.topology_request
        count = workers.count

        constraints = slice_topology_constraints(tr)
        if len(constraints) > 1 and not features.enabled(
                "TASMultiLayerTopology"):
            # Gate off: additional layers ignored (the annotation parser
            # only emits the list under the gate, jobframework/tas.go:91).
            constraints = constraints[:1]
        # getSliceSizeWithSinglePodAsDefault :1310.
        if constraints:
            slice_size = constraints[0][1]
            if slice_size <= 0:
                return None, ("slice topology requested, but slice size "
                              "not provided")
        else:
            slice_size = 1
        if count % slice_size != 0:
            return None, (
                f"pod count {count} not divisible by slice size {slice_size}")

        implied = tr is None
        mode = tr.mode if tr is not None else None
        required = mode == TopologyMode.REQUIRED
        preferred = mode == TopologyMode.PREFERRED
        slice_only = (not required and not preferred and bool(constraints))
        unconstrained = (mode == TopologyMode.UNCONSTRAINED or implied
                         or slice_only)

        # levelKey :1273 + levelKeyWithImpliedFallback :1263: required/
        # preferred name a level; slice-only anchors at the HIGHEST
        # level; unconstrained (incl. implied) at the LOWEST.
        if required or preferred:
            if tr.level is None or tr.level not in self.level_keys:
                return None, f"no requested topology level: {tr.level}"
            requested_level_idx = self.level_keys.index(tr.level)
        elif slice_only:
            requested_level_idx = 0
        elif unconstrained:
            requested_level_idx = len(self.level_keys) - 1
        else:
            return None, "topology level not specified"

        # sliceLevelKeyWithDefault :1248 — the OUTERMOST constraint's
        # level, defaulting to the lowest level.
        slice_level_key = (constraints[0][0] if constraints
                           and constraints[0][0] is not None
                           else self.level_keys[-1])
        if slice_level_key not in self.level_keys:
            return None, (
                f"no requested topology level for slices: {slice_level_key}")
        slice_level_idx = self.level_keys.index(slice_level_key)
        if requested_level_idx > slice_level_idx:
            named = tr.level if (tr is not None and tr.level) else \
                self.level_keys[requested_level_idx]
            return None, (
                f"podset slice topology {slice_level_key} is above the "
                f"podset topology {named}")

        # buildSliceSizeAtLevel :1174 — inner layers.
        slice_size_at_level: dict[int, int] = {}
        prev_size, prev_idx = slice_size, slice_level_idx
        for layer_key, layer_size in constraints[1:]:
            if layer_key not in self.level_keys:
                return None, ("no requested topology level for additional "
                              f"slice layer: {layer_key}")
            inner_idx = self.level_keys.index(layer_key)
            if inner_idx <= prev_idx:
                return None, (
                    f"additional slice layer topology {layer_key} must be "
                    f"at a lower level than {self.level_keys[prev_idx]}")
            if prev_size % layer_size != 0:
                return None, (
                    f"additional slice layer size {layer_size} must evenly "
                    f"divide parent layer size {prev_size}")
            for lvl in range(prev_idx + 1, inner_idx + 1):
                slice_size_at_level[lvl] = layer_size
            prev_size, prev_idx = layer_size, inner_idx

        state = _AssignState(
            count=count, slice_size=slice_size,
            requested_level_idx=requested_level_idx,
            slice_level_idx=slice_level_idx, required=required,
            unconstrained=unconstrained,
            leader_count=1 if has_leader else 0,
            least_free=(unconstrained
                        and features.enabled("TASProfileMixed")),
            slice_size_at_level=slice_size_at_level,
            multi_layer=constraints if slice_size_at_level else ())
        return state, ""

    def has_level(self, tr) -> bool:
        """HasLevel :1221 — whether the request names topology levels
        this snapshot resolves (the main level via levelKey :1273, the
        slice level, and every multi-layer layer). Used by the delayed
        topology-request gating (scheduler.go second pass)."""
        if tr is None:
            return False
        constraints = slice_topology_constraints(tr)
        mode = tr.mode
        if mode in (TopologyMode.REQUIRED, TopologyMode.PREFERRED):
            main = tr.level
        elif constraints:
            main = self.level_keys[0] if self.level_keys else None
        elif mode == TopologyMode.UNCONSTRAINED:
            main = self.level_keys[-1] if self.level_keys else None
        else:
            main = None
        if main is None or main not in self.level_keys:
            return False
        leaf_key = self.level_keys[-1] if self.level_keys else None
        slice_key = (constraints[0][0] or leaf_key) if constraints \
            else leaf_key
        if slice_key not in self.level_keys:
            return False
        return all((layer_key or leaf_key) in self.level_keys
                   for layer_key, _size in constraints)

    def _match_excluded(self, pod_set) -> dict:
        """matchNode (:1830) over every leaf: {leaf values: reason}
        where reason is ("taint", taint_string) | ("selector",) |
        ("affinity",). Only hostname-lowest topologies match nodes; the
        taint check folds in the flavor's tolerations. Memoized per
        (structure version, selector, tolerations, affinity) — the
        matchingLeavesCache / TASCacheNodeMatchResults analog."""
        if not self.is_lowest_level_node:
            return {}
        selector = pod_set.node_selector or {}
        tolerations = tuple(pod_set.tolerations) + tuple(
            self.flavor_tolerations)
        affinity = tuple(tuple(term) for term in
                         (pod_set.node_affinity or ()))
        if not selector and not affinity \
                and not getattr(self, "_any_taints", False):
            return {}
        key = (tuple(sorted(selector.items())), tolerations, affinity)
        cache = getattr(self, "_match_cache", None)
        if cache is None or cache[0] != self._version:
            cache = (self._version, {})
            self._match_cache = cache
        hit = cache[1].get(key)
        if hit is not None:
            return hit
        excluded: dict[tuple, tuple] = {}
        for values, leaf in self.leaves.items():
            reason = None
            for taint in leaf.node_taints:
                if not any(t.tolerates(taint) for t in tolerations):
                    reason = ("taint", _taint_to_string(taint))
                    break
            if reason is None and selector:
                labels = leaf.node_labels
                if any(labels.get(k) != v for k, v in selector.items()):
                    reason = ("selector",)
            if reason is None and affinity:
                labels = leaf.node_labels
                if not any(_node_affinity_term_matches(term, labels)
                           for term in affinity):
                    reason = ("affinity",)
            if reason is not None:
                excluded[values] = reason
        if len(cache[1]) > 256:
            cache[1].clear()
        cache[1][key] = excluded
        return excluded

    def _count_in_with_limiting(self, per_pod: dict[str, int],
                                remaining: dict[str, int]) -> tuple:
        """resources.Requests.CountInWithLimitingResource
        (pkg/resources/requests.go:208): (pods that fit, the limiting
        resource) — min count, lexicographically-smallest name on ties.
        A leaf without explicit "pods" capacity is unlimited on pods
        (our standalone node model; K8s nodes always report pods)."""
        best = None
        limiting = ""
        for res in sorted(per_pod):
            need = per_pod[res]
            if need == 0:
                continue
            if res == "pods" and res not in remaining:
                continue
            cnt = max(0, remaining.get(res, 0)) // need
            if best is None or cnt < best or (cnt == best
                                              and res < limiting):
                best = cnt
                limiting = res
        return (best if best is not None else 0), limiting

    def _exclusion_stats(self, pod_set, per_pod: dict[str, int],
                         simulate_empty: bool, assumed_usage: dict,
                         required_replacement_domain: tuple
                         ) -> ExclusionStats:
        """Build the failure-path ExclusionStats lazily — a pure function
        of (request, forest state), so EVERY decision path (host walk,
        numpy phase-1, device kernel, feasibility batch) renders the
        identical message by calling this at failure time instead of
        collecting counters inline.

        The walk is O(leaves); a churn cycle renders failure messages
        for MANY homogeneous rejected heads (30 heads x 5,120 leaves
        regressed the device churn path 50x before the memo), so
        results are memoized per (request fingerprint, usage/structure
        version) for the common unaccumulated call shape."""
        key = None
        memo = None
        if not assumed_usage and not required_replacement_domain:
            # ONE version key for both usage variants: simulate-empty
            # stats don't depend on usage, but alternating live/empty
            # renders with differing memo versions would thrash the
            # single-slot memo (they interleave per head).
            ver = (self._version, getattr(self, "_usage_version", 0))
            memo = getattr(self, "_stats_memo", None)
            if memo is None or memo[0] != ver or len(memo[1]) > 1024:
                memo = (ver, {})
                self._stats_memo = memo
            key = (tuple(sorted(per_pod.items())),
                   tuple(sorted(pod_set.node_selector.items())),
                   tuple(pod_set.tolerations),
                   tuple(tuple(t) for t in (pod_set.node_affinity or ())),
                   bool(simulate_empty))
            hit = memo[1].get(key)
            if hit is not None:
                return hit
        stats = ExclusionStats()
        stats.total_nodes = len(self.leaves)
        excluded = self._match_excluded(pod_set)
        for reason in excluded.values():
            if reason[0] == "taint":
                stats.taints[reason[1]] = stats.taints.get(reason[1], 0) + 1
            elif reason[0] == "selector":
                stats.node_selector += 1
            else:
                stats.affinity += 1
        rrd = tuple(required_replacement_domain or ())
        res_order = [(res, need) for res, need in
                     sorted(per_pod.items()) if need > 0]
        if (len(self.leaves) >= 256 and not assumed_usage
                and self._np_resource_exclusions(
                    res_order, simulate_empty, excluded, rrd, stats)):
            pass  # vectorized path filled the resource counts
        else:
            for values, leaf in self.leaves.items():
                if values in excluded:
                    continue
                if rrd and values[:len(rrd)] != rrd:
                    stats.topology_domain += 1
                    continue
                free = leaf.free_capacity
                usage = leaf.tas_usage if not simulate_empty else None
                assumed = assumed_usage.get(leaf.id) if not simulate_empty \
                    else None
                best = None
                limiting = ""
                for res, need in res_order:
                    if res == "pods" and res not in free:
                        continue
                    rem = free.get(res, 0)
                    if usage:
                        rem -= usage.get(res, 0)
                    if assumed:
                        rem -= assumed.get(res, 0)
                    cnt = max(0, rem) // need
                    if best is None or cnt < best:
                        best = cnt
                        limiting = res
                    if best == 0:
                        break  # sorted order: first zero IS the winner
                if best == 0 and limiting:
                    stats.resources[limiting] = \
                        stats.resources.get(limiting, 0) + 1
        if key is not None:
            memo[1][key] = stats
        return stats

    def _np_resource_exclusions(self, res_order, simulate_empty: bool,
                                excluded: dict, rrd: tuple,
                                stats: ExclusionStats) -> bool:
        """Vectorized resource-exclusion counting over the cached leaf
        matrices (device._free_matrix/_usage_matrix) — the per-leaf dict
        walk was the pod-slice-scale message-render bottleneck. Fills
        ``stats.resources``/``topology_domain``; returns False when the
        dense path can't serve (unknown columns)."""
        import numpy as np

        from kueue_tpu.tas import device

        struct = device._structure(self)
        cols = device._cols_for(struct, dict(res_order), {})
        col_of = {res: i for i, res in enumerate(cols)}
        if any(res not in col_of for res, _ in res_order):
            return False
        free = device._free_matrix(struct, cols)
        if simulate_empty:
            remaining = free
        else:
            remaining = free - device._usage_matrix(self, struct, cols)
        leaves = struct["leaves"]
        m = len(leaves)
        alive = struct["valid"][struct["nl"] - 1][:].copy()
        alive[m:] = False
        if excluded or rrd:
            for i, leaf in enumerate(leaves):
                if leaf.values in excluded:
                    alive[i] = False
                elif rrd and leaf.values[:len(rrd)] != rrd:
                    alive[i] = False
                    stats.topology_domain += 1
        # First zero-count resource in sorted order per leaf (the
        # CountInWithLimitingResource min+lexicographic tie rule: zero
        # is the global minimum, first-in-sorted-order wins ties).
        undecided = alive.copy()
        pods_cap = struct["has_pods_cap"]
        for res, need in res_order:
            ci = col_of[res]
            zero = remaining[:len(undecided), ci] < need
            if res == "pods":
                zero = zero & pods_cap[:len(undecided)]
            hit = undecided & zero
            n = int(hit.sum())
            if n:
                stats.resources[res] = stats.resources.get(res, 0) + n
                undecided = undecided & ~hit
        return True

    def find_topology_assignments_host(
        self,
        workers: TASPodSetRequest,
        leader: Optional[TASPodSetRequest] = None,
        simulate_empty: bool = False,
        assumed_usage: Optional[dict[tuple, dict[str, int]]] = None,
        required_replacement_domain: tuple = (),
    ) -> tuple[Optional[dict[str, TopologyAssignment]], str]:
        """The sequential oracle path of find_topology_assignments."""
        state, reason = self.resolve_request(workers, leader is not None)
        if reason:
            return None, reason
        count = workers.count

        per_pod = dict(workers.single_pod_requests)
        per_pod["pods"] = per_pod.get("pods", 0) + 1
        leader_per_pod = None
        if leader is not None:
            leader_per_pod = dict(leader.single_pod_requests)
            leader_per_pod["pods"] = leader_per_pod.get("pods", 0) + 1

        assumed = assumed_usage or {}
        state.stats_fn = lambda: self._exclusion_stats(
            workers.pod_set, per_pod, simulate_empty, assumed,
            required_replacement_domain)

        # Phase 1: per-domain fit counts — memoized across the heads of
        # a cycle (_Phase1Memo). Balanced-placement candidates are
        # excluded because balanced.apply re-aggregates clones through
        # bubble_up, stomping counts outside any selected subtree.
        p1 = None
        if (leader is None and not assumed and not required_replacement_domain
                and not (features.enabled("TASBalancedPlacement")
                         and not state.required and not state.unconstrained)):
            excluded = self._match_excluded(workers.pod_set)
            p1_key = (
                self._version, bool(simulate_empty),
                tuple(sorted(per_pod.items())),
                state.slice_size, state.slice_level_idx,
                tuple(sorted(state.slice_size_at_level.items())),
                id(excluded) if excluded else 0)
            p1 = getattr(self, "_p1", None)
            hit = p1 is not None and p1.key == p1_key
            if hit:
                p1.restore()
                if p1.stale:
                    # Simulate-empty counts ignore usage entirely; live
                    # counts get the touched leaves recomputed in place.
                    hit = simulate_empty or self._p1_repair(
                        p1, per_pod, excluded, state)
                    p1.stale.clear()
            if hit:
                self._p1_shares = getattr(self, "_p1_shares", 0) + 1
            else:
                self._fill_in_counts(workers.pod_set, per_pod, None,
                                     state, simulate_empty, assumed,
                                     required_replacement_domain)
                p1 = _Phase1Memo(p1_key)
                self._p1_fills = getattr(self, "_p1_fills", 0) + 1
            self._p1 = p1
        else:
            self._fill_in_counts(workers.pod_set, per_pod, leader_per_pod,
                                 state, simulate_empty, assumed,
                                 required_replacement_domain)

        slice_size = state.slice_size
        slice_level_idx = state.slice_level_idx
        unconstrained = state.unconstrained
        slice_count = count // slice_size

        # Phase 2a: balanced placement (preferred mode only), else find
        # the level with fitting domains (tas_flavor_snapshot.go:1060-1087).
        fit_domains = None
        fit_level_idx = 0
        used_balanced = False
        if (features.enabled("TASBalancedPlacement")
                and not state.required and not unconstrained):
            from kueue_tpu.tas import balanced
            cand, threshold = balanced.find_best_domains(self, state)
            if threshold > 0:
                fit_domains, fit_level_idx, reason = balanced.apply(
                    self, state, threshold, cand)
                used_balanced = not reason
        if not used_balanced:
            fit_level_idx, fit_domains, reason = self._find_level_with_fit(
                state.requested_level_idx, slice_count, state,
                sort_cache=p1.sorts if p1 is not None else None)
            if reason:
                return None, reason

        # Phase 2b: minimize the chosen domains, then descend
        # (tas_flavor_snapshot.go:1085-1130). The descent always orders
        # children with sortedDomains — leader consumption happens inside
        # the consume walk, not via the with-leader sort (that one is
        # selection-level only, :1387).
        if p1 is not None:
            p1.save_list(fit_domains)
        fit_domains = self._update_counts_to_minimum(
            fit_domains, count, state.leader_count, slice_size,
            state.least_free, use_slices=True)
        if fit_domains is None:
            return None, "internal: assignment accounting underflow"
        level = fit_level_idx
        while level < min(len(self.level_keys) - 1, slice_level_idx) \
                and not used_balanced:
            children = [c for d in fit_domains for c in d.children]
            if p1 is not None:
                p1.save_list(children)
            lower = self._sorted(children, state.least_free)
            fit_domains = self._update_counts_to_minimum(
                lower, count, state.leader_count, slice_size,
                state.least_free, use_slices=True)
            if fit_domains is None:
                return None, "internal: assignment accounting underflow"
            level += 1
        while level < len(self.level_keys) - 1:
            # At/below the slice level (or after balanced placement), pods
            # are distributed per parent domain
            # (tas_flavor_snapshot.go:1095-1130); inner multi-layer
            # constraints re-anchor the slice size per level.
            if level >= slice_level_idx:
                slice_on_level = state.slice_size_at_level.get(level + 1, 1)
            else:
                slice_on_level = slice_size
            new_fit = []
            for d in fit_domains:
                lower = self._sorted(d.children, state.least_free)
                if p1 is not None:
                    p1.save_list(lower)
                if slice_on_level > 1:
                    for c in lower:
                        c.slice_state = c.state // slice_on_level
                        c.slice_state_with_leader = \
                            c.state_with_leader // slice_on_level
                out = self._update_counts_to_minimum(
                    lower, d.state, d.leader_state, slice_on_level,
                    state.least_free, use_slices=slice_on_level > 1)
                if out is None:
                    return None, "internal: assignment accounting underflow"
                new_fit.extend(out)
            fit_domains = new_fit
            level += 1

        # Leader/worker split (tas_flavor_snapshot.go:1134-1157): leaders
        # land in the chosen domains that reserved leader capacity.
        assignments: dict[str, TopologyAssignment] = {}
        if leader is not None:
            leader_domains = []
            worker_domains = []
            for d in fit_domains:
                if d.leader_state > 0:
                    leader_domains.append(
                        TopologyDomainAssignment(d.values, d.leader_state))
                if d.state > 0:
                    worker_domains.append(d)
            assignments[leader.pod_set.name] = TopologyAssignment(
                tuple(self.level_keys),
                tuple(sorted(leader_domains, key=lambda a: a.values)))
            fit_domains = worker_domains

        domains = sorted(
            (TopologyDomainAssignment(d.values, d.state)
             for d in fit_domains if d.state > 0),
            key=lambda a: a.values)
        assignments[workers.pod_set.name] = TopologyAssignment(
            tuple(self.level_keys), tuple(domains))
        return assignments, ""

    # -- unhealthy-node replacement (tas_flavor_snapshot.go:747) --

    def is_topology_assignment_stale(
            self, assignment: TopologyAssignment) -> tuple[bool, str]:
        """IsTopologyAssignmentStale :878 — domains that no longer exist
        (node deleted / NotReady)."""
        for dom in assignment.domains:
            if tuple(dom.values) not in self.domains:
                return True, dom.values[0]
        return False, ""

    def find_replacement_assignment(
        self,
        tr: TASPodSetRequest,
        existing: TopologyAssignment,
        unhealthy_nodes,
        assumed_usage: dict,
    ) -> tuple[Optional[TopologyAssignment], Optional[TopologyAssignment],
               str]:
        """findReplacementAssignment :747: drop the unhealthy nodes'
        domains from the existing assignment, re-place only the affected
        pods (pinned to the required replacement domain when
        slices/required demand it), and merge. Unlike the reference (one
        node per pass), all currently-unhealthy nodes are replaced in one
        shot — in our model a failed node leaves the topology immediately,
        so a second dead node would otherwise trip the staleness check
        forever. Returns (new_full_assignment, replacement_only,
        reason)."""
        if isinstance(unhealthy_nodes, str):
            unhealthy_nodes = (unhealthy_nodes,)
        kept, affected = _delete_domains(existing, unhealthy_nodes)
        stale, stale_domain = self.is_topology_assignment_stale(kept)
        if stale:
            return None, None, (
                "cannot replace the node: existing topologyAssignment "
                f"contains the stale domain {stale_domain!r}")
        if affected == 0:
            return kept, TopologyAssignment(existing.levels, ()), ""
        required_domain = self._required_replacement_domain(tr, kept,
                                                           affected)
        tr_copy = TASPodSetRequest(tr.pod_set, tr.single_pod_requests,
                                   affected)
        treq = tr.pod_set.topology_request
        slice_size = (treq.slice_size or 1) if treq else 1
        if slice_size > 1 and required_domain and affected % slice_size != 0:
            # The replacement alone is not whole slices; keep leaf-level
            # grouping by dropping the slice constraint for the re-find
            # (the innermost dividing constraint, :768-789).
            tr_copy = TASPodSetRequest(
                replace(tr.pod_set,
                        topology_request=replace(treq, slice_size=None,
                                                 slice_level=None)),
                tr.single_pod_requests, affected)
        assignments, reason = self.find_topology_assignments(
            tr_copy, None, assumed_usage=assumed_usage,
            required_replacement_domain=required_domain)
        if reason:
            return None, None, reason
        repl = assignments[tr.pod_set.name]
        if not repl.domains:
            return None, None, (
                f"cannot find replacement assignment for unhealthy "
                f"node(s): {', '.join(unhealthy_nodes)}")
        merged = _merge_assignments(repl, kept)
        return merged, repl, ""

    def _required_replacement_domain(self, tr: TASPodSetRequest,
                                     kept: TopologyAssignment,
                                     missing: int) -> tuple:
        """requiredReplacementDomain :826: the domain the replacement must
        stay inside — the incomplete-slice domain for sliced pod sets, or
        the original required-level domain for required mode."""
        treq = tr.pod_set.topology_request
        if treq is None or not kept.domains:
            return ()
        slice_size = treq.slice_size or 1
        remaining = sum(d.count for d in kept.domains)
        if slice_size > 1 and (remaining + missing) % slice_size == 0 \
                and remaining % slice_size != 0:
            # findIncompleteSliceDomain :905: the slice-level domain whose
            # pod count needs topping up to a whole slice.
            slice_key = treq.slice_level or self.level_keys[-1]
            if slice_key not in self.level_keys:
                return ()
            slice_idx = self.level_keys.index(slice_key)
            usage: dict[tuple, int] = {}
            for dom in kept.domains:
                usage[tuple(dom.values[:slice_idx + 1])] = \
                    usage.get(tuple(dom.values[:slice_idx + 1]), 0) \
                    + dom.count
            for values, count in sorted(usage.items()):
                if (count + missing) % slice_size == 0:
                    return values
            return ()
        if treq.mode != TopologyMode.REQUIRED or treq.level is None:
            return ()
        if treq.level not in self.level_keys:
            return ()
        level_idx = self.level_keys.index(treq.level)
        return tuple(kept.domains[0].values[:level_idx + 1])

    # -- internals --

    def _leaf_fits(self, pod_set: PodSet, per_pod: dict[str, int],
                   leader_per_pod: Optional[dict[str, int]],
                   leaf: _Domain, simulate_empty: bool,
                   assumed_usage: dict,
                   required_replacement_domain: tuple,
                   excluded: dict) -> None:
        """fillLeafCounts :1864 — pods that fit, plus leader variants.
        ``excluded`` is the matchNode verdict map (_match_excluded):
        taints / full-label selectors / required node affinity."""
        leaf.state = 0
        leaf.leader_state = 0
        leaf.state_with_leader = 0
        if required_replacement_domain and \
                leaf.values[:len(required_replacement_domain)] != \
                required_replacement_domain:
            return
        if leaf.values in excluded:
            return

        remaining = dict(leaf.free_capacity)
        if not simulate_empty:
            for res, used in leaf.tas_usage.items():
                remaining[res] = remaining.get(res, 0) - used
            for res, used in assumed_usage.get(leaf.id, {}).items():
                remaining[res] = remaining.get(res, 0) - used

        def count_in(requests: dict[str, int]) -> int:
            counts = []
            for res, need in requests.items():
                if need == 0:
                    continue
                if res == "pods" and res not in leaf.free_capacity:
                    continue  # no explicit pod capacity: unlimited
                counts.append(max(0, remaining.get(res, 0)) // need)
            return min(counts) if counts else 0

        leaf.state = count_in(per_pod)
        if leader_per_pod is not None and count_in(leader_per_pod) > 0:
            leaf.leader_state = 1
            for res, need in leader_per_pod.items():
                remaining[res] = remaining.get(res, 0) - need
        # stateWithLeader is CountIn(remaining) UNCONDITIONALLY
        # (fillLeafCounts :1897): when the leader doesn't fit here it
        # equals state — the descent consume walk takes full worker
        # capacity from leaderless domains instead of wasting them.
        leaf.state_with_leader = count_in(per_pod)

    def _p1_repair(self, p1, per_pod: dict[str, int], excluded: dict,
                   state: _AssignState) -> bool:
        """Refresh phase-1 counts for the leaves whose tas_usage changed
        while the memo was live, plus their ancestor sums — the
        incremental form of fill_in_counts_np for the leaderless,
        no-assumed-usage, single-slice-size case (the memo's
        eligibility gate). Mirrors the vectorized leaf formula exactly:
        per-resource clamped floor-division, "pods" unconstrained for
        leaves without explicit pod capacity, matchNode exclusions
        zeroing the leaf. Returns False when the drift is too large to
        beat a refill or the geometry is out of scope."""
        if state.slice_size_at_level:
            return False
        if len(p1.stale) > 64:
            return False
        nl = len(self.level_keys)
        slice_size = state.slice_size
        slice_idx = state.slice_level_idx
        leaf_level = nl - 1
        changed: list = []
        parents: dict[int, _Domain] = {}
        for leaf in sorted(p1.stale, key=lambda d: d.values):
            if leaf.values not in self.leaves:
                continue  # removed node: _version bump misses the key
            if excluded and leaf.values in excluded:
                cnt = 0
            else:
                free = leaf.free_capacity
                usage = leaf.tas_usage
                cnt = _INF
                applied = False
                for res, need in per_pod.items():
                    if need <= 0:
                        continue
                    c = max(0, free.get(res, 0)
                            - usage.get(res, 0)) // need
                    if res == "pods":
                        if "pods" not in free:
                            continue  # unconstrained (fillLeafCounts)
                    applied = True
                    if c < cnt:
                        cnt = c
                if not applied:
                    cnt = 0
            leaf.state = cnt
            leaf.state_with_leader = cnt
            leaf.leader_state = 0
            sl = cnt // slice_size if leaf_level == slice_idx else 0
            leaf.slice_state = sl
            leaf.slice_state_with_leader = sl
            changed.append(leaf)
            d = leaf.parent
            while d is not None and id(d) not in parents:
                parents[id(d)] = d
                d = d.parent
        # Ancestors bottom-up (deepest level first): each sum reads the
        # children's already-current counts.
        for d in sorted(parents.values(), key=lambda a: -len(a.values)):
            st = 0
            for c in d.children:
                st += c.state
            lvl = len(d.values) - 1
            if lvl == slice_idx:
                sl = st // slice_size
            elif lvl < slice_idx:
                sl = 0
                for c in d.children:
                    sl += c.slice_state
            else:
                sl = 0
            d.state = st
            d.state_with_leader = st
            d.leader_state = 0
            d.slice_state = sl
            d.slice_state_with_leader = sl
            changed.append(d)
        if p1.sorts and changed:
            from bisect import insort
            by_level: dict[int, list] = {}
            for d in changed:
                by_level.setdefault(len(d.values) - 1, []).append(d)
            for (lvl, least_free), lst in p1.sorts.items():
                ch = by_level.get(lvl)
                if not ch:
                    continue
                if least_free:
                    def keyf(x):
                        return (-x.leader_state,
                                x.slice_state_with_leader,
                                x.state_with_leader, x.values)
                else:
                    def keyf(x):
                        return (-x.leader_state,
                                -x.slice_state_with_leader,
                                x.state_with_leader, x.values)
                try:
                    for d in ch:
                        lst.remove(d)  # identity (_Domain has no __eq__)
                except ValueError:
                    # A changed domain missing from a cached level order
                    # means the memo predates a structure change the key
                    # should have caught — discard it (the caller refills
                    # and builds a fresh memo).
                    return False
                for d in ch:
                    insort(lst, d, key=keyf)
        self._p1_repairs = getattr(self, "_p1_repairs", 0) + 1
        return True

    def _fill_in_counts(self, pod_set: PodSet, per_pod: dict[str, int],
                        leader_per_pod: Optional[dict[str, int]],
                        state: _AssignState, simulate_empty: bool,
                        assumed_usage: dict,
                        required_replacement_domain: tuple = ()) -> None:
        """fillInCounts :1750. The no-leader case runs as numpy
        reductions over the cached leaf matrices (tas/device.py
        fill_in_counts_np — ~10x the per-leaf dict walk); leader
        co-placement keeps the object walk (min-diff bubbling)."""
        # Any fill stomps every domain's count fields: whoever called —
        # including balanced pruning via bubble_up after this returns —
        # owns them now. The memoized host path re-installs its memo
        # right after this call; every other caller leaves it dead.
        self._p1 = None
        excluded = self._match_excluded(pod_set)
        if leader_per_pod is None:
            from kueue_tpu.tas import device
            if device.fill_in_counts_np(
                    self, pod_set, per_pod, state.slice_size,
                    state.slice_level_idx, simulate_empty,
                    assumed_usage or {}, required_replacement_domain,
                    excluded, state.slice_size_at_level):
                return
        for d in self.domains.values():
            d.state = 0
            d.slice_state = 0
            d.state_with_leader = 0
            d.slice_state_with_leader = 0
            d.leader_state = 0
        for leaf in self.leaves.values():
            self._leaf_fits(pod_set, per_pod, leader_per_pod, leaf,
                            simulate_empty, assumed_usage,
                            required_replacement_domain, excluded)
        for root in self.roots.values():
            self.bubble_up(root, state.slice_size, state.slice_level_idx,
                           0, leader_required=state.leader_count > 0,
                           slice_size_at_level=state.slice_size_at_level)

    def bubble_up(self, domain: _Domain, slice_size: int,
                  slice_level_idx: int, level: int,
                  leader_required: bool,
                  slice_size_at_level: Optional[dict] = None) -> None:
        """fillInCountsHelper :1906 — roll child capacities up one subtree.
        Also used by balanced-placement pruning to re-aggregate clones.
        With multi-layer constraints, children at a constrained level
        contribute pods rounded down to multiples of the inner slice
        size (:1925-1930)."""
        if not domain.children:
            if level == slice_level_idx:
                domain.slice_state = domain.state // slice_size
                domain.slice_state_with_leader = \
                    domain.state_with_leader // slice_size
            return
        children_capacity = 0
        slice_capacity = 0
        has_leader_contributor = False
        min_state_diff = _INF
        min_slice_diff = _INF
        leader_state = 0
        inner = (slice_size_at_level or {}).get(level + 1)
        for child in domain.children:
            self.bubble_up(child, slice_size, slice_level_idx, level + 1,
                           leader_required,
                           slice_size_at_level=slice_size_at_level)
            child_state = child.state
            child_swl = child.state_with_leader
            if inner:
                child_state = (child_state // inner) * inner
                child_swl = (child_swl // inner) * inner
            children_capacity += child_state
            slice_capacity += child.slice_state
            if not leader_required or child.leader_state > 0:
                has_leader_contributor = True
                min_state_diff = min(min_state_diff,
                                     child_state - child_swl)
                min_slice_diff = min(
                    min_slice_diff,
                    child.slice_state - child.slice_state_with_leader)
            leader_state = max(leader_state, child.leader_state)
        domain.state = children_capacity
        slice_with_leader = 0
        if has_leader_contributor:
            domain.state_with_leader = children_capacity - min_state_diff
            slice_with_leader = slice_capacity - min_slice_diff
        else:
            domain.state_with_leader = 0
        domain.leader_state = leader_state
        if level == slice_level_idx:
            slice_capacity = domain.state // slice_size
            slice_with_leader = domain.state_with_leader // slice_size
        domain.slice_state = slice_capacity
        domain.slice_state_with_leader = slice_with_leader

    def _sorted(self, domains: list, least_free: bool) -> list:
        """sortedDomains :1721 — BestFit order (sliceState desc, state asc,
        values asc), or LeastFreeCapacity ascending under the
        TASProfileMixed unconstrained profile."""
        if least_free:
            return sorted(domains,
                          key=lambda d: (d.slice_state, d.state, d.values))
        return sorted(domains,
                      key=lambda d: (-d.slice_state, d.state, d.values))

    def _sorted_with_leader(self, domains: list,
                            least_free: bool) -> list:
        """sortedDomainsWithLeader :1683 — leader capacity first."""
        if least_free:
            return sorted(domains, key=lambda d: (
                -d.leader_state, d.slice_state_with_leader,
                d.state_with_leader, d.values))
        return sorted(domains, key=lambda d: (
            -d.leader_state, -d.slice_state_with_leader,
            d.state_with_leader, d.values))

    def _find_level_with_fit(self, level_idx: int, slice_count: int,
                             state: _AssignState, sort_cache=None):
        """findLevelWithFitDomains :1377. ``sort_cache`` (a _Phase1Memo
        sorts dict, leaderless callers only) shares the per-level sorted
        order across the heads of a cycle: selection never mutates
        counts, and the memo's restore() reverts descent mutations
        before the next head sorts, so the cached order stays exact."""
        sorted_domains = None
        cache_key = (level_idx, state.least_free)
        if sort_cache is not None:
            sorted_domains = sort_cache.get(cache_key)
        if sorted_domains is None:
            domains = list(self.domains_per_level[level_idx].values()) \
                if self.level_keys else []
            if not domains:
                level_name = (self.level_keys[level_idx]
                              if self.level_keys else "")
                return 0, [], f"no topology domains at level: {level_name}"
            sorted_domains = self._sorted_with_leader(domains,
                                                     state.least_free)
            if sort_cache is not None:
                sort_cache[cache_key] = sorted_domains
        top = sorted_domains[0]
        if not state.least_free \
                and top.slice_state_with_leader >= slice_count \
                and top.leader_state >= state.leader_count:
            # optimize the potentially last domain
            top = _best_fit_for_slices(sorted_domains, slice_count,
                                       state.leader_count)
        if state.least_free:
            # LeastFreeCapacity: the fullest single domain that fits.
            # Deliberate deviation: when a leader must co-place, the
            # single-domain scan also requires leader capacity — the
            # reference checks only sliceState (:1402) and then emits an
            # empty assignment when the chosen domain can't host the
            # leader; requiring it here lets such groups fall through to
            # the multi-domain greedy and place correctly.
            for d in sorted_domains:
                if d.slice_state >= slice_count and (
                        state.leader_count == 0
                        or (d.slice_state_with_leader >= slice_count
                            and d.leader_state >= state.leader_count)):
                    return level_idx, [d], ""
        if top.slice_state_with_leader < slice_count or \
                top.leader_state < state.leader_count:
            if state.required:
                return 0, [], self._not_fit(state, top.slice_state,
                                            slice_count, level_idx)
            if level_idx > 0 and not state.unconstrained:
                return self._find_level_with_fit(level_idx - 1, slice_count,
                                                 state,
                                                 sort_cache=sort_cache)
            # Multi-domain greedy (:1430-1469): leaders first, then the
            # remaining domains re-sorted by worker capacity.
            results = []
            remaining = slice_count
            remaining_leaders = state.leader_count
            idx = 0
            while remaining_leaders > 0 and idx < len(sorted_domains) \
                    and sorted_domains[idx].leader_state > 0:
                d = sorted_domains[idx]
                if not state.least_free and \
                        d.slice_state_with_leader >= remaining:
                    d = _best_fit_for_slices(sorted_domains[idx:], remaining,
                                             remaining_leaders)
                results.append(d)
                remaining_leaders -= d.leader_state
                remaining -= d.slice_state_with_leader
                idx += 1
            if remaining_leaders > 0:
                return 0, [], self._not_fit(
                    state, state.leader_count - remaining_leaders,
                    slice_count, level_idx)
            # Leaderless (sort_cache) with no leader loop entered: the
            # with-leader order degenerates to the plain order (every
            # leader_state is 0 and *_with_leader ≡ the plain counts),
            # so the cached list IS the re-sort — skip it.
            if sort_cache is not None and idx == 0:
                rest = sorted_domains
            else:
                rest = self._sorted(sorted_domains[idx:], state.least_free)
            for i, d in enumerate(rest):
                if remaining <= 0:
                    break
                if d.slice_state <= 0:
                    # Zero-capacity domains contribute nothing and are
                    # filtered at assignment build; under LeastFreeCapacity
                    # ordering they sort FIRST, and the reference appends
                    # them all (thousands of zero-take domains threaded
                    # through the descent on a full cluster) — skip.
                    continue
                if not state.least_free and d.slice_state >= remaining:
                    d = _best_fit_for_slices(rest[i:], remaining, 0)
                results.append(d)
                remaining -= d.slice_state
            if remaining > 0:
                return 0, [], self._not_fit(
                    state, slice_count - remaining, slice_count, level_idx)
            return level_idx, results, ""
        return level_idx, [top], ""

    def _update_counts_to_minimum(self, sorted_domains: list, count: int,
                                  leader_count: int, slice_size: int,
                                  least_free: bool,
                                  use_slices: bool) -> Optional[list]:
        """updateCountsToMinimumGeneric :1575 + consumeWithLeadersGeneric
        :1518: distribute ``count`` pods (and the leader) over a minimal
        prefix of the sorted domains, clamping each domain's state to
        its assigned amount.

        Deliberate deviation in the multi-domain leader walk: the
        reference consumes the leader at the FIRST capable domain in
        worker-sort order, while the phase-1 bubbling that admitted this
        placement promised the MIN-DIFF placement (fillInCountsHelper
        :1930 takes min(state - stateWithLeader) over capable children)
        — so the reference's own walk can fall short of its selection
        and abort with errCodeAssumptionsViolated on feasible worlds.
        Here the leader (when no single domain completes the whole
        request) lands at the capable domain minimizing lost worker
        capacity, honoring the selection's arithmetic; the
        single-domain tight-fit completion path is unchanged."""
        results = []
        rem = [count // slice_size if use_slices else count, leader_count]

        def primary(d):
            return d.slice_state if use_slices else d.state

        def primary_wl(d):
            return d.slice_state_with_leader if use_slices \
                else d.state_with_leader

        def commit(d, take, leaders):
            d.leader_state = leaders
            if use_slices:
                d.slice_state = take
                d.state = take * slice_size
            else:
                d.state = take
            rem[0] -= take
            rem[1] -= leaders

        for i, dom in enumerate(sorted_domains):
            if rem[0] <= 0 and rem[1] <= 0:
                break
            if rem[1] > 0:
                # Single-domain completion (with the leader-filtered
                # best-fit swap): the whole remainder + leader in one
                # tight domain.
                d = dom
                if not least_free and primary_wl(dom) >= rem[0] \
                        and dom.leader_state >= rem[1]:
                    d = (_best_fit_for_slices if use_slices
                         else _best_fit_for_pods)(
                        sorted_domains[i:], rem[0], rem[1])
                if primary_wl(d) >= rem[0] and d.leader_state >= rem[1]:
                    commit(d, rem[0] + 0, rem[1])
                    results.append(d)
                    return results
                # No completion here: the leader goes to the min-diff
                # capable domain among the remainder; everything else
                # contributes full worker capacity.
                capable = [d2 for d2 in sorted_domains[i:]
                           if d2.leader_state >= rem[1]]
                if not capable:
                    return None
                min_dom = min(capable,
                              key=lambda d2: primary(d2) - primary_wl(d2))
                if dom is min_dom:
                    commit(dom, min(primary_wl(dom), rem[0]), rem[1])
                else:
                    commit(dom, min(primary(dom), rem[0]), 0)
                if dom.state > 0 or dom.leader_state > 0:
                    results.append(dom)
                continue
            # No leaders remaining: tail without leaders.
            if not least_free and primary(dom) >= rem[0]:
                dom = (_best_fit_for_slices if use_slices
                       else _best_fit_for_pods)(sorted_domains[i:],
                                                rem[0], 0)
            dom.leader_state = 0
            if primary(dom) >= rem[0]:
                commit(dom, rem[0] + 0, 0)
                results.append(dom)
                return results
            commit(dom, primary(dom), 0)
            if dom.state > 0:
                results.append(dom)
        if rem[0] > 0 or rem[1] > 0:
            return None  # accounting violated upstream
        return results

    def _not_fit(self, state: _AssignState, fit: int, want: int,
                 level_idx: int) -> str:
        """notFitReason closure of findLevelWithFitDomains :1394."""
        if state.multi_layer:
            return self._multi_layer_not_fit_message(
                level_idx, state.count, state.multi_layer, state.stats())
        return self._not_fit_message(fit, want, state.slice_size,
                                     state.stats())

    def _not_fit_message(self, fit: int, want: int, slice_size: int = 1,
                         stats: Optional[ExclusionStats] = None) -> str:
        """notFitMessage :1971 — quantities in slice units when slices
        are requested, with the exclusion-stats tail."""
        unit = "pod" if slice_size == 1 else "slice"
        if fit == 0:
            msg = (f'topology "{self.topology_name}" doesn\'t allow to fit '
                   f'any of {want} {unit}(s)')
        else:
            msg = (f'topology "{self.topology_name}" allows to fit only '
                   f'{fit} out of {want} {unit}(s)')
        if stats is not None and stats.has_exclusions():
            msg += (f". Total nodes: {stats.total_nodes}; "
                    f"excluded: {stats.format_reasons()}")
        return msg

    def _multi_layer_not_fit_message(self, level_idx: int, count: int,
                                     constraints: tuple,
                                     stats: Optional[ExclusionStats]
                                     ) -> str:
        """multiLayerNotFitMessage :2004: per-layer best-case fit counts
        from the best domain at the required level."""
        msg = f'topology "{self.topology_name}" doesn\'t allow to fit'
        best = None
        for d in self.domains_per_level[level_idx].values():
            if best is None or d.slice_state > best.slice_state or (
                    d.slice_state == best.slice_state and d.id < best.id):
                best = d
        if best is None:
            return msg
        for layer_key, layer_size in constraints:
            if layer_key not in self.level_keys:
                continue
            target_idx = self.level_keys.index(layer_key)
            needed = count // layer_size
            fit = _count_slices_in_subtree(best, level_idx, target_idx,
                                           layer_size)
            msg += f"; {fit}/{needed} slice(s) fit on level {layer_key}"
        if stats is not None and stats.has_exclusions():
            msg += (f". Total nodes: {stats.total_nodes}; "
                    f"excluded: {stats.format_reasons()}")
        return msg


def _count_slices_in_subtree(d, current_level: int, target_level: int,
                             slice_size: int) -> int:
    """countSlicesInSubtree :1993."""
    if current_level == target_level:
        return d.state // slice_size
    return sum(_count_slices_in_subtree(c, current_level + 1, target_level,
                                        slice_size) for c in d.children)


def _best_fit_by(sorted_domains: list, needed: int, cap, ok=None):
    """findBestFitDomainBy :1355: the FIRST domain with the lowest
    capacity >= needed; the first (most-capacity) domain if none fit.
    ``ok`` is an extra candidacy filter (see the deviation below)."""
    best = sorted_domains[0]
    best_cap = cap(best)
    for d in sorted_domains:
        c = cap(d)
        if c >= needed and c < best_cap and (ok is None or ok(d)):
            best = d
            best_cap = c
    return best


def _best_fit_for_slices(sorted_domains: list, slice_count: int,
                         leader_count: int):
    """findBestFitDomainForSlices :1342. Deliberate deviation: when a
    leader must co-place, only leader-capable domains are best-fit
    candidates — the reference filters on sliceStateWithLeader alone,
    and (since stateWithLeader == state for leaderless domains,
    fillLeafCounts :1897) can swap in a smaller domain that cannot host
    the leader and then fail a placement that fits (review repro:
    2 hosts, the leader-infeasible one barely covers the workers)."""
    if leader_count > 0:
        return _best_fit_by(
            sorted_domains, slice_count,
            lambda d: d.slice_state_with_leader,
            ok=lambda d: d.leader_state >= leader_count)
    return _best_fit_by(sorted_domains, slice_count,
                        lambda d: d.slice_state)


def _best_fit_for_pods(sorted_domains: list, count: int, leader_count: int):
    """findBestFitDomain :1326 — pod-count flavor of the above, same
    leader-capability deviation."""
    if leader_count > 0:
        return _best_fit_by(sorted_domains, count,
                            lambda d: d.state_with_leader,
                            ok=lambda d: d.leader_state >= leader_count)
    return _best_fit_by(sorted_domains, count, lambda d: d.state)


IS_GROUP_WORKLOAD_ANNOTATION = "kueue.x-k8s.io/is-group-workload"


def owned_by_single_pod(workload) -> bool:
    """workload.OwnedBySinglePod (pkg/workload/workload.go:1309): one
    core/v1 Pod owner and not a pod-group workload."""
    if workload is None:
        return False
    refs = tuple(getattr(workload, "owner_references", ()) or ())
    if len(refs) != 1:
        return False
    anns = getattr(workload, "annotations", {}) or {}
    if anns.get(IS_GROUP_WORKLOAD_ANNOTATION) == "true":
        return False
    api_version, kind = refs[0][0], refs[0][1]
    return kind == "Pod" and api_version == "v1"


def _find_leader_and_workers(trs: list[TASPodSetRequest]):
    """findLeaderAndWorkers :729 — in a 2-pod-set group the smaller-count
    pod set is the leader."""
    workers = trs[0]
    leader = None
    if len(trs) > 1:
        leader = trs[1]
        if leader.count > workers.count:
            leader, workers = workers, leader
    return leader, workers


def _existing_assignment(workload, pod_set_name: str):
    """findPSA :810."""
    status = getattr(workload, "status", None)
    admission = getattr(status, "admission", None)
    if admission is None:
        return None
    for psa in admission.pod_set_assignments:
        if psa.name == pod_set_name and psa.topology_assignment is not None:
            return psa.topology_assignment
    return None


def _delete_domains(assignment: TopologyAssignment,
                    unhealthy_nodes) -> tuple[TopologyAssignment, int]:
    """deleteDomain :884 — drop the domains whose leaf value is an
    unhealthy node; return (kept, affected_pod_count)."""
    failed = set(unhealthy_nodes)
    kept = []
    affected = 0
    for dom in assignment.domains:
        if dom.values[-1] in failed:
            affected += dom.count
        else:
            kept.append(dom)
    return TopologyAssignment(assignment.levels, tuple(kept)), affected


def _merge_assignments(repl: TopologyAssignment,
                       kept: TopologyAssignment) -> TopologyAssignment:
    """mergeTopologyAssignments — sum counts per domain, lex order."""
    counts: dict[tuple, int] = {}
    for dom in list(kept.domains) + list(repl.domains):
        counts[tuple(dom.values)] = counts.get(tuple(dom.values), 0) \
            + dom.count
    return TopologyAssignment(kept.levels, tuple(
        TopologyDomainAssignment(values, count)
        for values, count in sorted(counts.items())))


def _add_assumed(assumed: dict, assignment: TopologyAssignment,
                 tr: TASPodSetRequest) -> None:
    """addAssumedUsage :799."""
    if assignment is None:
        return
    for dom in assignment.domains:
        bucket = assumed.setdefault(tuple(dom.values), {})
        for res, per_pod in tr.single_pod_requests.items():
            bucket[res] = bucket.get(res, 0) + per_pod * dom.count
        bucket["pods"] = bucket.get("pods", 0) + dom.count
