"""Topology-aware scheduling (TAS): the gang-placement kernel.

Sequential correctness-oracle implementation of the reference's
pkg/cache/scheduler/tas_flavor_snapshot.go (KEP 2724) — the direct analog
of placing jobs onto TPU pod slices over ICI (within-domain) and DCN
(across domains).

Algorithm (tas_flavor_snapshot.go:933-945):
  Phase 1 (fillInCounts :1748): per leaf domain, compute how many pods fit
  in free capacity; bubble counts up the topology tree; at the slice level
  convert pod counts to whole-slice counts.
  Phase 2 (findTopologyAssignment :946): pick the assignment level — the
  requested level for `required`, climbing up for `preferred`, the whole
  forest for `unconstrained`; then descend level-by-level, each time
  sorting child domains (BestFit: sliceState desc, state asc, values asc —
  :1722 sortedDomains) and taking a minimal prefix, with a best-fit
  optimization for the final domain (:1390 findBestFitDomainForSlices).

Round-1 scope: required/preferred/unconstrained modes, pod-set slices
(single slice level), taint/selector node filtering, TAS usage accounting.
Leaders, balanced placement, multi-layer slices, and node replacement land
in later rounds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import (
    PodSet,
    PodSetTopologyRequest,
    Taint,
    Toleration,
    Topology,
    TopologyMode,
)

HOSTNAME_LABEL = "kubernetes.io/hostname"


@dataclass
class Node:
    """A capacity-bearing leaf (the reference uses corev1.Node; we are
    standalone). ``capacity`` is per-resource milli-units."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)
    capacity: dict[str, int] = field(default_factory=dict)
    taints: tuple[Taint, ...] = ()
    ready: bool = True


@dataclass
class TopologyDomainAssignment:
    values: tuple[str, ...]  # level values root->leaf
    count: int


@dataclass
class TopologyAssignment:
    levels: tuple[str, ...]
    domains: tuple[TopologyDomainAssignment, ...]


class _Domain:
    __slots__ = ("id", "values", "parent", "children", "state",
                 "slice_state", "free_capacity", "tas_usage", "node_name")

    def __init__(self, domain_id, values):
        self.id = domain_id
        self.values = values
        self.parent: Optional[_Domain] = None
        self.children: list[_Domain] = []
        self.state = 0  # pods that fit (phase-1), then assigned count
        self.slice_state = 0
        self.free_capacity: dict[str, int] = {}
        self.tas_usage: dict[str, int] = {}
        self.node_name: Optional[str] = None


@dataclass
class TASPodSetRequest:
    pod_set: PodSet
    single_pod_requests: dict[str, int]
    count: int


class TASFlavorSnapshot:
    """tas_flavor_snapshot.go:115."""

    def __init__(self, topology: Topology,
                 flavor_tolerations: tuple[Toleration, ...] = ()):
        self.topology_name = topology.name
        self.level_keys = [lv.node_label for lv in topology.levels]
        self.flavor_tolerations = flavor_tolerations
        self.is_lowest_level_node = (
            bool(self.level_keys) and self.level_keys[-1] == HOSTNAME_LABEL)
        self.domains: dict[tuple, _Domain] = {}
        self.leaves: dict[tuple, _Domain] = {}
        self.roots: dict[tuple, _Domain] = {}
        self.domains_per_level: list[dict[tuple, _Domain]] = [
            {} for _ in self.level_keys]

    # -- construction (tas_flavor.go / tas_nodes_cache.go) --

    def add_node(self, node: Node,
                 non_tas_usage: Optional[dict[str, int]] = None) -> None:
        if not node.ready:
            return
        values = tuple(node.labels.get(k, "") for k in self.level_keys)
        if "" in values:
            return  # node not labeled for this topology
        leaf = self._ensure_domain(values)
        leaf.node_name = node.name
        for res, cap in node.capacity.items():
            used = (non_tas_usage or {}).get(res, 0)
            leaf.free_capacity[res] = leaf.free_capacity.get(res, 0) \
                + max(0, cap - used)

    def _ensure_domain(self, values: tuple) -> _Domain:
        domain = self.domains.get(values)
        if domain is not None:
            return domain
        domain = _Domain(values, values)
        self.domains[values] = domain
        level = len(values) - 1
        self.domains_per_level[level][values] = domain
        if level == len(self.level_keys) - 1:
            self.leaves[values] = domain
        if level == 0:
            self.roots[values] = domain
        else:
            parent = self._ensure_domain(values[:-1])
            domain.parent = parent
            parent.children.append(domain)
        return domain

    # -- usage accounting (updateTASUsage) --

    def add_usage(self, values: tuple, requests: dict[str, int],
                  count: int) -> None:
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        for res, per_pod in requests.items():
            leaf.tas_usage[res] = leaf.tas_usage.get(res, 0) + per_pod * count
        leaf.tas_usage["pods"] = leaf.tas_usage.get("pods", 0)

    def remove_usage(self, values: tuple, requests: dict[str, int],
                     count: int) -> None:
        leaf = self.leaves.get(tuple(values))
        if leaf is None:
            return
        for res, per_pod in requests.items():
            leaf.tas_usage[res] = leaf.tas_usage.get(res, 0) - per_pod * count

    def fits(self, domain_requests) -> bool:
        """clusterqueue_snapshot.go:137 TAS part: every requested domain has
        the free capacity."""
        for values, requests, count in domain_requests:
            leaf = self.leaves.get(tuple(values))
            if leaf is None:
                return False
            for res, per_pod in requests.items():
                free = leaf.free_capacity.get(res, 0) - \
                    leaf.tas_usage.get(res, 0)
                if per_pod * count > free:
                    return False
        return True

    # -- the placement algorithm --

    def find_topology_assignment(
        self,
        request: TASPodSetRequest,
        simulate_empty: bool = False,
        assumed_usage: Optional[dict[tuple, dict[str, int]]] = None,
    ) -> tuple[Optional[TopologyAssignment], str]:
        """tas_flavor_snapshot.go:946 (findTopologyAssignment). Returns
        (assignment, failure_reason)."""
        tr = request.pod_set.topology_request or PodSetTopologyRequest()
        count = request.count
        required = tr.mode == TopologyMode.REQUIRED
        unconstrained = tr.mode == TopologyMode.UNCONSTRAINED

        slice_size = tr.slice_size or 1
        if count % slice_size != 0:
            return None, (
                f"pod count {count} not divisible by slice size {slice_size}")

        # Resolve requested level (unconstrained defaults to the root
        # level; required/preferred name a level).
        if tr.level is not None:
            if tr.level not in self.level_keys:
                return None, f"no requested topology level: {tr.level}"
            requested_level_idx = self.level_keys.index(tr.level)
        else:
            requested_level_idx = 0

        slice_level_key = tr.slice_level or self.level_keys[-1]
        if slice_level_key not in self.level_keys:
            return None, (
                f"no requested topology level for slices: {slice_level_key}")
        slice_level_idx = self.level_keys.index(slice_level_key)
        if requested_level_idx > slice_level_idx:
            return None, (
                f"podset slice topology {slice_level_key} is above the "
                f"podset topology {tr.level}")

        per_pod = dict(request.single_pod_requests)
        per_pod["pods"] = per_pod.get("pods", 0) + 1

        # Phase 1: per-domain fit counts.
        self._fill_in_counts(request.pod_set, per_pod, slice_size,
                             slice_level_idx, simulate_empty,
                             assumed_usage or {})

        slice_count = count // slice_size

        # Phase 2a: find the level with fitting domains.
        fit_level_idx, fit_domains, reason = self._find_level_with_fit(
            requested_level_idx, slice_count, required, unconstrained)
        if reason:
            return None, reason

        # Phase 2b: minimize the chosen domains, then descend.
        fit_domains = self._update_counts_to_minimum(
            fit_domains, count, slice_size, use_slices=True)
        level = fit_level_idx
        while level < min(len(self.level_keys) - 1, slice_level_idx):
            lower = self._sorted(
                [c for d in fit_domains for c in d.children], unconstrained)
            fit_domains = self._update_counts_to_minimum(
                lower, count, slice_size, use_slices=True)
            level += 1
        while level < len(self.level_keys) - 1:
            # Below the slice level, pods are distributed per parent domain
            # (tas_flavor_snapshot.go:1095-1120).
            new_fit = []
            for d in fit_domains:
                lower = self._sorted(d.children, unconstrained)
                new_fit.extend(self._update_counts_to_minimum(
                    lower, d.state, 1, use_slices=False))
            fit_domains = new_fit
            level += 1

        domains = sorted(
            (TopologyDomainAssignment(d.values, d.state)
             for d in fit_domains if d.state > 0),
            key=lambda a: a.values)
        return TopologyAssignment(tuple(self.level_keys),
                                  tuple(domains)), ""

    # -- internals --

    def _leaf_fits(self, pod_set: PodSet, per_pod: dict[str, int],
                   leaf: _Domain, simulate_empty: bool,
                   assumed_usage: dict) -> int:
        """How many pods fit in this leaf (fillLeafCounts)."""
        if self.is_lowest_level_node:
            # Taints/selector filtering against the node.
            tolerations = tuple(pod_set.tolerations) + \
                self.flavor_tolerations
            # Leaf nodes carry no taint info here (filtered at add_node
            # when implemented at cache layer); selector match on values.
            for key, val in pod_set.node_selector.items():
                if key in self.level_keys:
                    idx = self.level_keys.index(key)
                    if leaf.values[idx] != val:
                        return 0
        counts = []
        for res, need in per_pod.items():
            if need == 0:
                continue
            free = leaf.free_capacity.get(res, 0)
            if not simulate_empty:
                free -= leaf.tas_usage.get(res, 0)
                free -= assumed_usage.get(leaf.id, {}).get(res, 0)
            if res == "pods" and res not in leaf.free_capacity:
                continue  # node without explicit pod capacity: unlimited
            counts.append(max(0, free) // need)
        return min(counts) if counts else 0

    def _fill_in_counts(self, pod_set: PodSet, per_pod: dict[str, int],
                        slice_size: int, slice_level_idx: int,
                        simulate_empty: bool, assumed_usage: dict) -> None:
        """tas_flavor_snapshot.go:1748 (fillInCounts)."""
        for d in self.domains.values():
            d.state = 0
            d.slice_state = 0
        for leaf in self.leaves.values():
            leaf.state = self._leaf_fits(pod_set, per_pod, leaf,
                                         simulate_empty, assumed_usage)
        # Bubble up from deepest level.
        for level in range(len(self.level_keys) - 1, -1, -1):
            for d in self.domains_per_level[level].values():
                if d.children:
                    d.state = sum(c.state for c in d.children)
                if level == slice_level_idx:
                    d.slice_state = d.state // slice_size
                elif level < slice_level_idx:
                    d.slice_state = sum(c.slice_state for c in d.children)

    def _sorted(self, domains: list, unconstrained: bool) -> list:
        """tas_flavor_snapshot.go:1722 (sortedDomains) — BestFit order."""
        return sorted(domains,
                      key=lambda d: (-d.slice_state, d.state, d.values))

    def _find_level_with_fit(self, level_idx: int, slice_count: int,
                             required: bool, unconstrained: bool):
        """tas_flavor_snapshot.go findLevelWithFitDomains."""
        domains = list(self.domains_per_level[level_idx].values()) \
            if self.level_keys else []
        if not domains:
            return 0, [], "no topology domains at level"
        sorted_domains = self._sorted(domains, unconstrained)
        top = sorted_domains[0]
        if top.slice_state >= slice_count:
            # Best-fit: the smallest single domain that fits.
            best = self._best_fit_domain(sorted_domains, slice_count)
            return level_idx, [best], ""
        if required:
            return 0, [], self._not_fit_message(top.slice_state, slice_count)
        if level_idx > 0 and not unconstrained:
            return self._find_level_with_fit(level_idx - 1, slice_count,
                                             required, unconstrained)
        # Multi-domain greedy at the top (or unconstrained anywhere).
        results = []
        remaining = slice_count
        for i, d in enumerate(sorted_domains):
            if remaining <= 0:
                break
            if d.slice_state >= remaining:
                results.append(self._best_fit_domain(sorted_domains[i:],
                                                     remaining))
                remaining = 0
                break
            results.append(d)
            remaining -= d.slice_state
        if remaining > 0:
            return 0, [], self._not_fit_message(slice_count - remaining,
                                                slice_count)
        return level_idx, results, ""

    @staticmethod
    def _best_fit_domain(sorted_domains: list, slice_count: int):
        """findBestFitDomainForSlices: among fitting domains, the one with
        the least leftover capacity (first in sorted order on ties)."""
        best = None
        for d in sorted_domains:
            if d.slice_state >= slice_count and (
                    best is None or d.slice_state < best.slice_state):
                best = d
        return best if best is not None else sorted_domains[0]

    def _update_counts_to_minimum(self, sorted_domains: list, count: int,
                                  slice_size: int,
                                  use_slices: bool) -> list:
        """updateCountsToMinimumGeneric: distribute ``count`` pods over a
        minimal prefix of the sorted domains. ``use_slices`` selects the
        capacity field (sliceState for whole-slice placement, state for
        per-pod placement below the slice level)."""
        def cap(d):
            return d.slice_state if use_slices else d.state

        results = []
        remaining = count // slice_size if use_slices else count
        unit = slice_size if use_slices else 1
        for i, d in enumerate(sorted_domains):
            if remaining <= 0:
                break
            if cap(d) >= remaining:
                best = d
                for cand in sorted_domains[i:]:
                    if remaining <= cap(cand) <= cap(best):
                        best = cand
                best.state = remaining * unit
                best.slice_state = remaining if use_slices else 0
                results.append(best)
                remaining = 0
                break
            d.state = cap(d) * unit
            remaining -= cap(d)
            results.append(d)
        return results

    def _not_fit_message(self, fit: int, want: int) -> str:
        """notFitMessage."""
        if want == 1:
            return "topology %r doesn't allow to fit any pod" % \
                self.topology_name
        return (f"topology {self.topology_name!r} allows to fit only "
                f"{fit} out of {want} slice(s)/pod(s)")
