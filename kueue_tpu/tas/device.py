"""Device TAS: serving-path adapter for the ops/tas.tas_place kernel.

TASFlavorSnapshot.find_topology_assignments dispatches here when the
"DeviceTAS" gate is on (the default); the sequential implementation in
tas/snapshot.py stays as the fallback and the differential-test oracle
(tests/test_tas_device.py). The adapter:

  * encodes the topology forest once per structure change (slots sorted
    by values per level, parent pointers, value ranks), cached on the
    snapshot keyed by a structure version counter;
  * gathers the per-call leaf capacity state (free / TAS usage / assumed
    usage), the pod-set's resource vectors, and the selector /
    replacement-domain leaf mask;
  * launches the placement program and renders the reference's failure
    strings from the kernel's status codes
    (tas_flavor_snapshot.go:946 findTopologyAssignment semantics).

Unsupported corners fall back to the sequential path by returning
NotImplemented: balanced placement (tas_balanced_placement.go is
host-side; it only engages for preferred mode under the
TASBalancedPlacement gate) and level-less topologies.
"""

from __future__ import annotations

import numpy as np

from kueue_tpu.api.types import PodSetTopologyRequest, TopologyMode
from kueue_tpu.config import features

_VRANK_PAD = 1 << 40

# Crossover for offload: per-placement device dispatch costs ~1-10ms
# whatever the problem size, while the host descent scales with the
# domain count. Measured per-placement (bench.py tas/tas_large probes,
# both the CPU backend and the round-3 TPU capture), the numpy host
# phase-1 + descent beats a per-call device launch at every forest size
# tried (640: host 1.4ms vs device 2.9ms on TPU; 5120: host ~2ms vs
# device ~8ms on CPU) — the launch+readback overhead never amortizes
# for a SINGLE placement. The device TAS win is the BATCHED paths (the
# feasibility kernel in tas/feasibility.py and the per-cycle placement
# batch in tas/batched.py); per-placement offload turns on only when
# the persisted crossover measurement (tas/calibration.py, written by
# bench._tas_crossover_measure) says the launch beats the host descent
# on this backend at this forest shape. KUEUE_TPU_DEVICE_TAS_MIN still
# overrides both ways (0 = always, used by the differential suites;
# a huge value = never).


def worth_offloading(snap) -> bool:
    """True when per-placement device offload is enabled for this
    forest. KUEUE_TPU_DEVICE_TAS_MIN, when set, is an explicit leaf
    threshold (0 = always offload); otherwise the decision comes from
    the persisted crossover calibration, and with no calibration the
    host path wins (the pre-measurement default). Memoized per
    (structure version, env override) — the batched planner asks once
    per placement group per cycle."""
    import os

    from kueue_tpu.tas import calibration

    if not snap.level_keys:
        return False
    override = os.environ.get("KUEUE_TPU_DEVICE_TAS_MIN")
    key = (snap._version, override, calibration.generation)
    cached = getattr(snap, "_worth_memo", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    nl = len(snap.level_keys)
    if override is not None:
        try:
            threshold = int(override)
        except ValueError:
            snap._worth_memo = (key, False)
            return False
        out = len(snap.domains_per_level[nl - 1]) >= threshold
        snap._worth_memo = (key, out)
        return out
    out = calibration.device_placement_wins(snap)
    snap._worth_memo = (key, out)
    return out


def _structure(snap):
    """Padded per-level slot arrays for the snapshot's forest, cached by
    the snapshot's structure version."""
    cached = getattr(snap, "_device_struct", None)
    version = getattr(snap, "_version", 0)
    if cached is not None and cached["version"] == version:
        return cached
    # Forked snapshots (TASFlavorSnapshot.fork) share the prototype's
    # structure: remap the domain-object lists onto the fork's clones
    # and reuse every numpy array (same shapes, same slot order).
    donor = getattr(snap, "_struct_donor", None)
    donor_struct = None
    if donor is not None:
        # Build (or reuse) the struct ON THE PROTOTYPE so every future
        # fork shares it — deriving it on the fork would discard it at
        # cycle end and redo the encode + device transfers every cycle.
        donor_struct = _structure(donor)
    if donor_struct is not None and donor_struct["version"] == version:
        level_domains = [[snap.domains[d.values] for d in doms]
                         for doms in donor_struct["level_domains"]]
        cached = dict(donor_struct,
                      level_domains=level_domains,
                      leaves=(level_domains[-1] if level_domains
                              else []))
        # slot_of_leaf_values / slot_of_leaf_id stay valid: forks keep
        # ids, values and slot order.
        snap._device_struct = cached
        return cached
    nl = len(snap.level_keys)
    level_domains = [
        sorted(snap.domains_per_level[lvl].values(),
               key=lambda d: d.values)
        for lvl in range(nl)]
    m = max(1, max((len(doms) for doms in level_domains), default=1))
    mp = max(8, 1 << (m - 1).bit_length())
    valid = np.zeros((nl, mp), bool)
    vrank = np.full((nl, mp), _VRANK_PAD, np.int64)
    parent = np.full((nl, mp), -1, np.int64)
    slot_of = [{d.id: i for i, d in enumerate(doms)}
               for doms in level_domains]
    for lvl, doms in enumerate(level_domains):
        for i, d in enumerate(doms):
            valid[lvl, i] = True
            vrank[lvl, i] = i
            if lvl > 0:
                parent[lvl, i] = slot_of[lvl - 1][d.parent.id]
    leaves = level_domains[nl - 1] if nl else []
    res_axis = sorted({res for leaf in leaves
                       for res in leaf.free_capacity} | {"pods"})
    has_pods_cap = np.zeros(mp, bool)
    for i, leaf in enumerate(leaves):
        has_pods_cap[i] = "pods" in leaf.free_capacity
    cached = dict(version=version, nl=nl, m=mp,
                  level_domains=level_domains, leaves=leaves,
                  slot_of_leaf_values={d.values: i
                                       for i, d in enumerate(leaves)},
                  slot_of_leaf_id={d.id: i
                                   for i, d in enumerate(leaves)},
                  res_axis=res_axis, valid=valid, vrank=vrank,
                  parent=parent, has_pods_cap=has_pods_cap,
                  # Present from birth so fork copies SHARE them — a
                  # setdefault on a fork's dict would otherwise create
                  # per-fork caches and rebuild matrices every cycle.
                  free_cache={}, jnp_cache={})
    snap._device_struct = cached
    return cached


def _req_vector(requests: dict, cols: list[str]) -> np.ndarray:
    out = np.zeros(len(cols), np.int64)
    for i, res in enumerate(cols):
        out[i] = requests.get(res, 0)
    return out


def _cols_for(struct, per_pod: dict, leader_per_pod: dict) -> list[str]:
    """The column axis for a request pair, padded exactly like
    try_find so the free/usage matrix caches are shared between the
    device launch and the numpy phase-1."""
    axis = struct["res_axis"]
    extras = sorted((set(per_pod) | set(leader_per_pod)) - set(axis))
    cols = axis + extras
    sp = max(4, -(-len(cols) // 4) * 4)
    return cols + [f"__pad{i}" for i in range(sp - len(cols))]


def _free_matrix(struct, cols: list[str]) -> np.ndarray:
    cols_key = tuple(cols)
    free_cache = struct.setdefault("free_cache", {})
    free = free_cache.get(cols_key)
    if free is None:
        col_of = {res: i for i, res in enumerate(cols)}
        free = np.zeros((struct["m"], len(cols)), np.int64)
        for i, leaf in enumerate(struct["leaves"]):
            for res, cap in leaf.free_capacity.items():
                free[i, col_of[res]] = cap
        free_cache[cols_key] = free
    return free


_USAGE_LRU_CAP = 4


def _usage_matrix(snap, struct, cols: list[str]) -> np.ndarray:
    """Dense leaf usage for a column set, behind a small keyed LRU:
    pod sets with different column axes alternating within one cycle
    (e.g. a GPU head and a CPU head against the same forest) would
    thrash a single-entry cache, re-densifying the forest per call.
    Entries are keyed (usage_version, cols) — the version-restoration
    purges in snapshot.end_cycle / simulate_workload_removal drop
    whatever a revert made stale."""
    cols_key = tuple(cols)
    uver = getattr(snap, "_usage_version", 0)
    ucache = getattr(snap, "_usage_matrix_cache", None)
    if ucache is None:
        ucache = snap._usage_matrix_cache = {}
    hit = ucache.get((uver, cols_key))
    if hit is not None:
        snap._usage_matrix_hits = getattr(
            snap, "_usage_matrix_hits", 0) + 1
        # Recency bump: re-insert at the back so eviction drops the
        # least recently USED entry, not merely the oldest.
        ucache[(uver, cols_key)] = ucache.pop((uver, cols_key))
        return hit
    snap._usage_matrix_misses = getattr(
        snap, "_usage_matrix_misses", 0) + 1
    col_of = {res: i for i, res in enumerate(cols)}
    usage = np.zeros((struct["m"], len(cols)), np.int64)
    used_leaves = getattr(snap, "_used_leaves", None)
    if used_leaves is None:
        leaf_iter = enumerate(struct["leaves"])
    else:
        # Only leaves that ever carried usage — O(used), not O(forest).
        slot_of = struct["slot_of_leaf_values"]
        leaves = struct["leaves"]
        leaf_iter = ((slot_of[v], leaves[slot_of[v]])
                     for v in used_leaves if v in slot_of)
    for i, leaf in leaf_iter:
        for res, used in leaf.tas_usage.items():
            if res in col_of:
                usage[i, col_of[res]] = used
    while len(ucache) >= _USAGE_LRU_CAP:
        ucache.pop(next(iter(ucache)))
    ucache[(uver, cols_key)] = usage
    return usage


def fill_in_counts_np(snap, pod_set, per_pod: dict, slice_size: int,
                      slice_level_idx: int, simulate_empty: bool,
                      assumed_usage: dict,
                      required_replacement_domain: tuple,
                      excluded: dict = None,
                      slice_size_at_level: dict = None) -> bool:
    """Vectorized phase-1 (fillInCounts, tas_flavor_snapshot.go:1750)
    for the NO-LEADER case: compute per-domain fit counts as numpy
    reductions over the cached leaf matrices and write them back into
    the domain objects the host phase-2 descent reads. Runs on the host
    CPU — at small forest sizes dispatching a device program per
    placement costs more than the whole computation, but the dense
    encoding still beats the per-leaf dict walk by ~10x. Returns False
    when the world is unsupported (leaders are bubbled with min-diff
    tracking on the Python path; multi-layer inner slice rounding
    stays on the host bubble)."""
    if not snap.level_keys:
        return False
    if slice_size_at_level:
        return False
    struct = _structure(snap)
    nl = struct["nl"]
    mp = struct["m"]
    leaves = struct["leaves"]
    if not leaves:
        return False
    cols = _cols_for(struct, per_pod, {})
    col_of = {res: i for i, res in enumerate(cols)}
    free = _free_matrix(struct, cols)
    if simulate_empty:
        remaining = free.astype(np.int64, copy=True)
    else:
        remaining = free - _usage_matrix(snap, struct, cols)
        if assumed_usage:
            slot_of_leaf = {leaf.id: i for i, leaf in enumerate(leaves)}
            for leaf_id, used in assumed_usage.items():
                i = slot_of_leaf.get(leaf_id)
                if i is None:
                    continue
                for res, v in used.items():
                    ci = col_of.get(res)
                    if ci is not None:
                        remaining[i, ci] -= v
    remaining = np.maximum(remaining, 0)

    # Per-leaf pod counts: min over requested resources of
    # remaining // need; "pods" is unconstrained for leaves without
    # explicit pod capacity (fillLeafCounts :1864).
    BIG = np.int64(1) << 60
    counts = np.full(mp, BIG, np.int64)
    applied = np.zeros(mp, bool)
    pods_cap = struct["has_pods_cap"]
    for res, need in per_pod.items():
        if need <= 0:
            continue
        ci = col_of[res]
        c = remaining[:, ci] // need
        if res == "pods":
            c = np.where(pods_cap, c, BIG)
            applied |= pods_cap
        else:
            applied[:] = True
        counts = np.minimum(counts, c)
    counts = np.where(applied, counts, 0)
    counts[~struct["valid"][nl - 1]] = 0

    # matchNode exclusions (taints / selectors / affinity, precomputed
    # by snapshot._match_excluded) + replacement-domain filtering.
    rrd = tuple(required_replacement_domain or ())
    if rrd or excluded:
        excluded = excluded or {}
        for i, leaf in enumerate(leaves):
            if rrd and leaf.values[:len(rrd)] != rrd:
                counts[i] = 0
            elif leaf.values in excluded:
                counts[i] = 0

    # Bottom-up aggregation (fillInCountsHelper :1906, no-leader form:
    # state_with_leader == state, leader_state == 0 throughout).
    state = np.zeros((nl, mp), np.int64)
    state[nl - 1] = counts
    for lvl in range(nl - 2, -1, -1):
        child_valid = struct["valid"][lvl + 1]
        np.add.at(state[lvl], struct["parent"][lvl + 1][child_valid],
                  state[lvl + 1][child_valid])
    slices = np.zeros((nl, mp), np.int64)
    for lvl in range(nl - 1, -1, -1):
        if lvl == slice_level_idx:
            slices[lvl] = state[lvl] // slice_size
        elif lvl < slice_level_idx and lvl < nl - 1:
            child_valid = struct["valid"][lvl + 1]
            np.add.at(slices[lvl],
                      struct["parent"][lvl + 1][child_valid],
                      slices[lvl + 1][child_valid])

    for lvl, doms in enumerate(struct["level_domains"]):
        s = state[lvl].tolist()  # bulk int conversion beats per-item
        sl = slices[lvl].tolist()
        for i, d in enumerate(doms):
            si = s[i]
            sli = sl[i]
            d.state = si
            d.slice_state = sli
            d.state_with_leader = si
            d.slice_state_with_leader = sli
            d.leader_state = 0
    return True


def try_find(snap, workers, leader=None, simulate_empty=False,
             assumed_usage=None, required_replacement_domain=()):
    """Device counterpart of find_topology_assignments. Returns
    NotImplemented when the world needs the sequential path."""
    import jax

    # ops/tas packs multi-field sort keys into int64 lanes; without x64
    # they would silently truncate to int32 and mis-sort. Flip the
    # process-global flag, same deliberate choice as
    # engine.attach_oracle: the scheduler owns its process; embedders
    # sharing it with float32 JAX code must enable x64 at startup.
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    if not snap.level_keys:
        return NotImplemented
    if getattr(workers, "previous_assignment", None) is not None:
        # Elastic delta placement is decomposed on the host
        # (_handle_elastic_workload) before device dispatch.
        return NotImplemented
    if leader is not None:
        # Leader co-placement: host walk only. The round-5 parity rework
        # aligned the host descent with the reference's exact consume
        # semantics (leaderless domains contribute stateWithLeader ==
        # state, leader lands at the first capable domain in plain
        # sortedDomains order — tas_flavor_snapshot.go:1897,1518); the
        # kernel's leader-first formulation predates that and leader
        # groups never reach the serving device path anyway (the
        # feasibility batch skips groups, per-placement offload is
        # default-off).
        return NotImplemented
    count = workers.count
    state, reason = snap.resolve_request(workers, leader is not None)
    if state is None:
        return None, reason
    required = state.required
    unconstrained = state.unconstrained
    if (features.enabled("TASBalancedPlacement") and not required
            and not unconstrained):
        return NotImplemented
    if state.slice_size_at_level:
        # Multi-layer inner slice rounding: host path only.
        return NotImplemented
    if state.least_free != state.unconstrained:
        # TASProfileMixed off: the kernel's unconstrained branches encode
        # the LeastFreeCapacity profile; BestFit-unconstrained stays host.
        return NotImplemented
    slice_size = state.slice_size
    req_idx = state.requested_level_idx
    slice_idx = state.slice_level_idx

    struct = _structure(snap)
    if not struct["level_domains"][req_idx]:
        return None, ("no topology domains at level: "
                      f"{snap.level_keys[req_idx]}")

    per_pod = dict(workers.single_pod_requests)
    per_pod["pods"] = per_pod.get("pods", 0) + 1
    leader_per_pod = {}
    has_leader = leader is not None
    if has_leader:
        leader_per_pod = dict(leader.single_pod_requests)
        leader_per_pod["pods"] = leader_per_pod.get("pods", 0) + 1

    # Column axis + cached free/usage matrices shared with the numpy
    # phase-1 (fill_in_counts_np) — same keys, one construction path.
    cols = _cols_for(struct, per_pod, leader_per_pod)
    sp = len(cols)
    cols_key = tuple(cols)

    mp = struct["m"]
    leaves = struct["leaves"]
    col_of = {res: i for i, res in enumerate(cols)}

    free = _free_matrix(struct, cols)

    assumed = np.zeros((mp, sp), np.int64)
    if simulate_empty:
        usage = np.zeros((mp, sp), np.int64)
    else:
        usage = _usage_matrix(snap, struct, cols)
        if assumed_usage:
            slot_of_id = struct["slot_of_leaf_id"]
            for leaf_id, res_used in assumed_usage.items():
                i = slot_of_id.get(leaf_id)
                if i is None:
                    continue
                for res, used in res_used.items():
                    if res in col_of:
                        assumed[i, col_of[res]] = used

    # matchNode exclusions (taints / full-label selectors / affinity —
    # snapshot._match_excluded) + replacement-domain leaf filtering.
    leaf_mask = struct["valid"][struct["nl"] - 1].copy()
    rrd = tuple(required_replacement_domain or ())
    excluded = snap._match_excluded(workers.pod_set)
    needs_selector = bool(excluded)
    if rrd or excluded:
        for i, leaf in enumerate(leaves):
            if rrd and leaf.values[:len(rrd)] != rrd:
                leaf_mask[i] = False
            elif leaf.values in excluded:
                leaf_mask[i] = False

    import jax.numpy as jnp

    from kueue_tpu.ops import tas as tops
    from kueue_tpu.tas.snapshot import (
        TopologyAssignment,
        TopologyDomainAssignment,
    )

    # Device-resident constants: transfer the forest arrays (and the
    # per-version free matrix) once, not per placement call.
    jnp_cache = struct.setdefault("jnp_cache", {})
    if "consts" not in jnp_cache:
        jnp_cache["consts"] = (
            jnp.asarray(struct["has_pods_cap"]),
            jnp.asarray(struct["valid"]), jnp.asarray(struct["vrank"]),
            jnp.asarray(struct["parent"]))
    j_pods_cap, j_valid, j_vrank, j_parent = jnp_cache["consts"]
    j_free = jnp_cache.get(("free", cols_key))
    if j_free is None:
        j_free = jnp.asarray(free)
        jnp_cache[("free", cols_key)] = j_free
    # Usage / assumed / mask are device-resident between calls: the
    # usage matrix only changes when TAS usage mutates (keyed on the
    # same version as _usage_matrix, held on the snap so forks don't
    # alias), the all-zero assumed matrix is shared per shape, and the
    # default leaf mask is the forest's own validity row.
    def _cached_zeros(shape):
        z = jnp_cache.get(("zeros", shape))
        if z is None:
            z = jnp_cache[("zeros", shape)] = jnp.zeros(shape, jnp.int64)
        return z

    if simulate_empty or not np.any(usage):
        j_usage = _cached_zeros(usage.shape)
    else:
        ukey = (getattr(snap, "_usage_version", 0), cols_key)
        cached_u = getattr(snap, "_j_usage_cache", None)
        if cached_u is not None and cached_u[0] == ukey:
            j_usage = cached_u[1]
        else:
            j_usage = jnp.asarray(usage)
            snap._j_usage_cache = (ukey, j_usage)
    if np.any(assumed):
        j_assumed = jnp.asarray(assumed)
    else:
        j_assumed = _cached_zeros(assumed.shape)
    if rrd or needs_selector:
        j_mask = jnp.asarray(leaf_mask)
    else:
        j_mask = jnp_cache.get("default_mask")
        if j_mask is None:
            j_mask = jnp_cache["default_mask"] = jnp.asarray(leaf_mask)

    status, fit_arg, cnt, lead = tops.tas_place(
        j_free, j_usage, j_assumed,
        jnp.asarray(_req_vector(per_pod, cols)),
        jnp.asarray(_req_vector(leader_per_pod, cols)),
        j_mask, j_pods_cap,
        j_valid, j_vrank,
        j_parent, np.int64(count),
        np.int64(slice_size), num_levels=struct["nl"], max_domains=mp,
        pods_col=col_of["pods"], req_level=req_idx,
        slice_level=slice_idx, required=required,
        unconstrained=unconstrained, has_leader=has_leader)
    # One blocking transfer for all outputs, not one sync per field.
    status, fit_arg, cnt, lead = jax.device_get(
        (status, fit_arg, cnt, lead))
    status = int(status)
    if status == tops.ERR_NOT_FIT:
        # Identical failure string to the host walk: the exclusion-stats
        # tail is a pure function of (request, forest), built lazily.
        stats = snap._exclusion_stats(
            workers.pod_set, per_pod, simulate_empty, assumed_usage or {},
            required_replacement_domain)
        return None, snap._not_fit_message(int(fit_arg),
                                           count // slice_size,
                                           slice_size, stats)
    if status == tops.ERR_UNDERFLOW:
        return None, "internal: assignment accounting underflow"

    assignments = {}
    if has_leader:
        leader_domains = sorted(
            (TopologyDomainAssignment(leaves[i].values, int(lead[i]))
             for i in np.nonzero(lead > 0)[0]),
            key=lambda a: a.values)
        assignments[leader.pod_set.name] = TopologyAssignment(
            tuple(snap.level_keys), tuple(leader_domains))
    domains = sorted(
        (TopologyDomainAssignment(leaves[i].values, int(cnt[i]))
         for i in np.nonzero(cnt > 0)[0]),
        key=lambda a: a.values)
    assignments[workers.pod_set.name] = TopologyAssignment(
        tuple(snap.level_keys), tuple(domains))
    return assignments, ""
