"""Measured host/device TAS crossover, persisted across runs.

The old behavior hard-coded per-placement device dispatch OFF
(DEVICE_TAS_MIN_DOMAINS = 1 << 30): correct on the CPU backend, where a
single tas_place launch costs several ms regardless of problem size,
but wrong anywhere a real accelerator amortizes the dispatch. Instead
of a constant, the bench's crossover probe (bench._tas_crossover_measure
— one host descent vs one device launch on the live forest) persists
its measurement here, keyed by (backend, forest shape), and
tas/device.py consults the record at attach time:

  * no record, no env override -> host path (the safe default;
    identical to the old constant's effect);
  * record says the device launch beats the host descent at this
    forest shape -> per-placement offload and the batched placement
    path (tas/batched.py) switch on;
  * KUEUE_TPU_DEVICE_TAS_MIN always wins when set (0 = always offload,
    large = never), so tests and operators can force either path.

The record lives in ``$KUEUE_TPU_TAS_CALIBRATION`` if set, else
``$XDG_CACHE_HOME/kueue_tpu/tas_crossover.json``, else
``~/.cache/kueue_tpu/tas_crossover.json``. Forest shapes are bucketed
to the next power of two of the leaf count so re-runs on slightly
different worlds reuse the measurement.
"""

from __future__ import annotations

import json
import os
from typing import Optional

_cache: Optional[dict] = None
_cache_path: Optional[str] = None
# Bumped whenever the in-process record table may have changed;
# lets callers (tas/device.worth_offloading) memoize per generation.
generation = 0


def record_path() -> str:
    override = os.environ.get("KUEUE_TPU_TAS_CALIBRATION")
    if override:
        return override
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "kueue_tpu", "tas_crossover.json")


def leaf_bucket(leaves: int) -> int:
    """Next power of two — worlds of similar scale share a record."""
    if leaves <= 1:
        return 1
    return 1 << (leaves - 1).bit_length()


def _key(backend: str, num_levels: int, leaves: int) -> str:
    return f"{backend}:{num_levels}:{leaf_bucket(leaves)}"


def load(path: Optional[str] = None) -> dict:
    """The persisted record table ({key: {host_place_ms,
    device_place_ms, ...}}), cached per process per path."""
    global _cache, _cache_path
    path = path or record_path()
    if _cache is not None and _cache_path == path:
        return _cache
    table: dict = {}
    try:
        with open(path, encoding="utf-8") as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            table = loaded
    except (OSError, ValueError):
        pass
    _cache = table
    _cache_path = path
    return table


def save(backend: str, num_levels: int, leaves: int,
         host_place_ms: float, device_place_ms: float,
         extra: Optional[dict] = None) -> Optional[str]:
    """Merge one measurement into the record and rewrite it atomically.
    Returns the path written, or None when the location is unwritable
    (the calibration is an optimization, never a requirement)."""
    global _cache, _cache_path, generation
    generation += 1
    path = record_path()
    table = dict(load(path))
    entry = {"host_place_ms": round(float(host_place_ms), 4),
             "device_place_ms": round(float(device_place_ms), 4),
             "leaves": int(leaves), "num_levels": int(num_levels),
             "backend": backend}
    if extra:
        entry.update(extra)
    table[_key(backend, num_levels, leaves)] = entry
    tmp = path + ".tmp"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    _cache = table
    _cache_path = path
    return path


def lookup(backend: str, num_levels: int, leaves: int) -> Optional[dict]:
    return load().get(_key(backend, num_levels, leaves))


def device_placement_wins(snap) -> bool:
    """True when the persisted measurement says a device tas_place
    launch beats the host descent for this forest's shape on the
    current backend. False with no record — callers keep the host
    path, matching the old DEVICE_TAS_MIN_DOMAINS default."""
    if not snap.level_keys:
        return False
    nl = len(snap.level_keys)
    leaves = len(snap.domains_per_level[nl - 1])
    try:
        import jax
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - jax always present in-tree
        return False
    entry = lookup(backend, nl, leaves)
    if entry is None:
        return False
    return entry["device_place_ms"] < entry["host_place_ms"]


def invalidate_cache() -> None:
    """Test hook: drop the per-process record cache."""
    global _cache, _cache_path, generation
    generation += 1
    _cache = None
    _cache_path = None
