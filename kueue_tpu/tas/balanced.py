"""TAS balanced placement (feature gate ``TASBalancedPlacement``).

Reference: pkg/cache/scheduler/tas_balanced_placement.go (381 LoC) wired
in at tas_flavor_snapshot.go:1064-1080. Instead of best-fit packing,
spread the slices *evenly*: find the maximum threshold T such that every
selected domain can take at least T slices, select the optimal domain set
via dynamic programming (minimum domain count, then minimum leftover
capacity), and hand each selected domain T slices plus a fair share of
the remainder. Leaders are reserved on the first selected domain.

Used on preferred-mode requests only (never required/unconstrained); any
failure falls back to best-fit.
"""

from __future__ import annotations

import math
from typing import Optional

from kueue_tpu.tas.snapshot import _Domain, clone_domains

_NEG_INF = -(1 << 60)


def evaluate_greedy(snapshot, domains: list[_Domain], slice_count: int,
                    leader_count: int):
    """evaluateGreedyAssignment: simulate best-fit placement; return
    (fits, domains_used, last_leader_domain, last_worker_domain)."""
    selected = 0
    last_with_leader = None
    last = None
    rem_slices = slice_count
    rem_leaders = leader_count
    idx = 0
    if leader_count > 0:
        with_leader = snapshot._sorted_with_leader(domains, False)
        while rem_leaders > 0 and idx < len(with_leader) \
                and with_leader[idx].leader_state > 0:
            selected += 1
            last_with_leader = with_leader[idx]
            rem_leaders -= with_leader[idx].leader_state
            rem_slices -= with_leader[idx].slice_state_with_leader
            idx += 1
        rest = snapshot._sorted(with_leader[idx:], False)
    else:
        rest = snapshot._sorted(domains, False)
    if rem_leaders > 0:
        return False, 0, None, None
    for d in rest:
        if rem_slices <= 0:
            break
        if d.slice_state <= 0:
            break
        selected += 1
        last = d
        rem_slices -= d.slice_state
    if rem_slices > 0:
        return False, 0, None, None
    return True, selected, last_with_leader, last


def threshold_value(slice_count: int, selected: int, last_with_leader,
                    last) -> int:
    """balanceThresholdValue: the max possible min-slices-per-domain."""
    threshold = slice_count // selected
    if last_with_leader is not None:
        threshold = min(threshold, last_with_leader.slice_state_with_leader)
    if last is not None:
        threshold = min(threshold, last.slice_state)
    return threshold


def _entropy(domains: list[_Domain]) -> float:
    """calculateDomainsEntropy over children states."""
    total = sum(d.state for d in domains)
    if total == 0:
        return 0.0
    entropy = 0.0
    for d in domains:
        if d.state > 0:
            p = d.state / total
            entropy += -p * math.log2(p)
    return entropy


def _entropy_key(d: _Domain):
    """compareDomainCapacityAndEntropy (descending leader/slice/entropy)."""
    return (-d.leader_state, -d.slice_state_with_leader,
            -_entropy(d.children), d.values)


def select_optimal_domain_set(snapshot, domains: list[_Domain],
                              slice_count: int, leader_count: int,
                              slice_size: int,
                              prioritize_by_entropy: bool
                              ) -> Optional[list[_Domain]]:
    """selectOptimalDomainSetToFit: DP over (#domains, leaders left, pods
    left) to find a fitting subset using the greedy-minimal number of
    domains with the least leftover capacity."""
    fits, optimal, _, _ = evaluate_greedy(snapshot, domains, slice_count,
                                          leader_count)
    if not fits:
        return None

    ordered = sorted(domains,
                     key=_entropy_key if prioritize_by_entropy
                     else lambda d: d.values)

    # dp[i][leaders_left][pods_left] -> chosen domain list (first wins)
    dp: list[dict[int, dict[int, list[_Domain]]]] = [
        {} for _ in range(optimal + 1)]
    dp[0][leader_count] = {slice_count * slice_size: []}

    for d in ordered:
        for i in range(optimal, 0, -1):
            for before_leader in sorted(dp[i - 1]):
                for before_state in sorted(dp[i - 1][before_leader]):
                    if before_leader <= 0 and before_state <= 0:
                        continue
                    placement = dp[i - 1][before_leader][before_state] + [d]
                    if before_leader > 0 and d.leader_state > 0:
                        after_leader = before_leader - d.leader_state
                        after_state = before_state - d.state_with_leader
                        bucket = dp[i].setdefault(after_leader, {})
                        bucket.setdefault(after_state, placement)
                    if d.slice_state > 0:
                        after_state = before_state - d.state
                        bucket = dp[i].setdefault(before_leader, {})
                        bucket.setdefault(after_state, placement)

    best_slice = _NEG_INF
    best_placement = None
    for slices_left in sorted(dp[optimal].get(0, {})):
        if best_slice < slices_left <= 0:
            best_slice = slices_left
            best_placement = dp[optimal][0][slices_left]
    return best_placement


def _prune_node(d: _Domain, threshold: int, leader_required: bool) -> None:
    """pruneDomainNodeBelowThreshold."""
    if d.slice_state < threshold:
        d.clear_state()
        return
    if leader_required and d.leader_state > 0 \
            and d.slice_state_with_leader < threshold:
        d.clear_leader_capacity()


def prune_below_threshold(snapshot, domains: list[_Domain], threshold: int,
                          slice_size: int, slice_level_idx: int, level: int,
                          leader_required: bool) -> None:
    """pruneDomainsBelowThreshold: zero out sub-threshold children, then
    re-aggregate each candidate subtree and prune it too."""
    for d in domains:
        for c in d.children:
            _prune_node(c, threshold, leader_required)
    for d in domains:
        snapshot.bubble_up(d, slice_size, slice_level_idx, level,
                           leader_required)
        _prune_node(d, threshold, leader_required)


def find_best_domains(snapshot, state) -> tuple[Optional[list[_Domain]],
                                                int]:
    """findBestDomainsForBalancedPlacement: per sibling-group of the
    requested level, compute the balance threshold via a greedy probe,
    prune, and keep the group with the highest threshold (fewest domains
    on ties)."""
    slice_count = state.count // state.slice_size
    if state.requested_level_idx == 0:
        groups = [list(snapshot.domains_per_level[0].values())]
    else:
        parents = sorted(
            snapshot.domains_per_level[state.requested_level_idx - 1]
            .values(), key=lambda d: d.values)
        groups = [p.children for p in parents]

    best_threshold = 0
    best_count = 0
    best: Optional[list[_Domain]] = None
    leader_required = state.leader_count > 0

    for siblings in groups:
        if not siblings:
            continue
        cand = clone_domains(list(siblings))
        lower = [c for d in cand for c in d.children] \
            if state.requested_level_idx < state.slice_level_idx else cand
        fits, selected, lwl, last = evaluate_greedy(
            snapshot, lower, slice_count, state.leader_count)
        if not fits:
            continue
        threshold = threshold_value(slice_count, selected, lwl, last)
        threshold_with_reservation = threshold
        if state.leader_count > 0 and last is not None:
            threshold_with_reservation = min(
                threshold, last.slice_state_with_leader)
        if threshold < best_threshold:
            continue
        prune_below_threshold(snapshot, cand, threshold, state.slice_size,
                              state.slice_level_idx,
                              state.requested_level_idx, leader_required)
        fits2, count2, _, _ = evaluate_greedy(snapshot, cand, slice_count,
                                              state.leader_count)
        if not fits2 and threshold_with_reservation < threshold:
            # Retry with a lower threshold that reserves leader capacity.
            if threshold_with_reservation <= 0 or \
                    threshold_with_reservation < best_threshold:
                continue
            threshold = threshold_with_reservation
            cand = clone_domains(list(siblings))
            prune_below_threshold(snapshot, cand, threshold,
                                  state.slice_size, state.slice_level_idx,
                                  state.requested_level_idx,
                                  leader_required)
            fits2, count2, _, _ = evaluate_greedy(
                snapshot, cand, slice_count, state.leader_count)
        if not fits2:
            continue
        if threshold > best_threshold or (
                threshold == best_threshold and count2 < best_count):
            best_threshold = threshold
            best_count = count2
            best = cand
    return best, best_threshold


def place_slices_balanced(snapshot, domains: list[_Domain],
                          slice_count: int, leader_count: int,
                          slice_size: int, threshold: int
                          ) -> tuple[Optional[list[_Domain]], str]:
    """placeSlicesOnDomainsBalanced: give every selected domain the
    threshold share, distribute the remainder, reserve the leader."""
    result = select_optimal_domain_set(snapshot, domains, slice_count,
                                       leader_count, slice_size, False)
    if result is None:
        return None, ("TAS Balanced Placement: cannot find optimal domain "
                      "set to fit the request")
    if slice_count < len(result) * threshold:
        return None, ("TAS Balanced Placement: not enough slices to meet "
                      "the threshold")
    result = snapshot._sorted_with_leader(result, False)
    extra = slice_count - len(result) * threshold
    leaders_left = leader_count
    for d in result:
        if leaders_left > 0:
            take = min(d.slice_state_with_leader - threshold, extra)
            d.leader_state = 1
            leaders_left -= 1
        elif extra > 0:
            take = min(d.slice_state - threshold, extra)
            d.leader_state = 0
        else:
            d.leader_state = 0
            take = 0
        d.state = (threshold + take) * slice_size
        d.slice_state = threshold + take
        d.slice_state_with_leader = d.slice_state
        d.state_with_leader = d.state - d.leader_state
        extra -= take
    if extra > 0 or leaders_left > 0:
        return None, ("TAS Balanced Placement: not all slices or leaders "
                      "could be placed")
    return result, ""


def apply(snapshot, state, threshold: int, cand: list[_Domain]
          ) -> tuple[Optional[list[_Domain]], int, str]:
    """applyBalancedPlacementAlgorithm: pick the optimal set (entropy
    priority) at the requested level, drop to its children when the slice
    level is deeper, then balance-place the slices."""
    slice_count = state.count // state.slice_size
    if state.requested_level_idx < state.slice_level_idx:
        result = select_optimal_domain_set(
            snapshot, cand, slice_count, state.leader_count,
            state.slice_size, True)
        if result is None:
            return None, 0, ("TAS Balanced Placement: cannot find optimal "
                             "domain set to fit the request")
        cand = [c for d in result for c in d.children]
        fit_level_idx = state.requested_level_idx + 1
    else:
        fit_level_idx = state.requested_level_idx
    placed, reason = place_slices_balanced(
        snapshot, cand, slice_count, state.leader_count, state.slice_size,
        threshold)
    if reason:
        return None, 0, reason
    return placed, fit_level_idx, ""
