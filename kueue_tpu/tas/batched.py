"""Batched device TAS: cycle-level topology placement for the hybrid path.

Before this module, any ClusterQueue carrying a TAS flavor demoted its
whole cohort root to the sequential path (engine_bridge._flavor_unsafe
treated ``topology_name`` like taints), so TAS-heavy worlds never ran a
device cycle. The planner here lifts topology-aware admission into the
hybrid cycle:

  * ``plan_cycle`` nominates a topology assignment for every device-
    eligible TAS head BEFORE the quota kernel launches, against the
    cycle-start forest state — exactly the sequential nominate loop's
    semantics, where apply_tas_pass runs once per head against the
    cycle snapshot before any entry commits. Identical request
    signatures share one placement (the snapshot's _place_memo), and
    when the persisted crossover calibration (tas/calibration.py) says
    the device wins, all remaining distinct signatures of a flavor
    forest go through ONE padded ops/tas.tas_place_batch launch per
    (column axis, selection statics) group instead of a descent each.
  * Heads that need a TAS feature the batch can't express — leaders /
    pod-set groups, elastic previous slices, unhealthy-node
    replacement, multi-layer slice rounding, balanced placement — or
    whose placement fails at nomination (the host owns the
    PREEMPT -> simulate-empty -> park ladder) demote ONLY their root,
    with a per-reason counter, instead of forcing the cycle sequential.
  * ``commit_plan`` is the commit-order re-check: device admits
    serialize in slot_position order through a local capacity overlay
    that mirrors TASFlavorSnapshot.fits + add_usage (including the
    implicit per-pod "pods" slot), and an admit whose nominated
    placement no longer fits is DROPPED — the batched form of
    _process_entry's "no longer fits after processing another
    workload" skip. Dropped rows stay pending (device rows are never
    popped), exactly like a sequential commit skip.

Everything here READS the prototype forests; the only usage writes
remain in the assume path (scheduler_cache._account_tas ->
commit_usage), so the undo-log discipline (U1) is untouched.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_tpu.api.types import TopologyMode
from kueue_tpu.config import features

_FEATURE = "tas-feature"
_RESOLVE = "tas-resolve"
_NO_FIT = "tas-no-fit"
_PLAN_MISS = "tas-plan-miss"


def enabled() -> bool:
    """KUEUE_TPU_TAS_BATCH=0 restores the legacy demote-everything
    behavior (every TAS CQ runs sequential) — the toggle the digest
    equivalence suite flips."""
    return os.environ.get("KUEUE_TPU_TAS_BATCH", "1") != "0"


def _now() -> float:
    import time
    return time.perf_counter()  # graftlint: allow[D1] phase timing for bench detail, never decision state


def cq_tas_info(cache) -> dict:
    """{cq_name: (candidate TAS flavor names in spec order, tas_only)}
    for every ClusterQueue referencing at least one TAS flavor,
    memoized by spec version. ``tas_only`` mirrors assigner._tas_only:
    every flavor the CQ references carries a topology, so pod sets
    WITHOUT a topology request still get an (implied unconstrained)
    placement."""
    ver = cache.spec_version
    cached = getattr(cache, "_tas_cq_info", None)
    if cached is not None and cached[0] == ver:
        return cached[1]
    tas_names = cache._tas_flavor_names()
    info: dict = {}
    for name, spec in cache.cluster_queues.items():
        flv: list = []
        referenced: list = []
        for rg in spec.resource_groups:
            for fq in rg.flavors:
                referenced.append(fq.name)
                if fq.name in tas_names and fq.name not in flv:
                    flv.append(fq.name)
        if flv:
            info[name] = (tuple(flv),
                          all(n in tas_names for n in referenced))
    cache._tas_cq_info = (ver, info)
    return info


@dataclass
class CyclePlan:
    """One cycle's nominated placements and demotion verdicts."""

    # ci -> {flavor: {pod_set_name: TopologyAssignment}}. A ci mapped
    # to an EMPTY dict admits plainly (no pod set routes through TAS
    # for any candidate flavor — workload_tas_requests would skip it).
    placements: dict = field(default_factory=dict)
    # ci -> [(pod_set_name, single_pod_requests, count)] for the
    # commit overlay math (mirrors tas_usage_of_assignment inputs).
    requests: dict = field(default_factory=dict)
    # reason -> [ci] (heads the planner hands to the host path).
    demote: dict = field(default_factory=dict)
    # ci -> frozenset of candidate flavor names (forest-closure input;
    # includes demoted heads — a host TAS head can commit on any of
    # its CQ's TAS flavors).
    flavors_of: dict = field(default_factory=dict)
    # Real (unpadded) heads per tas_place_batch launch.
    launch_sizes: list = field(default_factory=list)
    placed_device: int = 0
    placed_host: int = 0
    memo_hits: int = 0
    timings: dict = field(default_factory=lambda: {
        "encode": 0.0, "place": 0.0, "decode": 0.0})

    def demote_head(self, ci: int, reason: str) -> None:
        self.demote.setdefault(reason, []).append(int(ci))


def plan_cycle(eng, w, head_wid, need: np.ndarray) -> CyclePlan:
    """Nominate placements for the TAS heads in ``need`` (bool[C]).

    Each head either gets a plan entry (every candidate flavor placed,
    or no placement needed), or a demotion reason. Placements are
    computed against the LIVE prototype forests (cache.tas_prototypes)
    — the same state the assume path commits into — so verdicts equal
    what the sequential nominate would produce at cycle start."""
    from kueue_tpu.tas.snapshot import TASPodSetRequest

    plan = CyclePlan()
    cache = eng.cache
    protos = cache.tas_prototypes()
    info_by_cq = cq_tas_info(cache)
    rows = eng.queues.rows
    balanced = features.enabled("TASBalancedPlacement")

    # flavor -> {memo_key: (req, state)}; insertion order is the
    # deterministic ci scan order below (D1: launch composition feeds
    # the decision stream through demotions).
    by_flavor: dict = {}
    # ci -> [(flavor, memo_key)] in candidate order, for assembly.
    head_keys: dict = {}

    for ci in np.nonzero(need)[0]:
        ci = int(ci)
        flv_only = info_by_cq.get(w.cq_names[ci])
        if flv_only is None:
            continue
        flv, tas_only = flv_only
        plan.flavors_of[ci] = frozenset(flv)
        winfo = rows.info_of[int(head_wid[ci])]
        wobj = winfo.obj
        if wobj.replaced_workload_slice is not None:
            plan.demote_head(ci, _FEATURE)  # elastic delta: host path
            continue
        if getattr(wobj.status, "unhealthy_nodes", ()):
            plan.demote_head(ci, _FEATURE)  # node replacement: host
            continue
        sigs = rows.tas_requests(int(head_wid[ci]))
        any_tr = any(s[1][0] is not None for s in sigs)
        if len(sigs) != 1:
            # Multi-podset TAS threads assumed usage between pod sets
            # (find_assignments' shared accumulator): host path. A
            # multi-podset head with no TAS routing at all admits
            # plainly — but such heads are not fast-path encodable
            # anyway, so this is defensive.
            if any_tr or tas_only:
                plan.demote_head(ci, _FEATURE)
            else:
                plan.placements[ci] = {}
            continue
        ps_name, sig, single, count, group = sigs[0]
        if sig[0] is None and not tas_only:
            # No topology request and the CQ has non-TAS flavors: the
            # sequential pass skips placement entirely.
            plan.placements[ci] = {}
            continue
        if group:
            plan.demote_head(ci, _FEATURE)  # leader/pod-set group
            continue
        if balanced and sig[0] == TopologyMode.PREFERRED:
            plan.demote_head(ci, _FEATURE)  # balanced placement: host
            continue
        ps = wobj.pod_sets[0]
        req = TASPodSetRequest(ps, single, count)
        keys = []
        failed = None
        for fname in flv:
            proto = protos.get(fname)
            if proto is None:
                failed = _RESOLVE
                break
            state, _reason = proto.resolve_request(req, False)
            if state is None:
                # The host path surfaces the resolve error as the
                # placement failure reason; it owns that ladder.
                failed = _RESOLVE
                break
            if state.slice_size_at_level:
                failed = _FEATURE  # multi-layer rounding: host only
                break
            key = (sig, ps_name, False,
                   tuple(sorted((ps.node_selector or {}).items())))
            by_flavor.setdefault(fname, {}).setdefault(
                key, (req, state))
            keys.append((fname, key))
        if failed is not None:
            plan.demote_head(ci, failed)
            continue
        head_keys[ci] = keys
        plan.requests[ci] = [(ps_name, single, count)]

    # One placement per distinct (flavor, signature) — memo first,
    # then a batched launch per group, host descent for the rest.
    results: dict = {}
    for fname in sorted(by_flavor):
        results[fname] = _place_flavor(protos[fname], by_flavor[fname],
                                       plan)

    for ci, keys in head_keys.items():
        fmap = {}
        ok = True
        for fname, key in keys:
            res = results[fname].get(key)
            if res is None:
                plan.demote_head(ci, _PLAN_MISS)  # defensive
                ok = False
                break
            assignments, _reason = res
            if assignments is None:
                # Placement failed on a candidate flavor at nominate:
                # the host owns PREEMPT -> simulate-empty -> park
                # (and the kernel's flavor pick is unknown pre-launch,
                # so any failing candidate demotes).
                plan.demote_head(ci, _NO_FIT)
                ok = False
                break
            fmap[fname] = assignments
        if ok:
            plan.placements[ci] = fmap
        else:
            plan.requests.pop(ci, None)
    return plan


def _place_flavor(proto, items: dict, plan: CyclePlan) -> dict:
    """Place every distinct request signature against one flavor
    forest. Returns {memo_key: (assignments | None, reason)} with the
    exact result shape find_topology_assignments memoizes — batched
    results are inserted into the snapshot's _place_memo so later
    same-cycle host calls (feasibility, the host tail) agree."""
    from kueue_tpu.tas import device

    out: dict = {}
    ver = getattr(proto, "_usage_version", 0)
    memo = getattr(proto, "_place_memo", None)
    if memo is None or memo[0] != ver or len(memo[1]) > 4096:
        memo = (ver, {})
        proto._place_memo = memo
    pending: dict = {}
    for key, (req, state) in items.items():
        hit = memo[1].get(key)
        if hit is not None:
            plan.memo_hits += 1
            out[key] = hit
        else:
            pending[key] = (req, state)
    if not pending:
        return out

    device_items: dict = {}
    host_keys: list = []
    if (features.enabled("DeviceTAS") and proto.level_keys
            and device.worth_offloading(proto)):
        for key, (req, state) in pending.items():
            if state.least_free != state.unconstrained:
                # BestFit-unconstrained (TASProfileMixed off): the
                # kernel encodes the LeastFree profile — host descent
                # for these heads, NOT a demotion.
                host_keys.append(key)
            else:
                device_items[key] = (req, state)
    else:
        host_keys = list(pending)

    if device_items:
        for key, res in _place_batch(proto, device_items, plan).items():
            out[key] = res
            memo[1][key] = res
            plan.placed_device += 1
    for key in host_keys:
        req, _state = pending[key]
        t0 = _now()
        # Routes through the snapshot's own memo + phase-1 memo; on
        # calibrated backends worth_offloading may still take the
        # per-placement device path inside.
        out[key] = proto.find_topology_assignments(req)
        plan.timings["place"] += _now() - t0
        plan.placed_host += 1
    return out


def _place_batch(proto, items: dict, plan: CyclePlan) -> dict:
    """One padded tas_place_batch launch per (column axis, selection
    statics) group of request signatures, decoded identically to
    device.try_find (same failure strings, same sorted domain
    order)."""
    import jax
    import jax.numpy as jnp

    from kueue_tpu.ops import tas as tops
    from kueue_tpu.tas.device import (
        _cols_for,
        _free_matrix,
        _req_vector,
        _structure,
        _usage_matrix,
    )
    from kueue_tpu.tas.snapshot import (
        TopologyAssignment,
        TopologyDomainAssignment,
    )

    t0 = _now()
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    struct = _structure(proto)
    nl = struct["nl"]
    mp = struct["m"]
    leaves = struct["leaves"]
    out: dict = {}

    # Group by the launch statics + column axis; order is the caller's
    # deterministic insertion order.
    groups: dict = {}
    for key, (req, state) in items.items():
        per_pod = dict(req.single_pod_requests)
        per_pod["pods"] = per_pod.get("pods", 0) + 1
        cols = _cols_for(struct, per_pod, {})
        if not struct["level_domains"][state.requested_level_idx]:
            out[key] = (None, (
                "no topology domains at level: "
                f"{proto.level_keys[state.requested_level_idx]}"))
            continue
        gkey = (tuple(cols), state.requested_level_idx,
                state.slice_level_idx, state.required,
                state.unconstrained)
        groups.setdefault(gkey, []).append((key, req, state, per_pod))

    jnp_cache = struct.setdefault("jnp_cache", {})
    if "consts" not in jnp_cache:
        jnp_cache["consts"] = (
            jnp.asarray(struct["has_pods_cap"]),
            jnp.asarray(struct["valid"]), jnp.asarray(struct["vrank"]),
            jnp.asarray(struct["parent"]))
    j_pods_cap, j_valid, j_vrank, j_parent = jnp_cache["consts"]
    valid_leaves = struct["valid"][nl - 1]
    plan.timings["encode"] += _now() - t0

    for gkey, members in groups.items():
        t0 = _now()
        cols_key, req_idx, slice_idx, required, unconstrained = gkey
        cols = list(cols_key)
        col_of = {res: i for i, res in enumerate(cols)}
        free = _free_matrix(struct, cols)
        usage = _usage_matrix(proto, struct, cols)
        B = len(members)
        Bp = 1 << (B - 1).bit_length() if B > 1 else 1
        per_pod = np.zeros((Bp, len(cols)), np.int64)
        count = np.ones(Bp, np.int64)
        slice_size = np.ones(Bp, np.int64)
        leaf_mask = np.zeros((Bp, mp), bool)
        leaf_mask[:] = valid_leaves  # padding rows fit trivially
        for b, (key, req, state, pp) in enumerate(members):
            per_pod[b] = _req_vector(pp, cols)
            count[b] = state.count
            slice_size[b] = state.slice_size
            excluded = proto._match_excluded(req.pod_set)
            if excluded:
                for i, leaf in enumerate(leaves):
                    if leaf.values in excluded:
                        leaf_mask[b, i] = False

        j_free = jnp_cache.get(("free", tuple(cols_key)))
        if j_free is None:
            j_free = jnp.asarray(free)
            jnp_cache[("free", tuple(cols_key))] = j_free
        if not np.any(usage):
            j_usage = jnp_cache.get(("zeros", usage.shape))
            if j_usage is None:
                j_usage = jnp_cache[("zeros", usage.shape)] = jnp.zeros(
                    usage.shape, jnp.int64)
        else:
            ukey = (getattr(proto, "_usage_version", 0), tuple(cols_key))
            cached_u = getattr(proto, "_j_usage_cache", None)
            if cached_u is not None and cached_u[0] == ukey:
                j_usage = cached_u[1]
            else:
                j_usage = jnp.asarray(usage)
                proto._j_usage_cache = (ukey, j_usage)
        plan.timings["encode"] += _now() - t0

        t0 = _now()
        status, fit_arg, cnt, _lead = jax.device_get(tops.tas_place_batch(
            j_free, j_usage, jnp.asarray(per_pod),
            jnp.asarray(leaf_mask), jnp.asarray(count),
            jnp.asarray(slice_size), j_pods_cap, j_valid, j_vrank,
            j_parent, num_levels=nl, max_domains=mp,
            pods_col=col_of["pods"], req_level=req_idx,
            slice_level=slice_idx, required=required,
            unconstrained=unconstrained))
        plan.timings["place"] += _now() - t0
        plan.launch_sizes.append(B)

        t0 = _now()
        for b, (key, req, state, pp) in enumerate(members):
            st = int(status[b])
            if st == tops.ERR_NOT_FIT:
                stats = proto._exclusion_stats(req.pod_set, pp, False,
                                               {}, ())
                out[key] = (None, proto._not_fit_message(
                    int(fit_arg[b]), state.count // state.slice_size,
                    state.slice_size, stats))
                continue
            if st == tops.ERR_UNDERFLOW:
                out[key] = (None,
                            "internal: assignment accounting underflow")
                continue
            domains = sorted(
                (TopologyDomainAssignment(leaves[i].values,
                                          int(cnt[b, i]))
                 for i in np.nonzero(cnt[b] > 0)[0]),
                key=lambda a: a.values)
            out[key] = ({req.pod_set.name: TopologyAssignment(
                tuple(proto.level_keys), tuple(domains))}, "")
        plan.timings["decode"] += _now() - t0
    return out


def commit_plan(eng, w, wls, plan: CyclePlan, wl_admitted: np.ndarray,
                slot_position: np.ndarray, flavor_of_res: np.ndarray,
                cq_on_device: np.ndarray, num_rows: int):
    """Commit-order re-check for the device admits that carry a plan.

    Mirrors the sequential commit loop: process admits in
    slot_position order; re-check the nominated placement against a
    local overlay of this cycle's earlier TAS commits (the exact
    fits() arithmetic: free_capacity - tas_usage - overlay, per
    domain, NO implicit pods on the check side); on success accumulate
    the overlay with add_usage semantics (scaled requests PLUS one
    "pods" slot per placed pod) and attach; on failure DROP the admit
    — the batched form of the SKIPPED "no longer fits after processing
    another workload" verdict. Rows were never popped, so a drop needs
    no queue action.

    Returns (attach, drops, demote_cis):
      attach: row -> {pod_set_name: TopologyAssignment} for admits
        that keep their verdict (empty placements admit plainly);
      drops: rows whose admit verdict must be cleared;
      demote_cis: slots whose ROOT must demote post-kernel — a drop on
        a multi-CQ root invalidates the root's later quota decisions
        (sequential would re-check them), so the host re-runs the
        whole root. Singleton roots (the common TAS world) never
        demote here."""
    protos = eng.cache.tas_prototypes()
    info_by_cq = cq_tas_info(eng.cache)
    admit_of: dict = {}
    for i in np.nonzero(wl_admitted[:num_rows])[0]:
        ci = int(wls.cq[i])
        if ci in plan.placements and cq_on_device[ci]:
            admit_of[ci] = int(i)
    overlay: dict = {}
    attach: dict = {}
    drops: list = []
    demote_cis: list = []
    root_of_cq = w.root_of_cq
    for ci in sorted(admit_of, key=lambda c: int(slot_position[c])):
        i = admit_of[ci]
        fmap = plan.placements[ci]
        if not fmap:
            continue  # nothing TAS-routed: plain admit
        flv = info_by_cq.get(w.cq_names[ci], ((), False))[0]
        fname = _kernel_pick(w, wls, flavor_of_res, ci, i,
                             frozenset(flv))
        if fname is None:
            # The kernel put every requesting pod set on a non-TAS
            # flavor: workload_tas_requests would skip it too.
            continue
        assignments = fmap.get(fname)
        proto = protos.get(fname)
        ok = assignments is not None and proto is not None
        if ok:
            for ps_name, single, _count in plan.requests.get(ci, ()):
                ta = assignments.get(ps_name)
                if ta is None:
                    continue
                for dom in ta.domains:
                    leaf = proto.leaves.get(tuple(dom.values))
                    if leaf is None:
                        ok = False
                        break
                    over = overlay.get((fname, dom.values))
                    for res, per_pod in single.items():
                        head = leaf.free_capacity.get(res, 0) \
                            - leaf.tas_usage.get(res, 0)
                        if over:
                            head -= over.get(res, 0)
                        if per_pod * dom.count > head:
                            ok = False
                            break
                    if not ok:
                        break
                if not ok:
                    break
        if ok:
            for ps_name, single, _count in plan.requests.get(ci, ()):
                ta = assignments.get(ps_name)
                if ta is None:
                    continue
                for dom in ta.domains:
                    over = overlay.setdefault((fname, dom.values), {})
                    for res, per_pod in single.items():
                        over[res] = over.get(res, 0) \
                            + per_pod * dom.count
                    over["pods"] = over.get("pods", 0) + dom.count
            attach[i] = assignments
        else:
            drops.append(i)
            root = int(root_of_cq[ci])
            if int(np.count_nonzero(root_of_cq == root)) > 1:
                demote_cis.append(ci)
    return attach, drops, demote_cis


def _kernel_pick(w, wls, flavor_of_res, ci: int, i: int,
                 tas_names: frozenset) -> Optional[str]:
    """The TAS flavor the sequential pass would route this admit
    through: the first assigned flavor (in the entry's resource
    iteration order, matching _make_entry) that is a TAS flavor —
    workload_tas_requests' next(fa.name in cq.tas_flavors)."""
    P = flavor_of_res.shape[1]
    for p in range(P):
        for s_i in range(len(w.resource_names)):
            fl = int(flavor_of_res[ci, p, s_i])
            if fl < 0 or wls.requests[i, p, s_i] <= 0:
                continue
            name = w.flavor_names[fl]
            if name in tas_names:
                return name
    return None


def closure_demotions(plan: CyclePlan, info_by_cq: dict, w,
                      has_head: np.ndarray, tas_cq: np.ndarray,
                      host_root: np.ndarray) -> list:
    """Shared-forest closure: TAS heads on host roots commit through
    the same prototype forests the plan was nominated against, at an
    arbitrary point of the host tail — placements for a forest must
    serialize through ONE path per cycle. Returns the device TAS slots
    whose candidate forests are touched by any host-root TAS head,
    iterated to a fixpoint (each demotion exposes its own forests to
    the host side). Forests are per-flavor (TAS usage never crosses
    flavors), so flavor names key the closure."""
    root_of_cq = w.root_of_cq
    hosted: set = set()
    for ci in np.nonzero(has_head & tas_cq & host_root[root_of_cq])[0]:
        flv = info_by_cq.get(w.cq_names[int(ci)])
        if flv is not None:
            hosted.update(flv[0])
    demoted: list = []
    demoted_set: set = set()
    changed = True
    while changed:
        changed = False
        for ci, flavors in plan.flavors_of.items():
            if ci in demoted_set or host_root[root_of_cq[ci]]:
                continue
            if flavors & hosted:
                demoted.append(ci)
                demoted_set.add(ci)
                # Every device slot on this root flips host with it.
                root = root_of_cq[ci]
                for cj, fl2 in plan.flavors_of.items():
                    if root_of_cq[cj] == root:
                        hosted.update(fl2)
                hosted.update(flavors)
                changed = True
    return demoted
