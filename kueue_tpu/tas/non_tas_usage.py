"""Non-TAS pod usage accounting for TAS capacity trees.

Reference: pkg/cache/scheduler/tas_non_tas_pod_cache.go (nonTasUsageCache —
per-pod usage entries plus pre-aggregated per-node totals, kept incrementally
to avoid the hot-path scan documented in kueue#8449) and
pkg/controller/tas/non_tas_usage_controller.go (the pod watch that feeds it:
only scheduled, non-terminated pods NOT managed by TAS belong in the cache;
deletes are idempotent so a missed Running→Terminated update still removes
usage).

TAS-managed pods are excluded because their usage is already accounted at
workload granularity through the scheduler cache; everything else running on
a topology-labeled node eats into the node's free capacity that
``TASFlavorSnapshot.add_node`` exposes to the placement algorithm.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.tas.ungater import TOPOLOGY_GATE

PODS_RESOURCE = "pods"


@dataclass
class PodUsage:
    """The slice of corev1.Pod the accounting needs (we are standalone)."""

    namespace: str
    name: str
    node_name: str = ""
    requests: dict[str, int] = field(default_factory=dict)  # milli-units
    terminated: bool = False  # phase Succeeded/Failed
    scheduling_gates: tuple[str, ...] = ()
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_tas(self) -> bool:
        """utiltas.IsTAS: the pod is managed by topology-aware scheduling —
        it carries the topology scheduling gate or a TAS domain label."""
        if TOPOLOGY_GATE in self.scheduling_gates:
            return True
        return any(k.startswith("kueue.x-k8s.io/tas")
                   or k == "kueue.x-k8s.io/podset"
                   for k in self.labels)


def belongs_to_cache(pod: PodUsage) -> bool:
    """non_tas_usage_controller.go belongsToNonTASCache: scheduled,
    non-terminated, not TAS-managed."""
    if pod.is_tas():
        return False
    if not pod.node_name:
        return False  # unscheduled pods use no capacity
    if pod.terminated:
        return False
    return True


class NonTASUsageCache:
    """tas_non_tas_pod_cache.go nonTasUsageCache."""

    def __init__(self) -> None:
        self._pod_usage: dict[str, tuple[str, dict[str, int]]] = {}
        self._node_usage: dict[str, dict[str, int]] = {}
        # Bumped whenever any node total changes; lets the scheduler
        # cache invalidate its TAS forest prototypes only when needed.
        self.version = 0

    # -- mutation (update/delete under the controller's event stream) --

    def update(self, pod: PodUsage) -> None:
        """May add a pod to the cache, or delete a terminated pod; an
        existing entry is replaced (handles node migration / in-place
        resource resize)."""
        if pod.terminated:
            self.delete(pod.key)
            return
        old = self._pod_usage.get(pod.key)
        requests = dict(pod.requests)
        if old == (pod.node_name, requests):
            return  # resync of an unchanged pod: totals did not move
        if old is not None:
            del self._pod_usage[pod.key]
            self._remove_node_usage(*old)
        self._pod_usage[pod.key] = (pod.node_name, requests)
        self._add_node_usage(pod.node_name, requests)
        self.version += 1

    def delete(self, key: str) -> None:
        old = self._pod_usage.pop(key, None)
        if old is None:
            return
        self._remove_node_usage(*old)
        self.version += 1

    # -- read side --

    def node_usage(self, node: str) -> dict[str, int]:
        """Pre-aggregated totals for one node (incl. a ``pods`` count)."""
        return self._node_usage.get(node, {})

    def nodes(self) -> dict[str, dict[str, int]]:
        return self._node_usage

    def __len__(self) -> int:
        return len(self._pod_usage)

    # -- internals --

    def _add_node_usage(self, node: str, usage: dict[str, int]) -> None:
        totals = self._node_usage.setdefault(node, {})
        for res, v in usage.items():
            totals[res] = totals.get(res, 0) + v
        totals[PODS_RESOURCE] = totals.get(PODS_RESOURCE, 0) + 1

    def _remove_node_usage(self, node: str, usage: dict[str, int]) -> None:
        totals = self._node_usage.get(node)
        if totals is None:
            return
        for res, v in usage.items():
            totals[res] = totals.get(res, 0) - v
        totals[PODS_RESOURCE] = totals.get(PODS_RESOURCE, 0) - 1
        if totals[PODS_RESOURCE] <= 0:
            del self._node_usage[node]


class NonTASUsageController:
    """non_tas_usage_controller.go NonTasUsageReconciler: routes pod
    events into the cache and invalidates the owning scheduler cache's
    TAS prototypes when totals move."""

    def __init__(self, cache) -> None:
        # ``cache`` is the scheduler Cache owning a NonTASUsageCache.
        self.cache = cache

    def pod_event(self, pod: PodUsage) -> bool:
        """Create/Update events: reconcile the single pod. Returns True
        when node totals moved (TAS prototypes were invalidated)."""
        before = self.cache.non_tas_usage.version
        if belongs_to_cache(pod):
            self.cache.non_tas_usage.update(pod)
        else:
            self.cache.non_tas_usage.delete(pod.key)
        changed = self.cache.non_tas_usage.version != before
        if changed:
            self.cache._invalidate_tas_prototypes()
        return changed

    def pod_deleted(self, namespace: str, name: str) -> bool:
        """Delete events are not filtered on terminal phase: a missed
        Running→Terminated update must still remove usage (idempotent)."""
        before = self.cache.non_tas_usage.version
        self.cache.non_tas_usage.delete(f"{namespace}/{name}")
        changed = self.cache.non_tas_usage.version != before
        if changed:
            self.cache._invalidate_tas_prototypes()
        return changed
