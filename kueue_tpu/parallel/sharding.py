"""Multi-chip sharding of the batched oracle over a jax.sharding.Mesh.

The scaling story (SURVEY.md §2.7/§5): the problem's big axis is Workloads
(50k+ pending), the small one is the node set (~1k CQs + cohorts). So:

  * workload-axis arrays ([W], [W, S]) are sharded over the mesh's "wl"
    axis — this is the framework's analog of data/sequence parallelism;
  * world/node arrays ([N, R], [C, ...]) are replicated (they're KBs);
  * heads selection (segment-min by CQ over all workloads) becomes a
    sharded reduction — XLA inserts the psum-style collectives over
    ICI when the workload axis spans chips;
  * nomination + commit operate on the [C]-sized head set, which is
    replicated — the commit scan is sequential by semantics and tiny.

Both the single cycle (sharded_cycle_step) and the WHOLE drain
(sharded_drain_loop — the jax.lax.while_loop over cycles runs entirely
on the mesh, no per-cycle host sync) are exposed. Decision parity of the
sharded programs against the single-device ones is enforced by
tests/test_multichip_parity.py.

On multi-host TPU (jax.distributed), the same jit works unchanged: the
mesh spans hosts and the workload shards ride ICI/DCN. No hand-written
collectives — the sharding annotations are the whole communication layer.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_tpu.oracle.batched import cycle_step, drain_loop

WL_AXIS = "wl"


def make_mesh(devices=None, axis: str = WL_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def _shardings(mesh: Mesh):
    return dict(
        wl=NamedSharding(mesh, P(WL_AXIS)),
        wl2=NamedSharding(mesh, P(WL_AXIS, None)),
        wl3=NamedSharding(mesh, P(WL_AXIS, None, None)),
        r=NamedSharding(mesh, P()),
        r2=NamedSharding(mesh, P(None, None)),
        r3=NamedSharding(mesh, P(None, None, None)),
    )


# (workload-sharded?, rank) of the common positional prefix:
# pending, inadmissible, usage, rank, commit_rank, wl_cq, wl_req,
# wl_priority, wl_has_qr, wl_hash, nominal, lend_limit, borrow_limit,
# parent, ancestors, height, group_of_res, group_flavors, no_preemption,
# can_pwb, can_always_reclaim, best_effort, fung_borrow_try_next,
# fung_pref_preempt_first, root_members, root_nodes, local_chain
_PREFIX = ("wl", "wl", "r2", "wl", "wl", "wl", "wl3", "wl", "wl", "wl",
           "r2", "r2", "r2", "r", "r2", "r", "r2", "r3", "r", "r", "r",
           "r", "r", "r", "r2", "r2", "r2")
# wl_ts, fair_weight, child_rank, local_depth, root_parent_local
_TAIL = ("wl", "r", "r", "r2", "r2")


def sharded_cycle_step(mesh: Mesh, depth: int, num_resources: int,
                       num_cqs: int, fair_mode: bool = False,
                       num_flavors: int = 1):
    """One scheduling cycle with the workload axis sharded over the mesh.
    Takes the _PREFIX args, then wl_ts, fair_weight, child_rank,
    local_depth, root_parent_local."""
    sh = _shardings(mesh)
    in_shardings = tuple(sh[n] for n in list(_PREFIX) + list(_TAIL))
    # 14 outputs (batched._cycle_core): ... plus slot_overflow [C],
    # victim_mask [C, 0], victim_variant [C, 0] (empty when the fused
    # preemption tensors are not provided, as here).
    out_shardings = (
        sh["wl"], sh["wl"], sh["r2"], sh["wl"], sh["r"], sh["r"],
        sh["r3"], sh["r"], sh["r"], sh["r"], sh["r"], sh["r"],
        sh["r2"], sh["r2"])

    def fn(pending, inadmissible, usage, rank, commit_rank, wl_cq,
           wl_req, wl_priority, wl_has_qr, wl_hash, nominal,
           lend_limit, borrow_limit, parent, ancestors, height,
           group_of_res, group_flavors, no_preemption, can_pwb,
           can_always_reclaim, best_effort, fung_borrow_try_next,
           fung_pref_preempt_first, root_members, root_nodes,
           local_chain, wl_ts, fair_weight, child_rank, local_depth,
           root_parent_local):
        return cycle_step.__wrapped__(
            pending, inadmissible, usage, rank, commit_rank, wl_cq,
            wl_req, wl_priority, wl_has_qr, wl_hash, nominal,
            lend_limit, borrow_limit, parent, ancestors, height,
            group_of_res, group_flavors, no_preemption, can_pwb,
            can_always_reclaim, best_effort, fung_borrow_try_next,
            fung_pref_preempt_first, root_members, root_nodes,
            local_chain, wl_ts, fair_weight, child_rank, local_depth,
            root_parent_local=root_parent_local,
            depth=depth, num_resources=num_resources,
            num_cqs=num_cqs, fair_mode=fair_mode,
            num_flavors=num_flavors)

    return jax.jit(fn, in_shardings=in_shardings,
                   out_shardings=out_shardings)


def sharded_drain_loop(mesh: Mesh, depth: int, num_resources: int,
                       num_cqs: int, fair_mode: bool = False,
                       num_flavors: int = 1):
    """The WHOLE drain (oracle.batched.drain_loop) on the mesh: the
    while-loop over cycles compiles into one sharded program; per-cycle
    heads selection reduces across workload shards via mesh collectives.
    Takes the _PREFIX args, then max_cycles (int), wl_ts, fair_weight,
    child_rank, local_depth, root_parent_local."""
    sh = _shardings(mesh)
    names = list(_PREFIX) + ["r"] + list(_TAIL)
    in_shardings = tuple(sh[n] for n in names)
    out_shardings = (sh["wl"], sh["wl"], sh["wl3"], sh["r2"], sh["r"],
                     sh["r"])

    def fn(pending, inadmissible, usage, rank, commit_rank, wl_cq,
           wl_req, wl_priority, wl_has_qr, wl_hash, nominal, lend_limit,
           borrow_limit, parent, ancestors, height, group_of_res,
           group_flavors, no_preemption, can_pwb, can_always_reclaim,
           best_effort, fung_borrow_try_next, fung_pref_preempt_first,
           root_members, root_nodes, local_chain, max_cycles, wl_ts,
           fair_weight, child_rank, local_depth, root_parent_local):
        return drain_loop.__wrapped__(
            pending, inadmissible, usage, rank, commit_rank, wl_cq,
            wl_req, wl_priority, wl_has_qr, wl_hash, nominal, lend_limit,
            borrow_limit, parent, ancestors, height, group_of_res,
            group_flavors, no_preemption, can_pwb, can_always_reclaim,
            best_effort, fung_borrow_try_next, fung_pref_preempt_first,
            root_members, root_nodes, local_chain, max_cycles, wl_ts,
            fair_weight, child_rank, local_depth, root_parent_local,
            depth=depth, num_resources=num_resources, num_cqs=num_cqs,
            fair_mode=fair_mode, num_flavors=num_flavors)

    return jax.jit(fn, in_shardings=in_shardings,
                   out_shardings=out_shardings)


def solver_mesh_args(solver, mesh: Mesh):
    """Assemble a BatchedDrainSolver's arrays in the positional order the
    sharded programs take (_PREFIX then tail), device_put with the right
    shardings. Workload counts must be divisible by the mesh size (pad
    upstream). Returns (prefix_list, tail_list)."""
    w, wl = solver.world, solver.wls
    W = wl.num_workloads
    sh = _shardings(mesh)
    prefix_vals = [
        wl.eligible & (wl.cq >= 0),                     # pending
        np.zeros(W, bool),                              # inadmissible
        np.broadcast_to(w.usage,
                        (w.num_nodes, w.nominal.shape[1])).copy(),
        solver.head_ranks(), solver.commit_ranks(),
        wl.cq, wl.requests, wl.priority, wl.has_quota_reservation,
        wl.hash_id,
        w.nominal, w.lend_limit, w.borrow_limit, w.parent, w.ancestors,
        w.height, w.group_of_res, w.group_flavors, w.no_preemption,
        w.can_preempt_while_borrowing, w.can_always_reclaim,
        w.best_effort, w.fung_borrow_try_next, w.fung_pref_preempt_first,
        w.root_members, w.root_nodes, w.local_chain,
    ]
    tail_vals = [wl.timestamp, w.fair_weight, w.child_rank, w.local_depth,
                 w.root_parent_local]
    prefix = [jax.device_put(v, sh[n])
              for v, n in zip(prefix_vals, _PREFIX)]
    tail = [jax.device_put(v, sh[n]) for v, n in zip(tail_vals, _TAIL)]
    return prefix, tail
