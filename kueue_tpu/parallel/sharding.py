"""Multi-chip sharding of the batched oracle over a jax.sharding.Mesh.

The scaling story (SURVEY.md §2.7/§5): the problem's big axis is Workloads
(50k+ pending), the small one is the node set (~1k CQs + cohorts). So:

  * workload-axis arrays ([W], [W, S]) are sharded over the mesh's "wl"
    axis — this is the framework's analog of data/sequence parallelism;
  * world/node arrays ([N, R], [C, ...]) are replicated (they're KBs);
  * heads selection (segment-min by CQ over all workloads) becomes a
    sharded reduction — XLA inserts the psum-style collectives over
    ICI when the workload axis spans chips;
  * nomination + commit operate on the [C]-sized head set, which is
    replicated — the commit scan is sequential by semantics and tiny.

On multi-host TPU (jax.distributed), the same jit works unchanged: the
mesh spans hosts and the workload shards ride ICI/DCN. No hand-written
collectives — the sharding annotations are the whole communication layer.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_tpu.oracle.batched import cycle_step

WL_AXIS = "wl"


def make_mesh(devices=None, axis: str = WL_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def sharded_cycle_step(mesh: Mesh, depth: int, num_resources: int,
                       num_cqs: int, fair_mode: bool = False,
                       num_flavors: int = 1):
    """Build a pjit-ed cycle step with the workload axis sharded over the
    mesh. Returns a callable with the same signature as
    oracle.batched.cycle_step (minus the static kwargs); pass wl_ts and
    fair_weight positionally after local_chain (required when
    fair_mode=True, accepted otherwise)."""
    wl_sharded = NamedSharding(mesh, P(WL_AXIS))
    wl_sharded2 = NamedSharding(mesh, P(WL_AXIS, None))
    repl = NamedSharding(mesh, P())
    repl2 = NamedSharding(mesh, P(None, None))
    repl3 = NamedSharding(mesh, P(None, None, None))

    in_shardings = (
        wl_sharded,  # pending
        wl_sharded,  # inadmissible
        repl2,  # usage
        wl_sharded,  # rank
        wl_sharded,  # commit_rank
        wl_sharded,  # wl_cq
        wl_sharded2,  # wl_req
        wl_sharded,  # wl_priority
        wl_sharded,  # wl_has_qr
        wl_sharded,  # wl_hash
        repl2,  # nominal
        repl2,  # lend_limit
        repl2,  # borrow_limit
        repl,  # parent
        repl2,  # ancestors
        repl,  # height
        repl2,  # group_of_res
        repl3,  # group_flavors
        repl,  # no_preemption
        repl,  # can_pwb
        repl,  # can_always_reclaim
        repl,  # best_effort
        repl,  # fung_borrow_try_next
        repl,  # fung_pref_preempt_first
        repl2,  # root_members
        repl2,  # root_nodes
        repl2,  # local_chain
        wl_sharded,  # wl_ts
        repl,  # fair_weight
    )
    out_shardings = (
        wl_sharded,  # new_pending
        wl_sharded,  # new_inadmissible
        repl2,  # usage
        wl_sharded,  # wl_admitted
        repl,  # slot_admitted
        repl,  # slot_position
        repl2,  # flavor_of_res
        repl,  # any_needs_oracle
        repl,  # slot_oracle
        repl,  # slot_preempting
        repl,  # head_idx
    )

    fn = partial(cycle_step.__wrapped__, depth=depth,
                 num_resources=num_resources, num_cqs=num_cqs,
                 fair_mode=fair_mode, num_flavors=num_flavors)
    return jax.jit(fn, in_shardings=in_shardings,
                   out_shardings=out_shardings)


def shard_workload_arrays(mesh: Mesh, *arrays):
    """Device-put workload-axis arrays with the wl sharding."""
    out = []
    for a in arrays:
        spec = P(WL_AXIS) if a.ndim == 1 else P(WL_AXIS, *([None] *
                                                           (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)
