"""Per-integration job webhooks: defaulting + validation.

Reference: each job framework ships a ``<kind>_webhook.go``
(pkg/controller/jobs/*/) layered over the shared helpers in
pkg/controller/jobframework/{defaults,validation}.go. The behaviors
mirrored here:

Defaulting (defaults.go):
  * default LocalQueue: a job with no queue name in a namespace that has
    a LocalQueue literally named "default" joins it
    (ApplyDefaultLocalQueue);
  * suspend-on-create: any queue-managed job is created suspended so
    kueue owns its start (ApplyDefaultForSuspend).

Validation (validation.go):
  * queue name must be a DNS-1123 label (ValidateQueueName);
  * maximum execution time must be > 0 (validateCreateForMaxExecTime);
  * queue name is immutable while the job is unsuspended
    (validateUpdateForQueueName);
  * prebuilt workload reference is immutable (validateUpdateForPrebuilt);
  * priority is immutable while quota is held (suspended jobs may
    change it — validateJobUpdateForWorkloadPriorityClassName);
  * per-framework rules, e.g. batch/job partial admission:
    0 < minParallelism < parallelism (job_webhook.go
    validatePartialAdmissionCreate).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _valid_queue_name(name: str) -> bool:
    return bool(_DNS1123.match(name)) and len(name) <= 63


# -- shared defaulting (jobframework/defaults.go) --


def apply_default_local_queue(job, default_lq_exists: Callable[[str], bool],
                              enabled: bool = True) -> None:
    """ApplyDefaultLocalQueue: adopt the namespace's LocalQueue named
    "default" when the job names none. Gated: kube_features.go
    LocalQueueDefaulting."""
    from kueue_tpu.config import features
    if not features.enabled("LocalQueueDefaulting"):
        return
    if enabled and not job.queue_name \
            and default_lq_exists(getattr(job, "namespace", "default")):
        job.queue_name = "default"


def apply_default_for_suspend(job, manage_jobs_without_queue_name: bool
                              ) -> None:
    """ApplyDefaultForSuspend: queue-managed jobs start suspended."""
    managed = bool(job.queue_name) or manage_jobs_without_queue_name
    if managed and not job.is_suspended():
        job.suspend()


# -- shared validation (jobframework/validation.go) --

QUEUE_NAME_LABEL_PATH = "metadata.labels[kueue.x-k8s.io/queue-name]"
PRIORITY_CLASS_LABEL_PATH = \
    "metadata.labels[kueue.x-k8s.io/priority-class]"
ADMISSION_GATED_BY_ANNOTATION = "kueue.x-k8s.io/admission-gated-by"
ADMISSION_GATED_BY_PATH = \
    f"metadata.annotations[{ADMISSION_GATED_BY_ANNOTATION}]"
ELASTIC_JOB_ANNOTATION = "kueue.x-k8s.io/elastic-job"
# workload_types.go topology annotations (jobframework/tas_validation.go
# validateTASPodSetRequest: at most one per pod template).
TOPOLOGY_ANNOTATIONS = (
    "kueue.x-k8s.io/podset-required-topology",
    "kueue.x-k8s.io/podset-preferred-topology",
    "kueue.x-k8s.io/podset-unconstrained-topology",
)
# util/webhook/validation_admissiongatedby.go:32 (the spec.managedBy
# constraint for Jobs).
MAX_GATE_NAME_LENGTH = 63

_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_PATH_SEGMENT = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")


def _csv_parse(value: str) -> list[str]:
    """pkg/util/csv Parse: comma split with per-entry whitespace trim."""
    return [p.strip() for p in value.split(",")]


def _validate_gate_format(value: str) -> list[str]:
    """validateAdmissionGatedByAnnotationFormat
    (util/webhook/validation_admissiongatedby.go:92): domain-prefixed
    paths, no duplicates, bounded length."""
    errs: list[str] = []
    if not value:
        return errs
    seen = set()
    for gate in _csv_parse(value):
        if gate == "":
            errs.append(f"{ADMISSION_GATED_BY_PATH}: Invalid value: "
                        f"{value!r}: cannot contain empty gate names")
            continue
        if gate in seen:
            errs.append(f"{ADMISSION_GATED_BY_PATH}: Invalid value: "
                        f"{value!r}: duplicate gate name: {gate}")
            continue
        seen.add(gate)
        # validation.IsDomainPrefixedPath (the spec.managedBy test).
        domain, slash, path = gate.partition("/")
        if not slash or not path or not domain:
            errs.append(
                f"{ADMISSION_GATED_BY_PATH}: Invalid value: {gate!r}: "
                'must be a domain-prefixed path (such as "acme.io/foo")')
            continue
        if not _SUBDOMAIN.match(domain):
            errs.append(
                f"{ADMISSION_GATED_BY_PATH}: Invalid value: {domain!r}: "
                "a lowercase RFC 1123 subdomain must consist of lower "
                "case alphanumeric characters, '-' or '.', and must "
                "start and end with an alphanumeric character")
            continue
        if any(not _PATH_SEGMENT.match(part)
               for part in path.split("/")):
            errs.append(
                f"{ADMISSION_GATED_BY_PATH}: Invalid value: {path!r}: "
                "name part must consist of alphanumeric characters, "
                "'-', '_' or '.', and must start and end with an "
                "alphanumeric character")
            continue
        if len(gate) > MAX_GATE_NAME_LENGTH:
            errs.append(f"{ADMISSION_GATED_BY_PATH}: Too long: may not "
                        f"be more than {MAX_GATE_NAME_LENGTH} bytes")
    return errs


def validate_admission_gated_by_on_create(job) -> list[str]:
    """ValidateAdmissionGatedByAnnotationOnCreate :36 (gated on
    kube_features.go AdmissionGatedBy)."""
    from kueue_tpu.config import features
    if not features.enabled("AdmissionGatedBy"):
        return []
    anns = getattr(job, "annotations", None) or {}
    return _validate_gate_format(anns.get(ADMISSION_GATED_BY_ANNOTATION,
                                          ""))


def validate_admission_gated_by_on_update(old, new) -> list[str]:
    """ValidateAdmissionGatedByAnnotationOnUpdate :45: gates may only be
    removed after creation, never added."""
    from kueue_tpu.config import features
    if not features.enabled("AdmissionGatedBy"):
        return []
    old_anns = getattr(old, "annotations", None) or {}
    new_anns = getattr(new, "annotations", None) or {}
    old_val = old_anns.get(ADMISSION_GATED_BY_ANNOTATION, "")
    new_val = new_anns.get(ADMISSION_GATED_BY_ANNOTATION, "")
    errs: list[str] = []
    if not old_val and new_val:
        errs.append(f"{ADMISSION_GATED_BY_PATH}: Forbidden: cannot add "
                    "admission gate after creation")
    if old_val and new_val:
        old_gates = _csv_parse(old_val)
        if any(g not in old_gates for g in _csv_parse(new_val)):
            errs.append(f"{ADMISSION_GATED_BY_PATH}: Forbidden: can "
                        "only remove gates, not add new ones")
    errs.extend(_validate_gate_format(new_val))
    return errs


def reject_elastic_annotation(job, gvk: str) -> list[str]:
    """statefulset_webhook.go / sparkapplication_webhook.go: kinds with
    their own scale semantics forbid the workload-slice opt-in
    annotation (gate ElasticJobsViaWorkloadSlices)."""
    from kueue_tpu.config import features
    if not features.enabled("ElasticJobsViaWorkloadSlices"):
        return []
    anns = getattr(job, "annotations", None) or {}
    if anns.get(ELASTIC_JOB_ANNOTATION) == "true":
        return [f"metadata.annotations[{ELASTIC_JOB_ANNOTATION}]: "
                f"Forbidden: elastic job is not supported for {gvk!r}"]
    return []


def validate_topology_annotations(path: str, annotations: dict
                                  ) -> list[str]:
    """tas_validation.go: a pod template names at most one of the
    topology mode annotations."""
    present = [a for a in TOPOLOGY_ANNOTATIONS if a in (annotations or {})]
    if len(present) > 1:
        names = ", ".join(f'"{a}"' for a in TOPOLOGY_ANNOTATIONS)
        return [f"{path}.annotations: Invalid value: must not contain "
                f"more than one topology annotation: [{names}]"]
    return []


def validate_job_on_create(job) -> list[str]:
    errs = []
    if job.queue_name and not _valid_queue_name(job.queue_name):
        errs.append(f"{QUEUE_NAME_LABEL_PATH}: Invalid value: "
                    f"{job.queue_name!r}: queue name is not a DNS-1123 "
                    f"label")
    max_exec = getattr(job, "maximum_execution_time_seconds", None)
    if max_exec is not None and max_exec <= 0:
        errs.append("maximum execution time should be greater than 0")
    errs.extend(validate_admission_gated_by_on_create(job))
    return errs


def validate_job_on_update(old, new) -> list[str]:
    errs = []
    if old.queue_name != new.queue_name and not old.is_suspended():
        errs.append(f"{QUEUE_NAME_LABEL_PATH}: Invalid value: queue "
                    "name is immutable while the job is unsuspended")
    if getattr(old, "prebuilt_workload_name", None) != \
            getattr(new, "prebuilt_workload_name", None):
        errs.append("prebuilt workload is immutable")
    if getattr(old, "priority", 0) != getattr(new, "priority", 0) \
            and not old.is_suspended():
        errs.append("priority is immutable while the job holds quota")
    errs.extend(validate_admission_gated_by_on_update(old, new))
    return errs


# -- per-framework webhooks (pkg/controller/jobs/*/*_webhook.go) --


@dataclass
class JobWebhook:
    """The generic webhook; framework-specific subclasses refine
    extra_create_rules."""

    kind: str = ""

    def default(self, job, registry) -> None:
        apply_default_local_queue(job, registry.default_lq_exists)
        apply_default_for_suspend(job,
                                  registry.manage_jobs_without_queue_name)

    def validate_create(self, job) -> list[str]:
        return validate_job_on_create(job) + self.extra_create_rules(job)

    def validate_update(self, old, new) -> list[str]:
        return validate_job_on_update(old, new)

    def extra_create_rules(self, job) -> list[str]:
        return []


@dataclass
class BatchJobWebhook(JobWebhook):
    """jobs/job/job_webhook.go."""

    kind: str = "batch/job"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        # validatePartialAdmissionCreate: 0 < minParallelism < parallelism
        min_p = getattr(job, "min_parallelism", None)
        if min_p is not None:
            if min_p <= 0:
                errs.append("minimum parallelism must be positive")
            elif min_p >= job.parallelism:
                errs.append("minimum parallelism must be lower than "
                            "parallelism")
        # validateSyncCompletionCreate: completions must cover
        # parallelism when partial admission syncs completions.
        completions = getattr(job, "completions", None)
        if min_p is not None and completions is not None \
                and completions < job.parallelism:
            errs.append("completions should be equal to parallelism when "
                        "partial admission is used")
        return errs


@dataclass
class JobSetWebhook(JobWebhook):
    """jobs/jobset/jobset_webhook.go."""

    kind: str = "jobset.x-k8s.io/jobset"

    def extra_create_rules(self, job) -> list[str]:
        if not getattr(job, "replicated_jobs", None):
            return ["a JobSet needs at least one replicated job"]
        names = [rj[0] for rj in job.replicated_jobs]
        if len(set(names)) != len(names):
            return ["replicated job names must be unique"]
        return []


def _elastic_job_allowed(job) -> bool:
    """The shared elastic gate: a kueue-managed job may use an external
    autoscaling mechanism only when ElasticJobsViaWorkloadSlices is on
    AND the job is elastic (raycluster_webhook.go:148,
    sparkapplication_webhook.go:129)."""
    from kueue_tpu.config import features
    return (features.enabled("ElasticJobsViaWorkloadSlices")
            and getattr(job, "elastic", False))


MAX_POD_SETS = 18  # jobframework/constants.go:21
RAY_HEAD_GROUP = "head"  # raycluster_controller.go:44


@dataclass
class RayClusterWebhook(JobWebhook):
    """jobs/raycluster/raycluster_webhook.go."""

    kind: str = "ray.io/raycluster"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "enable_in_tree_autoscaling", False) \
                and not _elastic_job_allowed(job):
            errs.append(
                "spec.enableInTreeAutoscaling: Invalid value: a kueue "
                "managed job can use autoscaling only when the "
                "ElasticJobsViaWorkloadSlices feature gate is on and "
                "the job is an elastic job")
        groups = list(getattr(job, "worker_groups", ()))
        # MaxPodSets cap: head + worker groups (raycluster_webhook.go
        # validateCreate; field.TooMany over spec.workerGroupSpecs).
        if len(groups) + 1 > MAX_POD_SETS:
            errs.append(f"spec.workerGroupSpecs: Too many: "
                        f"{len(groups) + 1}: must have at most "
                        f"{MAX_POD_SETS} items")
        names = [g[0] for g in groups]
        for i, name in enumerate(names):
            if name == RAY_HEAD_GROUP:
                errs.append(
                    f"spec.workerGroupSpecs[{i}].groupName: Forbidden: "
                    f'"{RAY_HEAD_GROUP}" is reserved for the head group')
        if len(set(names)) != len(names):
            errs.append("worker group names must be unique")
        errs.extend(validate_topology_annotations(
            "spec.headGroupSpec.template.metadata",
            getattr(job, "head_annotations", None)))
        for i, g in enumerate(groups):
            # (name, replicas, requests[, annotations]) tuples.
            if len(g) > 3:
                errs.extend(validate_topology_annotations(
                    f"spec.workerGroupSpecs[{i}].template.metadata",
                    g[3]))
        return errs


@dataclass
class SparkApplicationWebhook(JobWebhook):
    """jobs/sparkapplication/sparkapplication_webhook.go."""

    kind: str = "sparkoperator.k8s.io/sparkapplication"
    gvk: str = "sparkoperator.k8s.io/v1beta2, Kind=SparkApplication"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "dynamic_allocation", False) \
                and not _elastic_job_allowed(job):
            errs.append(
                "spec.dynamicAllocation.enabled: Invalid value: true: "
                "a kueue managed job can use dynamicAllocation only "
                "when the ElasticJobsViaWorkloadSlices feature gate is "
                "on and the job is an elastic job")
        # Even WITH the gate on, the kind itself rejects the slice
        # opt-in annotation (sparkapplication_webhook_test.go
        # "dynamicAllocation with elastic job feature").
        errs.extend(reject_elastic_annotation(job, self.gvk))
        if getattr(job, "executor_instances", 1) < 0:
            errs.append("executor instances must be non-negative")
        errs.extend(validate_topology_annotations(
            "spec.driver", getattr(job, "driver_annotations", None)))
        errs.extend(validate_topology_annotations(
            "spec.executor", getattr(job, "executor_annotations", None)))
        return errs


@dataclass
class ServingScaleWebhook(JobWebhook):
    """Shared rules for serving-scale kinds (StatefulSet/Deployment):
    replicas bounds on create; scale is the ONLY mutable shape field
    while running — the per-kind webhooks reject pod-template mutation
    of a managed set, and the queue/priority labels freeze once any
    replica is READY (statefulset_webhook.go TestValidateUpdate keys
    immutability on status.readyReplicas, not on suspension — a
    scaled-to-zero set may re-queue)."""

    display: str = "workload"
    gvk: str = ""

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "replicas", 1) < 0:
            errs.append("replicas must be non-negative")
        errs.extend(reject_elastic_annotation(job, self.gvk))
        return errs

    def validate_update(self, old, new) -> list[str]:
        errs = []
        ready = getattr(old, "ready_replicas", 0) > 0
        if old.queue_name and not new.queue_name:
            # Deleting the queue label orphans the managed set's
            # Workload: forbidden even at zero ready replicas
            # (statefulset_webhook_test.go "delete queue name").
            errs.append(f"{QUEUE_NAME_LABEL_PATH}: Invalid value: "
                        "queue name cannot be removed from a managed "
                        f"{self.display}")
        elif old.queue_name != new.queue_name and ready:
            errs.append(f"{QUEUE_NAME_LABEL_PATH}: Invalid value: "
                        "queue name is immutable while the "
                        f"{self.display} has ready replicas")
        if getattr(old, "priority", 0) != getattr(new, "priority", 0) \
                and ready:
            errs.append(f"{PRIORITY_CLASS_LABEL_PATH}: Invalid value: "
                        "priority is immutable while the "
                        f"{self.display} has ready replicas")
        if getattr(old, "prebuilt_workload_name", None) != \
                getattr(new, "prebuilt_workload_name", None):
            errs.append("prebuilt workload is immutable")
        if (getattr(old, "requests", None) != getattr(new, "requests",
                                                      None)
                and not old.is_suspended()):
            errs.append(f"pod template resources are immutable while "
                        f"the {self.display} is managed and running")
        errs.extend(validate_admission_gated_by_on_update(old, new))
        return errs


@dataclass
class StatefulSetWebhook(ServingScaleWebhook):
    """jobs/statefulset/statefulset_webhook.go."""

    kind: str = "apps/statefulset"
    display: str = "StatefulSet"
    gvk: str = "apps/v1, Kind=StatefulSet"


@dataclass
class DeploymentWebhook(ServingScaleWebhook):
    """jobs/deployment/deployment_webhook.go."""

    kind: str = "apps/deployment"
    display: str = "Deployment"
    gvk: str = "apps/v1, Kind=Deployment"


@dataclass
class LeaderWorkerSetWebhook(JobWebhook):
    """jobs/leaderworkerset/leaderworkerset_webhook.go: group shape
    bounds + topology-annotation exclusivity for the leader and worker
    templates."""

    kind: str = "leaderworkerset.x-k8s.io/leaderworkerset"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "replicas", 1) < 0:
            errs.append("spec.replicas: Invalid value: must be "
                        "non-negative")
        if getattr(job, "size", 1) <= 0:
            errs.append("spec.leaderWorkerTemplate.size: Invalid value: "
                        "must be positive")
        errs.extend(validate_topology_annotations(
            "spec.leaderWorkerTemplate.leaderTemplate.metadata",
            getattr(job, "leader_annotations", None)))
        errs.extend(validate_topology_annotations(
            "spec.leaderWorkerTemplate.workerTemplate.metadata",
            getattr(job, "worker_annotations", None)))
        return errs


@dataclass
class MPIJobWebhook(JobWebhook):
    """jobs/mpijob/mpijob_webhook.go."""

    kind: str = "kubeflow.org/mpijob"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "worker_replicas", 1) < 0:
            errs.append("worker replicas must be non-negative")
        if getattr(job, "slots_per_worker", 1) <= 0:
            errs.append("slotsPerWorker must be positive")
        if getattr(job, "run_launcher_as_worker", False) \
                and getattr(job, "worker_replicas", 1) == 0:
            errs.append("runLauncherAsWorker needs at least one worker")
        return errs


class JobWebhookRegistry:
    """Dispatches per-kind webhooks, the admission-webhook layer in front
    of JobReconciler.create_job."""

    def __init__(self, engine, integrations=None,
                 manage_jobs_without_queue_name: bool = False,
                 local_queue_defaulting: bool = True):
        from kueue_tpu.controllers.jobframework import DEFAULT_INTEGRATIONS

        self.engine = engine
        self.integrations = integrations or DEFAULT_INTEGRATIONS
        self.manage_jobs_without_queue_name = manage_jobs_without_queue_name
        self.local_queue_defaulting = local_queue_defaulting
        self.webhooks: dict[str, JobWebhook] = {
            "batch/job": BatchJobWebhook(),
            "jobset.x-k8s.io/jobset": JobSetWebhook(),
            "ray.io/raycluster": RayClusterWebhook(),
            "sparkoperator.k8s.io/sparkapplication":
                SparkApplicationWebhook(),
            "apps/statefulset": StatefulSetWebhook(),
            "apps/deployment": DeploymentWebhook(),
            "kubeflow.org/mpijob": MPIJobWebhook(),
            "leaderworkerset.x-k8s.io/leaderworkerset":
                LeaderWorkerSetWebhook(),
        }
        self._generic = JobWebhook()

    def register(self, kind: str, webhook: JobWebhook) -> None:
        self.webhooks[kind] = webhook

    def default_lq_exists(self, namespace: str) -> bool:
        if not self.local_queue_defaulting:
            return False
        return f"{namespace}/default" in self.engine.queues.local_queues

    def webhook_for(self, job) -> JobWebhook:
        kind = self.integrations.kind_of(job)
        return self.webhooks.get(kind, self._generic)

    def admit_create(self, job) -> list[str]:
        """Default + ValidateCreate; returns validation errors (empty =
        admitted)."""
        hook = self.webhook_for(job)
        hook.default(job, self)
        return hook.validate_create(job)

    def admit_update(self, old, new) -> list[str]:
        return self.webhook_for(new).validate_update(old, new)
