"""Per-integration job webhooks: defaulting + validation.

Reference: each job framework ships a ``<kind>_webhook.go``
(pkg/controller/jobs/*/) layered over the shared helpers in
pkg/controller/jobframework/{defaults,validation}.go. The behaviors
mirrored here:

Defaulting (defaults.go):
  * default LocalQueue: a job with no queue name in a namespace that has
    a LocalQueue literally named "default" joins it
    (ApplyDefaultLocalQueue);
  * suspend-on-create: any queue-managed job is created suspended so
    kueue owns its start (ApplyDefaultForSuspend).

Validation (validation.go):
  * queue name must be a DNS-1123 label (ValidateQueueName);
  * maximum execution time must be > 0 (validateCreateForMaxExecTime);
  * queue name is immutable while the job is unsuspended
    (validateUpdateForQueueName);
  * prebuilt workload reference is immutable (validateUpdateForPrebuilt);
  * priority is immutable while quota is held (suspended jobs may
    change it — validateJobUpdateForWorkloadPriorityClassName);
  * per-framework rules, e.g. batch/job partial admission:
    0 < minParallelism < parallelism (job_webhook.go
    validatePartialAdmissionCreate).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _valid_queue_name(name: str) -> bool:
    return bool(_DNS1123.match(name)) and len(name) <= 63


# -- shared defaulting (jobframework/defaults.go) --


def apply_default_local_queue(job, default_lq_exists: Callable[[str], bool],
                              enabled: bool = True) -> None:
    """ApplyDefaultLocalQueue: adopt the namespace's LocalQueue named
    "default" when the job names none. Gated: kube_features.go
    LocalQueueDefaulting."""
    from kueue_tpu.config import features
    if not features.enabled("LocalQueueDefaulting"):
        return
    if enabled and not job.queue_name \
            and default_lq_exists(getattr(job, "namespace", "default")):
        job.queue_name = "default"


def apply_default_for_suspend(job, manage_jobs_without_queue_name: bool
                              ) -> None:
    """ApplyDefaultForSuspend: queue-managed jobs start suspended."""
    managed = bool(job.queue_name) or manage_jobs_without_queue_name
    if managed and not job.is_suspended():
        job.suspend()


# -- shared validation (jobframework/validation.go) --


def validate_job_on_create(job) -> list[str]:
    errs = []
    if job.queue_name and not _valid_queue_name(job.queue_name):
        errs.append(f"queue name {job.queue_name!r} is not a DNS-1123 "
                    f"label")
    max_exec = getattr(job, "maximum_execution_time_seconds", None)
    if max_exec is not None and max_exec <= 0:
        errs.append("maximum execution time should be greater than 0")
    return errs


def validate_job_on_update(old, new) -> list[str]:
    errs = []
    if old.queue_name != new.queue_name and not old.is_suspended():
        errs.append("queue name is immutable while the job is "
                    "unsuspended")
    if getattr(old, "prebuilt_workload_name", None) != \
            getattr(new, "prebuilt_workload_name", None):
        errs.append("prebuilt workload is immutable")
    if getattr(old, "priority", 0) != getattr(new, "priority", 0) \
            and not old.is_suspended():
        errs.append("priority is immutable while the job holds quota")
    return errs


# -- per-framework webhooks (pkg/controller/jobs/*/*_webhook.go) --


@dataclass
class JobWebhook:
    """The generic webhook; framework-specific subclasses refine
    extra_create_rules."""

    kind: str = ""

    def default(self, job, registry) -> None:
        apply_default_local_queue(job, registry.default_lq_exists)
        apply_default_for_suspend(job,
                                  registry.manage_jobs_without_queue_name)

    def validate_create(self, job) -> list[str]:
        return validate_job_on_create(job) + self.extra_create_rules(job)

    def validate_update(self, old, new) -> list[str]:
        return validate_job_on_update(old, new)

    def extra_create_rules(self, job) -> list[str]:
        return []


@dataclass
class BatchJobWebhook(JobWebhook):
    """jobs/job/job_webhook.go."""

    kind: str = "batch/job"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        # validatePartialAdmissionCreate: 0 < minParallelism < parallelism
        min_p = getattr(job, "min_parallelism", None)
        if min_p is not None:
            if min_p <= 0:
                errs.append("minimum parallelism must be positive")
            elif min_p >= job.parallelism:
                errs.append("minimum parallelism must be lower than "
                            "parallelism")
        # validateSyncCompletionCreate: completions must cover
        # parallelism when partial admission syncs completions.
        completions = getattr(job, "completions", None)
        if min_p is not None and completions is not None \
                and completions < job.parallelism:
            errs.append("completions should be equal to parallelism when "
                        "partial admission is used")
        return errs


@dataclass
class JobSetWebhook(JobWebhook):
    """jobs/jobset/jobset_webhook.go."""

    kind: str = "jobset.x-k8s.io/jobset"

    def extra_create_rules(self, job) -> list[str]:
        if not getattr(job, "replicated_jobs", None):
            return ["a JobSet needs at least one replicated job"]
        names = [rj[0] for rj in job.replicated_jobs]
        if len(set(names)) != len(names):
            return ["replicated job names must be unique"]
        return []


def _elastic_job_allowed(job) -> bool:
    """The shared elastic gate: a kueue-managed job may use an external
    autoscaling mechanism only when ElasticJobsViaWorkloadSlices is on
    AND the job is elastic (raycluster_webhook.go:148,
    sparkapplication_webhook.go:129)."""
    from kueue_tpu.config import features
    return (features.enabled("ElasticJobsViaWorkloadSlices")
            and getattr(job, "elastic", False))


@dataclass
class RayClusterWebhook(JobWebhook):
    """jobs/raycluster/raycluster_webhook.go."""

    kind: str = "ray.io/raycluster"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "enable_in_tree_autoscaling", False) \
                and not _elastic_job_allowed(job):
            errs.append(
                "a kueue managed job can use autoscaling only when the "
                "ElasticJobsViaWorkloadSlices feature gate is on and "
                "the job is an elastic job")
        names = [g[0] for g in getattr(job, "worker_groups", ())]
        if len(set(names)) != len(names):
            errs.append("worker group names must be unique")
        return errs


@dataclass
class SparkApplicationWebhook(JobWebhook):
    """jobs/sparkapplication/sparkapplication_webhook.go."""

    kind: str = "sparkoperator.k8s.io/sparkapplication"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "dynamic_allocation", False) \
                and not _elastic_job_allowed(job):
            errs.append(
                "a kueue managed job can use dynamicAllocation only "
                "when the ElasticJobsViaWorkloadSlices feature gate is "
                "on and the job is an elastic job")
        if getattr(job, "executor_instances", 1) < 0:
            errs.append("executor instances must be non-negative")
        return errs


@dataclass
class ServingScaleWebhook(JobWebhook):
    """Shared rules for serving-scale kinds (StatefulSet/Deployment):
    replicas bounds on create; scale is the ONLY mutable shape field
    while running — the per-kind webhooks reject pod-template mutation
    of a managed set (statefulset_webhook.go, deployment_webhook.go)."""

    display: str = "workload"

    def extra_create_rules(self, job) -> list[str]:
        if getattr(job, "replicas", 1) < 0:
            return ["replicas must be non-negative"]
        return []

    def validate_update(self, old, new) -> list[str]:
        errs = super().validate_update(old, new)
        if (getattr(old, "requests", None) != getattr(new, "requests",
                                                      None)
                and not old.is_suspended()):
            errs.append(f"pod template resources are immutable while "
                        f"the {self.display} is managed and running")
        return errs


@dataclass
class StatefulSetWebhook(ServingScaleWebhook):
    """jobs/statefulset/statefulset_webhook.go."""

    kind: str = "apps/statefulset"
    display: str = "StatefulSet"


@dataclass
class DeploymentWebhook(ServingScaleWebhook):
    """jobs/deployment/deployment_webhook.go."""

    kind: str = "apps/deployment"
    display: str = "Deployment"


@dataclass
class MPIJobWebhook(JobWebhook):
    """jobs/mpijob/mpijob_webhook.go."""

    kind: str = "kubeflow.org/mpijob"

    def extra_create_rules(self, job) -> list[str]:
        errs = []
        if getattr(job, "worker_replicas", 1) < 0:
            errs.append("worker replicas must be non-negative")
        if getattr(job, "slots_per_worker", 1) <= 0:
            errs.append("slotsPerWorker must be positive")
        if getattr(job, "run_launcher_as_worker", False) \
                and getattr(job, "worker_replicas", 1) == 0:
            errs.append("runLauncherAsWorker needs at least one worker")
        return errs


class JobWebhookRegistry:
    """Dispatches per-kind webhooks, the admission-webhook layer in front
    of JobReconciler.create_job."""

    def __init__(self, engine, integrations=None,
                 manage_jobs_without_queue_name: bool = False,
                 local_queue_defaulting: bool = True):
        from kueue_tpu.controllers.jobframework import DEFAULT_INTEGRATIONS

        self.engine = engine
        self.integrations = integrations or DEFAULT_INTEGRATIONS
        self.manage_jobs_without_queue_name = manage_jobs_without_queue_name
        self.local_queue_defaulting = local_queue_defaulting
        self.webhooks: dict[str, JobWebhook] = {
            "batch/job": BatchJobWebhook(),
            "jobset.x-k8s.io/jobset": JobSetWebhook(),
            "ray.io/raycluster": RayClusterWebhook(),
            "sparkoperator.k8s.io/sparkapplication":
                SparkApplicationWebhook(),
            "apps/statefulset": StatefulSetWebhook(),
            "apps/deployment": DeploymentWebhook(),
            "kubeflow.org/mpijob": MPIJobWebhook(),
        }
        self._generic = JobWebhook()

    def register(self, kind: str, webhook: JobWebhook) -> None:
        self.webhooks[kind] = webhook

    def default_lq_exists(self, namespace: str) -> bool:
        if not self.local_queue_defaulting:
            return False
        return f"{namespace}/default" in self.engine.queues.local_queues

    def webhook_for(self, job) -> JobWebhook:
        kind = self.integrations.kind_of(job)
        return self.webhooks.get(kind, self._generic)

    def admit_create(self, job) -> list[str]:
        """Default + ValidateCreate; returns validation errors (empty =
        admitted)."""
        hook = self.webhook_for(job)
        hook.default(job, self)
        return hook.validate_create(job)

    def admit_update(self, old, new) -> list[str]:
        return self.webhook_for(new).validate_update(old, new)
