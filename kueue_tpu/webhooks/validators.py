"""Validating/defaulting webhooks (standalone validators).

Reference: pkg/webhooks/{clusterqueue,cohort,resourceflavor,workload}
_webhook.go — quota shape validation, cohort references, pod-set
invariants — plus pkg/cache/hierarchy/cycle.go:31 (HasCycle)."""

from __future__ import annotations

import re
from typing import Optional

from kueue_tpu.api.types import (
    BorrowWithinCohortPolicy,
    ClusterQueue,
    Cohort,
    PreemptionPolicy,
    ResourceFlavor,
    Workload,
)

_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
MAX_PODSETS = 8


def _name_errors(name: str, what: str) -> list[str]:
    if not name:
        return [f"{what}: name must not be empty"]
    if len(name) > 253 or not _NAME_RE.match(name):
        return [f"{what}: invalid name {name!r}"]
    return []


def validate_cluster_queue(cq: ClusterQueue) -> list[str]:
    """clusterqueue_webhook.go."""
    errs = _name_errors(cq.name, "clusterQueue")
    if cq.cohort:
        errs += _name_errors(cq.cohort, "clusterQueue.cohortName")
    seen_resources: set[str] = set()
    for gi, rg in enumerate(cq.resource_groups):
        if not rg.covered_resources:
            errs.append(f"resourceGroups[{gi}]: coveredResources empty")
        if not rg.flavors:
            errs.append(f"resourceGroups[{gi}]: flavors empty")
        for res in rg.covered_resources:
            if res in seen_resources:
                errs.append(
                    f"resourceGroups[{gi}]: resource {res} already covered "
                    "by another group")
            seen_resources.add(res)
        for fq in rg.flavors:
            # Each flavor must quota exactly the covered resources.
            if set(fq.resources) != set(rg.covered_resources):
                errs.append(
                    f"resourceGroups[{gi}].flavors[{fq.name}]: resources "
                    "must match coveredResources")
            for res, q in fq.resources.items():
                if q.nominal < 0:
                    errs.append(
                        f"flavor {fq.name}/{res}: nominalQuota < 0")
                if q.borrowing_limit is not None and q.borrowing_limit < 0:
                    errs.append(
                        f"flavor {fq.name}/{res}: borrowingLimit < 0")
                if q.lending_limit is not None and q.lending_limit < 0:
                    errs.append(
                        f"flavor {fq.name}/{res}: lendingLimit < 0")
                if (q.lending_limit is not None and not cq.cohort):
                    errs.append(
                        f"flavor {fq.name}/{res}: lendingLimit requires a "
                        "cohort")
                if (q.borrowing_limit is not None and not cq.cohort):
                    errs.append(
                        f"flavor {fq.name}/{res}: borrowingLimit requires "
                        "a cohort")
    p = cq.preemption
    if (p.borrow_within_cohort is not None
            and p.borrow_within_cohort.policy
            != BorrowWithinCohortPolicy.NEVER
            and p.reclaim_within_cohort == PreemptionPolicy.NEVER):
        errs.append(
            "preemption.borrowWithinCohort requires reclaimWithinCohort "
            "!= Never")
    return errs


def validate_cohort(cohort: Cohort) -> list[str]:
    errs = _name_errors(cohort.name, "cohort")
    if cohort.parent:
        from kueue_tpu.config import features
        if not features.enabled("HierarchicalCohorts"):
            errs.append("cohort: parentName requires the"
                        " HierarchicalCohorts feature gate")
        errs += _name_errors(cohort.parent, "cohort.parentName")
        if cohort.parent == cohort.name:
            errs.append("cohort: parentName must differ from name")
    return errs


def validate_resource_flavor(rf: ResourceFlavor) -> list[str]:
    errs = _name_errors(rf.name, "resourceFlavor")
    for k in rf.node_labels:
        if not k:
            errs.append("resourceFlavor: empty nodeLabel key")
    return errs


def default_workload(wl: Workload) -> None:
    """workload_webhook.go Default: drop minCounts when PartialAdmission
    is gated off; name a sole anonymous PodSet "main"."""
    from kueue_tpu.config import features

    if not features.enabled("PartialAdmission"):
        for ps in wl.pod_sets:
            ps.min_count = None
    if len(wl.pod_sets) == 1 and not wl.pod_sets[0].name:
        wl.pod_sets[0].name = "main"


def validate_workload(wl: Workload) -> list[str]:
    """workload_webhook.go: pod-set invariants."""
    errs = _name_errors(wl.name, "workload")
    if not wl.pod_sets:
        errs.append("workload: podSets must not be empty")
    if len(wl.pod_sets) > MAX_PODSETS:
        errs.append(f"workload: at most {MAX_PODSETS} podSets")
    names = set()
    for ps in wl.pod_sets:
        if ps.name in names:
            errs.append(f"workload: duplicate podSet name {ps.name}")
        names.add(ps.name)
        if ps.count < 1:
            errs.append(f"podSet {ps.name}: count must be >= 1")
        if ps.min_count is not None and not (
                0 < ps.min_count <= ps.count):
            errs.append(
                f"podSet {ps.name}: minCount must be in (0, count]")
        for res, q in ps.requests.items():
            if q < 0:
                errs.append(f"podSet {ps.name}: negative request {res}")
        tr = ps.topology_request
        if tr is not None and tr.slice_size is not None:
            if tr.slice_size <= 0:
                errs.append(f"podSet {ps.name}: sliceSize must be > 0")
            elif ps.count % tr.slice_size != 0:
                errs.append(
                    f"podSet {ps.name}: count must be a multiple of "
                    "sliceSize")
    return errs


def validate_workload_update(old: Workload, new: Workload) -> list[str]:
    """Admission immutability (workload_webhook.go): pod sets can't change
    while quota is reserved."""
    errs = []
    if old.has_quota_reservation:
        old_shape = [(ps.name, ps.count, tuple(sorted(ps.requests.items())))
                     for ps in old.pod_sets]
        new_shape = [(ps.name, ps.count, tuple(sorted(ps.requests.items())))
                     for ps in new.pod_sets]
        if old_shape != new_shape:
            errs.append(
                "workload: podSets are immutable while quota is reserved")
    return errs


def find_cohort_cycle(cohorts: list[Cohort]) -> Optional[list[str]]:
    """hierarchy/cycle.go:31 (HasCycle): returns a cycle path or None."""
    parent = {c.name: c.parent for c in cohorts}
    for start in parent:
        seen: list[str] = []
        cur: Optional[str] = start
        while cur is not None:
            if cur in seen:
                return seen[seen.index(cur):]
            seen.append(cur)
            cur = parent.get(cur)
    return None
