"""Priority booster: age-based priority boost for long-pending workloads.

Reference: cmd/experimental/kueue-priority-booster (pairs with the
PriorityBoost gate) — boosts the effective priority of workloads that
have waited too long so they stop starving."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class BoostPolicy:
    after_seconds: float = 600.0
    boost_per_interval: int = 1
    interval_seconds: float = 300.0
    max_boost: int = 100


class PriorityBooster:
    def __init__(self, engine, policy: BoostPolicy = None):
        self.engine = engine
        self.policy = policy or BoostPolicy()

    def reconcile(self) -> int:
        """Boost pending workloads by age; returns number boosted."""
        p = self.policy
        now = self.engine.clock
        boosted = 0
        for pcq in self.engine.queues.cluster_queues.values():
            infos = list(pcq.items.values()) + \
                list(pcq.inadmissible.values())
            for info in infos:
                wl = info.obj
                waited = now - wl.creation_time
                if waited < p.after_seconds:
                    continue
                intervals = int((waited - p.after_seconds)
                                // p.interval_seconds) + 1
                boost = min(p.max_boost,
                            intervals * p.boost_per_interval)
                if boost > wl.priority_boost:
                    wl.priority_boost = boost
                    pcq.push_or_update(info)  # re-heapify with new priority
                    boosted += 1
        return boosted
