"""Priority booster.

Reference: cmd/experimental/kueue-priority-booster
(pkg/controller/controller.go:44): once a workload has been ADMITTED for
timeSharingInterval, set a NEGATIVE priority boost so same-base-priority
pending workloads can preempt it under withinClusterQueue: LowerPriority
— cooperative time sharing. The boost clears when the workload is no
longer admitted (or leaves scope). ``maxWorkloadPriority`` bounds the
scope: higher-priority workloads are never demoted.

The rebuild keeps an additional age-based positive boost for
long-PENDING workloads (an anti-starvation mode the reference pairs with
via WorkloadPriorityClass updates)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kueue_tpu.api.types import PRIORITY_BOOST_ANNOTATION


@dataclass
class BoostPolicy:
    # Pending-age anti-starvation boost.
    after_seconds: float = 600.0
    boost_per_interval: int = 1
    interval_seconds: float = 300.0
    max_boost: int = 100


@dataclass
class TimeSharingPolicy:
    """controller.go:60 (PriorityBoostReconcilerOptions)."""

    time_sharing_interval_seconds: float = 3600.0
    negative_boost_value: int = -1
    # Workloads above this base priority are out of scope (never demoted).
    max_workload_priority: Optional[int] = None
    # Selector over workloads; the reserved key "queue" matches
    # queue_name (the reference uses a label selector).
    workload_selector: Optional[dict[str, str]] = None


class PriorityBooster:
    def __init__(self, engine, policy: BoostPolicy = None,
                 time_sharing: Optional[TimeSharingPolicy] = None):
        self.engine = engine
        self.policy = policy or BoostPolicy()
        self.time_sharing = time_sharing

    # -- pending-age anti-starvation boost --

    def reconcile(self) -> int:
        """Boost pending workloads by age; returns number changed."""
        p = self.policy
        now = self.engine.clock
        boosted = 0
        for pcq in self.engine.queues.cluster_queues.values():
            infos = list(pcq.items.values()) + \
                list(pcq.inadmissible.values())
            for info in infos:
                wl = info.obj
                waited = now - wl.creation_time
                if waited < p.after_seconds:
                    continue
                intervals = int((waited - p.after_seconds)
                                // p.interval_seconds) + 1
                boost = min(p.max_boost,
                            intervals * p.boost_per_interval)
                if boost > wl.priority_boost:
                    wl.priority_boost = boost
                    wl.annotations[PRIORITY_BOOST_ANNOTATION] = str(boost)
                    pcq.push_or_update(info)  # re-heapify
                    boosted += 1
        if self.time_sharing is not None:
            boosted += self.reconcile_time_sharing()
        return boosted

    # -- time-sharing negative boost (controller.go:118) --

    def _in_scope(self, wl) -> bool:
        ts = self.time_sharing
        if ts.max_workload_priority is not None \
                and wl.priority > ts.max_workload_priority:
            return False
        if ts.workload_selector:
            if ts.workload_selector.get("queue") not in (
                    None, wl.queue_name):
                return False
        return True

    def reconcile_time_sharing(self) -> int:
        """Demote workloads admitted past the time-sharing interval;
        clear the boost once they stop being admitted (computeBoost +
        clearBoostAnnotationIfPresent)."""
        ts = self.time_sharing
        now = self.engine.clock
        changed = 0
        from kueue_tpu.api.types import WorkloadConditionType

        for wl in self.engine.workloads.values():
            if wl.is_finished:
                continue
            if not wl.is_admitted or not self._in_scope(wl):
                # Out of scope / no longer admitted: a stale demotion is
                # cleared so the requeued workload competes at its base
                # priority (clearBoostAnnotationIfPresent).
                if wl.priority_boost < 0:
                    wl.priority_boost = 0
                    wl.annotations.pop(PRIORITY_BOOST_ANNOTATION, None)
                    if wl.active and not wl.is_admitted \
                            and not wl.is_finished:
                        # Re-heapify: the pending heap key baked in the
                        # demoted priority.
                        self.engine.queues.add_or_update_workload(wl)
                    changed += 1
                continue
            adm = wl.condition(WorkloadConditionType.ADMITTED)
            if adm is None \
                    or now - adm.last_transition_time \
                    < ts.time_sharing_interval_seconds:
                continue
            if wl.priority_boost != ts.negative_boost_value:
                wl.priority_boost = ts.negative_boost_value
                wl.annotations[PRIORITY_BOOST_ANNOTATION] = \
                    str(ts.negative_boost_value)
                self.engine._event("PriorityBoostSet", wl.key,
                                   detail=str(ts.negative_boost_value))
                changed += 1
        return changed
