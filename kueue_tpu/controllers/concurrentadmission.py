"""Concurrent admission (KEP 8691): evaluate a job against several
ClusterQueues at once via per-CQ Workload variants; the most favorable
admitted variant wins and the siblings are cleaned up.

Reference: pkg/controller/concurrentadmission + pkg/workload/
concurrentadmission + the scheduler hooks (scheduler.go:386-393,469-479).

Round-1 scope: variants fan out across LocalQueues; the first admitted
variant (by candidate-list preference order on ties within a cycle) wins;
pending siblings are withdrawn. Migration of an already-admitted
less-favorable variant lands with orchestrated preemption in a later
round.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import Workload


@dataclass
class _VariantGroup:
    original: Workload
    candidates: list[str]  # LocalQueue names in preference order
    variants: dict[str, str] = field(default_factory=dict)  # lq -> wl key
    winner: Optional[str] = None


class ConcurrentAdmissionController:
    def __init__(self, engine):
        self.engine = engine
        self.groups: dict[str, _VariantGroup] = {}

    def submit_concurrent(self, wl: Workload,
                          candidate_queues: list[str]) -> list[Workload]:
        """Fan a workload out into per-queue variants."""
        group = _VariantGroup(original=wl, candidates=candidate_queues)
        created = []
        for lq in candidate_queues:
            variant = copy.deepcopy(wl)
            variant.name = f"{wl.name}-{lq}"
            variant.queue_name = lq
            variant.uid = ""
            variant.__post_init__()
            if self.engine.submit(variant):
                group.variants[lq] = variant.key
                created.append(variant)
        self.groups[wl.key] = group
        return created

    def reconcile(self) -> None:
        """Pick winners; withdraw losing variants."""
        for group in self.groups.values():
            if group.winner is not None:
                continue
            for lq in group.candidates:  # preference order
                key = group.variants.get(lq)
                if key is None:
                    continue
                variant = self.engine.workloads.get(key)
                if variant is not None and variant.is_admitted:
                    group.winner = lq
                    self._withdraw_losers(group)
                    break

    def winner_of(self, original_key: str) -> Optional[Workload]:
        group = self.groups.get(original_key)
        if group is None or group.winner is None:
            return None
        return self.engine.workloads.get(group.variants[group.winner])

    def _withdraw_losers(self, group: _VariantGroup) -> None:
        for lq, key in group.variants.items():
            if lq == group.winner:
                continue
            wl = self.engine.workloads.get(key)
            if wl is None:
                continue
            if wl.has_quota_reservation:
                self.engine.evict(wl, "ConcurrentAdmissionLost",
                                  requeue=False)
            wl.active = False
            self.engine.queues.delete_workload(wl)
