"""Concurrent admission (KEP 8691): evaluate a workload on several
ResourceFlavors at once via per-flavor Workload variants; migration
policy decides whether a later, more-preferred admission replaces an
earlier, less-preferred one.

Reference: pkg/controller/concurrentadmission/controller.go — variants
are clones of the parent pinned to one flavor
(WorkloadAllowedResourceFlavorAnnotation, :356 generateVariant), carry a
closed ConcurrentAdmission preemption gate (ungated one at a time with a
5-minute timeout), and are activated/deactivated per the CQ's migration
mode (:485-610):

  * RetainFirstAdmission — the first admitted variant wins; every other
    variant is deactivated.
  * TryPreferredFlavors — variants on more-preferred flavors keep
    running even after a less-preferred variant admits; when one of
    them admits, the less-preferred admitted variant is evicted and
    deactivated (the migration), optionally bounded below by
    lastAcceptableFlavorName.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import Workload, WorkloadConditionType

CONCURRENT_ADMISSION_GATE = "kueue.x-k8s.io/concurrent-admission"
PREEMPTION_TIMEOUT = 300.0  # controller.go:68 preemptionTimeout

RETAIN_FIRST_ADMISSION = "RetainFirstAdmission"
TRY_PREFERRED_FLAVORS = "TryPreferredFlavors"


@dataclass
class ConcurrentAdmissionPolicy:
    """clusterqueue_types.go ConcurrentAdmissionPolicy (migration)."""

    mode: str = RETAIN_FIRST_ADMISSION
    last_acceptable_flavor: Optional[str] = None


@dataclass
class _VariantGroup:
    parent: Workload
    cluster_queue: str
    policy: ConcurrentAdmissionPolicy
    flavor_order: list[str]  # preference order (CQ resource-group order)
    variants: dict[str, str] = field(default_factory=dict)  # flavor -> key
    done: bool = False


class ConcurrentAdmissionController:
    def __init__(self, engine):
        self.engine = engine
        self.groups: dict[str, _VariantGroup] = {}

    # -- fan-out (controller.go:307 createVariants) --

    def submit_concurrent(self, wl: Workload, queue_name: str,
                          policy: ConcurrentAdmissionPolicy = None
                          ) -> list[Workload]:
        """Create one preemption-gated variant per CQ flavor, pinned to
        that flavor. The parent itself is never queued — it tracks the
        family (ConcurrentAdmissionParentLabelKey relationship)."""
        eng = self.engine
        if wl.key in self.groups:
            # Idempotent re-submit: the existing fan-out keeps tracking
            # its (possibly admitted) variants.
            group = self.groups[wl.key]
            return [eng.workloads[k] for k in group.variants.values()
                    if k in eng.workloads]
        lq = eng.queues.local_queues.get(f"{wl.namespace}/{queue_name}")
        cq = (eng.cache.cluster_queues.get(lq.cluster_queue)
              if lq is not None else None)
        if cq is None:
            return []
        flavor_order: list[str] = []
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                if fq.name not in flavor_order:
                    flavor_order.append(fq.name)
        group = _VariantGroup(
            parent=wl, cluster_queue=cq.name,
            policy=policy or ConcurrentAdmissionPolicy(),
            flavor_order=flavor_order)
        created = []
        for flavor in flavor_order:
            variant = copy.deepcopy(wl)
            variant.name = f"{wl.name}-{flavor}"
            variant.queue_name = queue_name
            variant.allowed_resource_flavor = flavor
            variant.preemption_gates = ()
            variant.ensure_preemption_gate(CONCURRENT_ADMISSION_GATE)
            variant.uid = ""
            variant.__post_init__()
            if eng.submit(variant):
                group.variants[flavor] = variant.key
                created.append(variant)
                eng._event("CreatedVariant", variant.key,
                           cluster_queue=cq.name, detail=flavor)
        self.groups[wl.key] = group
        return created

    # -- the reconcile pass (controller.go:188) --

    def reconcile(self) -> None:
        for group in self.groups.values():
            if group.done:
                continue
            self._sync_group(group)

    def _order(self, group: _VariantGroup, flavor: str) -> int:
        try:
            return group.flavor_order.index(flavor)
        except ValueError:
            return len(group.flavor_order)

    def _admitted_variant(self, group: _VariantGroup
                          ) -> Optional[tuple[str, Workload]]:
        """The most-preferred admitted variant (getAdmittedVariant over
        the sorted family)."""
        best = None
        for flavor, key in group.variants.items():
            wl = self.engine.workloads.get(key)
            if wl is not None and wl.is_admitted:
                if best is None or self._order(group, flavor) \
                        < self._order(group, best[0]):
                    best = (flavor, wl)
        return best

    def _sync_group(self, group: _VariantGroup) -> None:
        eng = self.engine
        if not group.parent.active:
            self._deactivate(group, lambda f, wl: True,
                             "parent not active")
            group.done = True
            return
        admitted = self._admitted_variant(group)
        if admitted is None:
            self._maybe_ungate(group)
            return
        adm_flavor, adm_wl = admitted
        mode = group.policy.mode
        if mode == RETAIN_FIRST_ADMISSION:
            self._deactivate(
                group, lambda f, wl: wl.key != adm_wl.key,
                f"RetainFirstAdmission: variant {adm_wl.name} admitted")
            group.done = True
            return
        # TryPreferredFlavors (controller.go:519-553): kill variants less
        # preferred than the admitted one (and anything below the
        # lastAcceptableFlavor); keep more-preferred ones racing. The
        # admitted variant itself is MIGRATED AWAY FROM when a
        # more-preferred variant admits — it matches the "less preferred
        # than admitted" predicate of that later pass.
        last = group.policy.last_acceptable_flavor
        if last is not None:
            self._deactivate(
                group,
                lambda f, wl: (wl.key != adm_wl.key and self._order(
                    group, f) > self._order(group, last)),
                f"below lastAcceptableFlavor {last}")
        self._deactivate(
            group,
            lambda f, wl: self._order(group, f) > self._order(
                group, adm_flavor) and wl.key != adm_wl.key,
            f"lower preference than admitted variant {adm_wl.name}")
        if self._order(group, adm_flavor) == 0:
            group.done = True  # best possible flavor admitted
            return
        self._maybe_ungate(group)

    # -- gate rotation (ReasonPreemptionUngatedVariant) --

    def _maybe_ungate(self, group: _VariantGroup) -> None:
        """Open one variant's preemption gate at a time, most preferred
        flavor first, rotating on PREEMPTION_TIMEOUT like MultiKueue's
        orchestrated preemption."""
        now = self.engine.clock
        previous_open = None
        stale: list[Workload] = []
        candidate = None
        for flavor in group.flavor_order:
            key = group.variants.get(flavor)
            wl = self.engine.workloads.get(key) if key else None
            if wl is None or not wl.active or wl.is_finished:
                continue
            opened = wl.status.open_preemption_gates.get(
                CONCURRENT_ADMISSION_GATE)
            if opened is not None:
                stale.append(wl)
                if previous_open is None or opened > previous_open:
                    previous_open = opened
                continue
            cond = wl.condition(
                WorkloadConditionType.BLOCKED_ON_PREEMPTION_GATES)
            if cond is None or not cond.status:
                continue
            if candidate is None:
                candidate = wl
        if candidate is None:
            return
        if previous_open is not None \
                and now - previous_open < PREEMPTION_TIMEOUT:
            return
        # Rotation RE-CLOSES the stalled gate so only one variant holds
        # preemption rights at a time (unlike MultiKueue's cross-cluster
        # gates, these are same-engine and safely closable).
        for wl in stale:
            wl.status.open_preemption_gates.pop(
                CONCURRENT_ADMISSION_GATE, None)
        candidate.open_preemption_gate(CONCURRENT_ADMISSION_GATE, now)
        self.engine._event("PreemptionUngatedVariant", candidate.key)
        self.engine.queues.queue_inadmissible_workloads()

    # -- helpers --

    def _deactivate(self, group: _VariantGroup, predicate,
                    message: str) -> None:
        """deactivateMatchingVariants (controller.go:469): deactivate +
        evict matching variants."""
        eng = self.engine
        for flavor, key in group.variants.items():
            wl = eng.workloads.get(key)
            if wl is None or not wl.active or wl.is_finished:
                continue
            if not predicate(flavor, wl):
                continue
            wl.active = False
            if wl.has_quota_reservation:
                eng.evict(wl, "ConcurrentAdmissionLost", requeue=False)
            eng.queues.delete_workload(wl)
            eng._event("DeactivatedVariant", wl.key, detail=message)

    def winner_of(self, parent_key: str) -> Optional[Workload]:
        group = self.groups.get(parent_key)
        if group is None:
            return None
        best = self._admitted_variant(group)
        return best[1] if best is not None else None
