"""Importer: adopt pre-existing running workloads as admitted.

Reference: cmd/importer — mapping rules (mapping/mapping.go:48 Rule:
match pods by priorityClassName + labels -> LocalQueue, first match
wins, unmatched skip), check phase (validate queue mapping and flavor
assignment), import phase (create admitted Workloads without scheduling
them, admitWorkload pod/import.go:173)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import (
    Admission,
    PodSet,
    PodSetAssignmentStatus,
    Workload,
    WorkloadConditionType,
)


@dataclass
class MappingRule:
    """mapping.go:48 (Rule): labels + optional priority class ->
    LocalQueue; ``skip`` short-circuits (explicitly unmanaged pods)."""

    to_local_queue: str = ""
    match_labels: dict[str, str] = field(default_factory=dict)
    priority_class_name: str = ""
    skip: bool = False

    def matches(self, priority_class: str,
                labels: dict[str, str]) -> bool:
        if self.priority_class_name \
                and priority_class != self.priority_class_name:
            return False
        return all(labels.get(k) == v
                   for k, v in self.match_labels.items())


@dataclass
class MappingRules:
    """mapping.go:54 (Rules): ordered, first match wins."""

    rules: tuple[MappingRule, ...] = ()

    def queue_for(self, priority_class: str, labels: dict[str, str]
                  ) -> tuple[Optional[str], bool]:
        """Returns (queue name, matched); (None, True) = matched a skip
        rule (:56 QueueFor)."""
        for rule in self.rules:
            if rule.matches(priority_class, labels):
                return (None, True) if rule.skip \
                    else (rule.to_local_queue, True)
        return None, False

    @classmethod
    def for_label(cls, label: str) -> "MappingRules":
        """RulesForLabel (:78): the value of ``label`` IS the queue."""
        return cls(rules=(MappingRule(to_local_queue=f"${{{label}}}"),))


@dataclass
class PodToImport:
    """The pod-shaped input of the importer (cmd/importer/pod)."""

    name: str
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    priority_class_name: str = ""
    priority: int = 0
    requests: dict[str, int] = field(default_factory=dict)


def pods_to_workloads(pods: list[PodToImport], rules: MappingRules,
                      queue_label: Optional[str] = None
                      ) -> tuple[list[Workload], list[str]]:
    """The mapping pass: each managed pod becomes a one-pod Workload in
    its mapped LocalQueue; unmatched/skipped pods are reported."""
    out: list[Workload] = []
    skipped: list[str] = []
    for pod in pods:
        queue, matched = rules.queue_for(pod.priority_class_name,
                                         pod.labels)
        if matched and queue is not None and queue.startswith("${"):
            # RulesForLabel indirection: ${label-name}.
            queue = pod.labels.get(queue[2:-1])
        if not matched or queue is None:
            skipped.append(f"{pod.namespace}/{pod.name}")
            continue
        out.append(Workload(
            name=pod.name, namespace=pod.namespace, queue_name=queue,
            priority=pod.priority,
            priority_class_name=pod.priority_class_name or None,
            pod_sets=(PodSet("main", 1, dict(pod.requests)),)))
    return out, skipped


@dataclass
class ImportResult:
    imported: list[str] = field(default_factory=list)
    errors: dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.errors


def check(engine, workloads: list[Workload],
          flavor_mapping: dict[str, str]) -> ImportResult:
    """The dry-run check phase."""
    result = ImportResult()
    for wl in workloads:
        lq = engine.queues.local_queues.get(
            f"{wl.namespace}/{wl.queue_name}")
        if lq is None:
            result.errors[wl.key] = (
                f"no LocalQueue {wl.queue_name} in {wl.namespace}")
            continue
        cq = engine.cache.cluster_queues.get(lq.cluster_queue)
        if cq is None:
            result.errors[wl.key] = (
                f"LocalQueue {lq.name} points to missing ClusterQueue")
            continue
        for ps in wl.pod_sets:
            for res in ps.requests:
                flavor = flavor_mapping.get(res)
                if flavor is None:
                    result.errors[wl.key] = f"no flavor mapping for {res}"
                    break
                from kueue_tpu.api.types import FlavorResource
                if cq.quota_for(FlavorResource(flavor, res)).nominal == 0 \
                        and not any(
                            fq.name == flavor
                            for rg in cq.resource_groups
                            for fq in rg.flavors):
                    result.errors[wl.key] = (
                        f"flavor {flavor} not in ClusterQueue "
                        f"{cq.name}")
                    break
        result.imported.append(wl.key)
    return result


def import_workloads(engine, workloads: list[Workload],
                     flavor_mapping: dict[str, str]) -> ImportResult:
    """The import phase: admit directly (bypassing scheduling), matching
    the reference's adoption of already-running pods."""
    precheck = check(engine, workloads, flavor_mapping)
    if not precheck.ok:
        return precheck
    result = ImportResult()
    for wl in workloads:
        lq = engine.queues.local_queues[f"{wl.namespace}/{wl.queue_name}"]
        psas = []
        for ps in wl.pod_sets:
            flavors = {res: flavor_mapping[res] for res in ps.requests}
            usage = {res: q * ps.count for res, q in ps.requests.items()}
            psas.append(PodSetAssignmentStatus(
                name=ps.name, flavors=flavors, resource_usage=usage,
                count=ps.count))
        wl.status.admission = Admission(
            cluster_queue=lq.cluster_queue,
            pod_set_assignments=tuple(psas))
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                         reason="Imported", now=engine.clock)
        wl.set_condition(WorkloadConditionType.ADMITTED, True,
                         reason="Imported", now=engine.clock)
        engine.workloads[wl.key] = wl
        engine.cache.add_or_update_workload(wl)
        result.imported.append(wl.key)
    return result
