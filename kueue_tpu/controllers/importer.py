"""Importer: adopt pre-existing running workloads as admitted.

Reference: cmd/importer — check phase (validate queue mapping and flavor
assignment) + import phase (create admitted Workloads without scheduling
them)."""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.api.types import (
    Admission,
    PodSetAssignmentStatus,
    Workload,
    WorkloadConditionType,
)


@dataclass
class ImportResult:
    imported: list[str] = field(default_factory=list)
    errors: dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.errors


def check(engine, workloads: list[Workload],
          flavor_mapping: dict[str, str]) -> ImportResult:
    """The dry-run check phase."""
    result = ImportResult()
    for wl in workloads:
        lq = engine.queues.local_queues.get(
            f"{wl.namespace}/{wl.queue_name}")
        if lq is None:
            result.errors[wl.key] = (
                f"no LocalQueue {wl.queue_name} in {wl.namespace}")
            continue
        cq = engine.cache.cluster_queues.get(lq.cluster_queue)
        if cq is None:
            result.errors[wl.key] = (
                f"LocalQueue {lq.name} points to missing ClusterQueue")
            continue
        for ps in wl.pod_sets:
            for res in ps.requests:
                flavor = flavor_mapping.get(res)
                if flavor is None:
                    result.errors[wl.key] = f"no flavor mapping for {res}"
                    break
                from kueue_tpu.api.types import FlavorResource
                if cq.quota_for(FlavorResource(flavor, res)).nominal == 0 \
                        and not any(
                            fq.name == flavor
                            for rg in cq.resource_groups
                            for fq in rg.flavors):
                    result.errors[wl.key] = (
                        f"flavor {flavor} not in ClusterQueue "
                        f"{cq.name}")
                    break
        result.imported.append(wl.key)
    return result


def import_workloads(engine, workloads: list[Workload],
                     flavor_mapping: dict[str, str]) -> ImportResult:
    """The import phase: admit directly (bypassing scheduling), matching
    the reference's adoption of already-running pods."""
    precheck = check(engine, workloads, flavor_mapping)
    if not precheck.ok:
        return precheck
    result = ImportResult()
    for wl in workloads:
        lq = engine.queues.local_queues[f"{wl.namespace}/{wl.queue_name}"]
        psas = []
        for ps in wl.pod_sets:
            flavors = {res: flavor_mapping[res] for res in ps.requests}
            usage = {res: q * ps.count for res, q in ps.requests.items()}
            psas.append(PodSetAssignmentStatus(
                name=ps.name, flavors=flavors, resource_usage=usage,
                count=ps.count))
        wl.status.admission = Admission(
            cluster_queue=lq.cluster_queue,
            pod_set_assignments=tuple(psas))
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                         reason="Imported", now=engine.clock)
        wl.set_condition(WorkloadConditionType.ADMITTED, True,
                         reason="Imported", now=engine.clock)
        engine.workloads[wl.key] = wl
        engine.cache.add_or_update_workload(wl)
        result.imported.append(wl.key)
    return result
