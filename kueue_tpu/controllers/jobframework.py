"""Job integration framework: the job <-> Workload contract.

Reference: pkg/controller/jobframework — the GenericJob plugin interface
(interface.go:36), the generic reconciler (reconciler.go:286
ReconcileGenericJob) and the integration registry
(integrationmanager.go). Any job-like object type plugs in by
implementing GenericJob; the reconciler owns the Workload lifecycle:

  * ensure exactly one Workload per job (reconciler.go:399
    ensureOneWorkload), built from the job's pod sets;
  * when the Workload is admitted, start the job with the admission's
    per-PodSet node selectors / counts (RunWithPodSetsInfo);
  * when the Workload is evicted/preempted, stop the job and restore pod
    set info; when the job finishes, mark the Workload Finished.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional, Protocol, runtime_checkable

from kueue_tpu.api.types import (
    PodSet,
    Workload,
    WorkloadConditionType,
)


@dataclass
class PodSetInfo:
    """Injected per-PodSet scheduling directives (podset.PodSetInfo):
    node selectors from the assigned flavor + count from admission, plus
    labels/annotations/tolerations merged from admission-check
    PodSetUpdates (podset.FromUpdate + Merge, pkg/podset/podset.go)."""

    name: str
    count: int
    node_selector: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    tolerations: tuple = ()

    def merge_update(self, update) -> None:
        """podset.Merge: additive only — an already-present key with a
        different value is a conflict (the reference fails admission)."""
        for attr, pairs in (("node_selector", update.node_selector),
                            ("labels", update.labels),
                            ("annotations", update.annotations)):
            dst = getattr(self, attr)
            for k, v in pairs:
                if k in dst and dst[k] != v:
                    raise ValueError(
                        f"conflict for {attr} key {k} in pod set "
                        f"{self.name}")
                dst[k] = v
        self.tolerations = self.tolerations + tuple(update.tolerations)


@runtime_checkable
class GenericJob(Protocol):
    """interface.go:36 (GenericJob)."""

    name: str
    namespace: str
    queue_name: str

    def is_suspended(self) -> bool: ...

    def suspend(self) -> None: ...

    def run_with_pod_sets_info(self, infos: list[PodSetInfo]) -> None: ...

    def restore_pod_sets_info(self, infos: list[PodSetInfo]) -> None: ...

    def pod_sets(self) -> list[PodSet]: ...

    def is_active(self) -> bool: ...

    def finished(self) -> tuple[bool, bool]:
        """Returns (finished, success)."""
        ...

    @property
    def key(self) -> str: ...


@dataclass
class BatchJob:
    """The batch/v1 Job adapter (pkg/controller/jobs/job/)."""

    name: str
    namespace: str = "default"
    queue_name: str = ""
    parallelism: int = 1
    completions: Optional[int] = None
    requests: dict[str, int] = field(default_factory=dict)  # per pod
    priority: int = 0
    min_parallelism: Optional[int] = None  # partial admission
    node_selector: dict[str, str] = field(default_factory=dict)
    suspended: bool = True
    active_pods: int = 0
    succeeded: int = 0
    failed: int = 0
    injected_info: Optional[list[PodSetInfo]] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.active_pods = 0

    def run_with_pod_sets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected_info = infos
        self.suspended = False
        self.active_pods = infos[0].count if infos else self.parallelism

    def restore_pod_sets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected_info = None

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(
            name="main", count=self.parallelism,
            requests=dict(self.requests),
            min_count=self.min_parallelism,
            node_selector=dict(self.node_selector))]

    def is_active(self) -> bool:
        return self.active_pods > 0

    def reclaimable_pods(self) -> dict[str, int]:
        """JobWithReclaimablePods (jobs/job/job_controller.go:213):
        reclaim only the parallelism the job can no longer use — while
        remaining completions >= parallelism every finished pod is
        replaced, so nothing is reclaimable."""
        if self.parallelism == 1 or self.succeeded == 0:
            return {}
        target = self.completions if self.completions is not None \
            else self.parallelism
        remaining = target - self.succeeded
        if remaining >= self.parallelism:
            return {}
        return {"main": self.parallelism - remaining}

    def finished(self) -> tuple[bool, bool]:
        target = self.completions if self.completions is not None \
            else self.parallelism
        if self.succeeded >= target:
            return True, True
        if self.failed > 0:
            return True, False
        return False, False


@dataclass
class JobSetJob:
    """A JobSet-style multi-pod-set gang job
    (pkg/controller/jobs/jobset/)."""

    name: str
    namespace: str = "default"
    queue_name: str = ""
    # replicated jobs: list of (name, replicas, per-pod requests, topology)
    replicated_jobs: list = field(default_factory=list)
    priority: int = 0
    suspended: bool = True
    active: bool = False
    done: bool = False
    success: bool = False
    injected_info: Optional[list[PodSetInfo]] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.active = False

    def run_with_pod_sets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected_info = infos
        self.suspended = False
        self.active = True

    def restore_pod_sets_info(self, infos) -> None:
        self.injected_info = None

    def pod_sets(self) -> list[PodSet]:
        out = []
        for rj in self.replicated_jobs:
            name, replicas, requests = rj[0], rj[1], rj[2]
            topology = rj[3] if len(rj) > 3 else None
            out.append(PodSet(name=name, count=replicas,
                              requests=dict(requests),
                              topology_request=topology))
        return out

    def is_active(self) -> bool:
        return self.active

    def finished(self) -> tuple[bool, bool]:
        return self.done, self.success


class IntegrationManager:
    """integrationmanager.go: the registry of enabled integrations."""

    def __init__(self) -> None:
        self._types: dict[str, type] = {}

    def register(self, kind: str, job_type: type) -> None:
        self._types[kind] = job_type

    def kind_of(self, job) -> Optional[str]:
        for kind, t in self._types.items():
            if isinstance(job, t):
                return kind
        return None

    def kinds(self) -> list[str]:
        return sorted(self._types)


DEFAULT_INTEGRATIONS = IntegrationManager()
DEFAULT_INTEGRATIONS.register("batch/job", BatchJob)
DEFAULT_INTEGRATIONS.register("jobset.x-k8s.io/jobset", JobSetJob)

_wl_suffix = itertools.count(1)


class JobReconciler:
    """reconciler.go:286 (ReconcileGenericJob), driven by the engine."""

    def __init__(self, engine, integrations: IntegrationManager = None,
                 manage_jobs_without_queue_name: bool = False,
                 webhooks=None, managed_namespace_selector=None):
        """``webhooks``: an optional webhooks.jobwebhooks.JobWebhookRegistry
        — when set, create_job/update_job run the per-framework
        defaulting + validation layer first (the admission webhook in
        front of the reconciler). ``managed_namespace_selector``: an
        optional namespace -> bool predicate
        (managedJobsNamespaceSelector, reconciler.go:323)."""
        self.engine = engine
        self.integrations = integrations or DEFAULT_INTEGRATIONS
        self.manage_all = manage_jobs_without_queue_name
        self.managed_namespace_selector = managed_namespace_selector
        self.webhooks = webhooks
        self.jobs: dict[str, GenericJob] = {}
        self.job_to_workload: dict[str, str] = {}
        self.workload_to_job: dict[str, str] = {}
        engine.on_admit = self._chain(engine.on_admit, self._on_admit)

    @staticmethod
    def _chain(prev, new):
        if prev is None:
            return new

        def both(*a, **k):
            prev(*a, **k)
            new(*a, **k)
        return both

    # -- the job-side reconcile loop --

    def create_job(self, job: GenericJob) -> list[str]:
        """Returns webhook validation errors; on any, the job is
        rejected (not registered), like an admission-webhook denial."""
        from kueue_tpu.config import features
        if (type(job).__name__ == "SparkApplicationJob"
                and not features.enabled("SparkApplicationIntegration")):
            # kube_features.go SparkApplicationIntegration: the Spark
            # adapter is gated off -> the job is not managed.
            self.engine._event(
                "JobRejected", job.key,
                detail="SparkApplicationIntegration gate disabled")
            return ["SparkApplicationIntegration feature gate disabled"]
        if self.webhooks is not None:
            errs = self.webhooks.admit_create(job)
            if errs:
                self.engine._event("JobRejected", job.key,
                                   detail="; ".join(errs))
                return errs
        self.jobs[job.key] = job
        self.reconcile(job)
        return []

    def update_job(self, job: GenericJob) -> list[str]:
        """Webhook-validated replacement of a registered job object."""
        old = self.jobs.get(job.key)
        if old is None:
            return self.create_job(job)
        if self.webhooks is not None:
            errs = self.webhooks.admit_update(old, job)
            if errs:
                self.engine._event("JobUpdateRejected", job.key,
                                   detail="; ".join(errs))
                return errs
        self.jobs[job.key] = job
        # A (suspended-only, webhook-enforced) queue move must follow
        # through to the pending Workload (reconciler.go queue-name
        # update handling), or the job and its workload diverge.
        wl_key = self.job_to_workload.get(job.key)
        if wl_key and old.queue_name != job.queue_name:
            wl = self.engine.workloads.get(wl_key)
            if wl is not None and not wl.is_finished:
                if wl.has_quota_reservation:
                    # A reserved/admitted workload must release its old
                    # CQ's quota before re-queueing elsewhere — pushing
                    # it pending while still assumed would double-admit.
                    self.engine.evict(wl, "QueueChanged", requeue=False)
                self.engine.queues.delete_workload(wl)
                wl.queue_name = job.queue_name
                self.engine.queues.add_or_update_workload(wl)
        self.reconcile(job)
        return []

    def delete_job(self, job_key: str) -> None:
        job = self.jobs.pop(job_key, None)
        wl_key = self.job_to_workload.pop(job_key, None)
        if wl_key:
            self.workload_to_job.pop(wl_key, None)
            wl = self.engine.workloads.get(wl_key)
            if wl is not None and not wl.is_finished:
                self.engine.finish(wl_key)
        if job is not None and getattr(job, "finalize", None) is not None:
            job.finalize()  # strip per-pod finalizers (:577)

    def reconcile(self, job: GenericJob) -> None:
        """One ReconcileGenericJob pass."""
        if not job.queue_name and not self.manage_all:
            return  # queue-name management gating (reconciler.go:313-377)
        if (self.managed_namespace_selector is not None
                and not self.managed_namespace_selector(job.namespace)):
            # With ManagedJobsNamespaceSelectorAlwaysRespected (default)
            # the selector gates even jobs that name a queue; with the
            # gate off, an explicit queue-name opts the job in anyway.
            from kueue_tpu.config import features
            if (features.enabled(
                    "ManagedJobsNamespaceSelectorAlwaysRespected")
                    or not job.queue_name):
                return
        if (getattr(job, "complete", None) is not None
                and not job.complete()
                and self.job_to_workload.get(job.key) is None):
            # ComposableJob: wait for the whole group to exist before
            # CREATING the Workload; an existing group keeps reconciling
            # through member failures (replacement-pod flow).
            return
        if getattr(job, "hold_at_zero", False):
            # Serving jobs (StatefulSet): scale-to-zero releases the
            # reservation with reason OnHold instead of finishing or
            # requeueing (statefulset_reconciler.go:223-264); scaling
            # back up clears the hold below.
            total = sum(ps.count for ps in job.pod_sets())
            wl_key = self.job_to_workload.get(job.key)
            if total == 0:
                if wl_key is not None:
                    self.engine.hold_workload(
                        wl_key, "scaled to zero; workload on hold")
                return
            if wl_key is not None:
                wl_held = self.engine.workloads.get(wl_key)
                if wl_held is not None and \
                        self.engine.is_on_hold(wl_held):
                    self.engine.clear_hold(wl_key)
        wl = self._ensure_one_workload(job)
        if wl is None:
            return
        # Pod-group housekeeping (pod_controller.go): trim excess
        # members and surface the replacement-pods signal.
        if getattr(job, "sync_excess", None) is not None:
            for pod in job.sync_excess():
                self.engine._event("ExcessPodRemoved", wl.key,
                                   detail=pod.key)
        if getattr(job, "custom_workload_conditions", None) is not None:
            for ctype, status, reason in job.custom_workload_conditions(
                    self.engine.clock):
                prev = wl.condition(ctype)
                if prev is None and not status:
                    continue  # never set a fresh False condition
                if prev is None or prev.status != status:
                    wl.set_condition(ctype, status, reason=reason,
                                     now=self.engine.clock)
        finished, success = job.finished()
        if finished and not wl.is_finished:
            # workloadfinish.Finish (reconciler.go:453-465).
            wl.set_condition(
                WorkloadConditionType.FINISHED, True,
                reason="Succeeded" if success else "Failed",
                now=self.engine.clock)
            self.engine.finish(wl.key)
            if getattr(job, "finalize", None) is not None:
                job.finalize()  # strip per-pod finalizers (:577)
            return
        if wl.is_admitted and job.is_suspended():
            self._start_job(job, wl)
        elif not wl.is_admitted and not job.is_suspended():
            old_slice = wl.replaced_workload_slice
            old_wl = (self.engine.workloads.get(old_slice)
                      if old_slice is not None else None)
            if old_wl is not None and old_wl.is_admitted:
                # Elastic slice replacement pending: the OLD slice still
                # holds the quota and the pods keep running
                # (workloadslicing.go — scale never stops the job).
                pass
            else:
                # stopJob on eviction (reconciler.go:379-394).
                job.suspend()
                job.restore_pod_sets_info([])
        self._sync_reclaimable(job, wl)

    def _sync_reclaimable(self, job: GenericJob, wl: Workload) -> None:
        """JobWithReclaimablePods (interface.go): pods the job no longer
        needs release their quota share while the workload runs."""
        getter = getattr(job, "reclaimable_pods", None)
        if getter is None:
            return
        reclaimable = {k: v for k, v in getter().items() if v > 0}
        if reclaimable == wl.status.reclaimable_pods:
            return
        wl.status.reclaimable_pods = reclaimable
        if wl.status.admission is not None:
            self.engine.cache.add_or_update_workload(wl)
            self.engine._requeue_cohort_inadmissible(
                wl.status.admission.cluster_queue)

    def reconcile_all(self) -> None:
        for job in list(self.jobs.values()):
            self.reconcile(job)

    # -- internals --

    def _ensure_one_workload(self, job: GenericJob) -> Optional[Workload]:
        """reconciler.go:399 (ensureOneWorkload): the Workload must match
        the job's pod sets; replaced if the shape changed. A job carrying
        a prebuilt-workload reference (reconciler.go:915, the
        MultiKueue-remote path) adopts that Workload instead of creating
        one."""
        prebuilt = getattr(job, "prebuilt_workload_name", None)
        if prebuilt:
            key = f"{job.namespace}/{prebuilt}"
            wl = self.engine.workloads.get(key)
            if wl is None:
                return None  # ErrPrebuiltWorkloadNotFound: wait
            self.job_to_workload[job.key] = key
            self.workload_to_job[key] = job.key
            return wl
        wl_key = self.job_to_workload.get(job.key)
        pod_sets = job.pod_sets()
        replaced_slice = None
        if wl_key is not None:
            wl = self.engine.workloads.get(wl_key)
            if wl is not None and _pod_sets_match(wl, pod_sets):
                return wl
            if wl is not None:
                from kueue_tpu.config import features
                if (getattr(job, "elastic", False)
                        and features.enabled(
                            "ElasticJobsViaWorkloadSlices")
                        and wl.is_admitted and not wl.is_finished):
                    # Elastic scale of a RUNNING job: the replacement
                    # workload SLICE preempt-replaces the old one
                    # without stopping its pods (workloadslicing.go:46;
                    # the scheduler finishes the old slice when the
                    # replacement admits, scheduler.go:558).
                    replaced_slice = wl_key
                else:
                    # A re-scale before a pending slice admitted must
                    # keep pointing at the still-admitted predecessor:
                    # dropping the chain would leak its quota forever
                    # and suspend the running pods.
                    old_key = wl.replaced_workload_slice
                    if old_key is not None:
                        old = self.engine.workloads.get(old_key)
                        if old is not None and old.is_admitted \
                                and not old.is_finished:
                            replaced_slice = old_key
                    self.engine.finish(wl_key)
                    self.workload_to_job.pop(wl_key, None)
        wl = Workload(
            name=f"{job.name}-wl-{next(_wl_suffix)}",
            namespace=job.namespace,
            queue_name=job.queue_name,
            priority=getattr(job, "priority", 0),
            pod_sets=tuple(pod_sets),
            replaced_workload_slice=replaced_slice,
        )
        if not self.engine.submit(wl):
            return None
        self.job_to_workload[job.key] = wl.key
        self.workload_to_job[wl.key] = job.key
        return wl

    def _start_job(self, job: GenericJob, wl: Workload) -> None:
        """startJob -> RunWithPodSetsInfo (reconciler.go admitted path):
        inject node selectors of the assigned flavors + admitted counts,
        then merge each admission check's PodSetUpdates
        (reconciler.go:1606-1615). A conflicting update fails the start
        and evicts the workload, as the reference's admission error does."""
        infos = []
        flavors = self.engine.cache.resource_flavors
        for psa in wl.status.admission.pod_set_assignments:
            selector: dict[str, str] = {}
            for flavor_name in psa.flavors.values():
                rf = flavors.get(flavor_name)
                if rf is not None:
                    selector.update(rf.node_labels)
            infos.append(PodSetInfo(name=psa.name, count=psa.count,
                                    node_selector=selector))
        try:
            for check_name, updates in sorted(
                    wl.status.admission_check_updates.items()):
                for update in updates:
                    for info in infos:
                        if info.name == update.name:
                            info.merge_update(update)
                            break
        except ValueError as exc:
            self.engine._event("PodSetUpdateConflict", wl.key,
                               detail=str(exc))
            self.engine.evict(wl, "PodSetUpdateConflict", requeue=False)
            return
        job.run_with_pod_sets_info(infos)

    def _on_admit(self, wl: Workload, admission) -> None:
        job_key = self.workload_to_job.get(wl.key)
        if job_key and job_key in self.jobs:
            self.reconcile(self.jobs[job_key])


def _pod_sets_match(wl: Workload, pod_sets: list[PodSet]) -> bool:
    if len(wl.pod_sets) != len(pod_sets):
        return False
    for a, b in zip(wl.pod_sets, pod_sets):
        if (a.name, a.count, a.requests) != (b.name, b.count, b.requests):
            return False
    return True
