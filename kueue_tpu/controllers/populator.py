"""Populator: auto-create LocalQueues in namespaces matching a
ClusterQueue's namespace selector.

Reference: cmd/experimental/kueue-populator (pkg/controller/
controller.go:108 Reconcile, :218 ensureLocalQueueExists) — for every
(ClusterQueue, matching namespace) pair, ensure a LocalQueue exists,
named either a fixed name (LocalQueueNameModeFixed, default "default")
or after the ClusterQueue (LocalQueueNameModeAsClusterQueue)."""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api.types import LocalQueue

NAME_MODE_FIXED = "Fixed"
NAME_MODE_AS_CLUSTER_QUEUE = "AsClusterQueue"


class PopulatorController:
    def __init__(self, engine, local_queue_name: str = "default",
                 name_mode: str = NAME_MODE_FIXED,
                 namespace_selector: Optional[dict[str, str]] = None):
        self.engine = engine
        self.local_queue_name = local_queue_name
        self.name_mode = name_mode
        # Populator-level selector intersected with each CQ's own.
        self.namespace_selector = namespace_selector
        self.created: list[str] = []

    def _matches(self, selector: Optional[dict[str, str]],
                 labels: dict[str, str]) -> bool:
        if selector is None:
            return True
        return all(labels.get(k) == v for k, v in selector.items())

    def reconcile(self) -> list[str]:
        """One pass over (CQ, namespace) pairs (controller.go:108).
        Returns the LocalQueue keys created this pass."""
        eng = self.engine
        created = []
        for cq in eng.cache.cluster_queues.values():
            for namespace, labels in eng.namespace_labels.items():
                if not self._matches(self.namespace_selector, labels):
                    continue
                if not self._matches(cq.namespace_selector, labels):
                    continue
                name = (cq.name if self.name_mode
                        == NAME_MODE_AS_CLUSTER_QUEUE
                        else self.local_queue_name)
                key = f"{namespace}/{name}"
                if key in eng.queues.local_queues:
                    continue
                eng.create_local_queue(LocalQueue(
                    name=name, namespace=namespace,
                    cluster_queue=cq.name))
                created.append(key)
        self.created.extend(created)
        return created
