"""CQ/LQ status controllers and finished-object retention.

Reference: pkg/controller/core/clusterqueue_controller.go:505
(updateCqStatusIfChanged — flavorsReservation/flavorsUsage/pending/
reserving/admitted counts + the Active condition whose reasons come from
pkg/cache/scheduler/clusterqueue.go:300 inactiveReason),
localqueue_controller.go (the LocalQueue mirror), and the
objectRetentionPolicies sweep (workload_controller.go retention:
finished / deactivated-by-kueue workloads deleted after a grace period).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import StopPolicy


@dataclass
class QueueStatus:
    """The shared shape of ClusterQueueStatus / LocalQueueStatus
    (clusterqueue_types.go:369-392, localqueue_types.go)."""

    pending_workloads: int = 0
    reserving_workloads: int = 0
    admitted_workloads: int = 0
    # flavor -> resource -> total quantity
    flavors_reservation: dict[str, dict[str, int]] = field(
        default_factory=dict)
    flavors_usage: dict[str, dict[str, int]] = field(default_factory=dict)
    active: bool = True
    active_reason: str = "Ready"
    active_message: str = "Can admit new workloads"
    weighted_share: Optional[float] = None


@dataclass
class WorkloadRetentionPolicy:
    """configuration_types.go:656 (WorkloadRetentionPolicy), seconds."""

    after_finished: Optional[float] = None
    after_deactivated_by_kueue: Optional[float] = None


class StatusController:
    """Computes and publishes CQ/LQ statuses; owns the retention sweep."""

    def __init__(self, engine,
                 retention: Optional[WorkloadRetentionPolicy] = None,
                 attach: bool = True):
        """``attach=False`` builds a read-only view (used by the HTTP
        status endpoints) that must not install itself on the engine."""
        self.engine = engine
        self.retention = retention
        self.cq_statuses: dict[str, QueueStatus] = {}
        self.lq_statuses: dict[str, QueueStatus] = {}
        if attach:
            engine.status_controller = self

    # -- activeness (clusterqueue.go:300 inactiveReason) --

    def cq_active_condition(self, cq) -> tuple[bool, str, str]:
        """Delegates to the cache's single source of inactive reasons so
        the status surface can never disagree with what the scheduler
        actually excludes."""
        reasons = self.engine.cache.cq_inactive_reasons(cq)
        if reasons:
            return (False, reasons[0][0],
                    "Can't admit new workloads: "
                    + ", ".join(m for _, m in reasons))
        return True, "Ready", "Can admit new workloads"

    # -- status computation (clusterqueue_controller.go:505) --

    def cq_status(self, name: str, snap=None) -> Optional[QueueStatus]:
        eng = self.engine
        cq = eng.cache.cluster_queues.get(name)
        if cq is None:
            return None
        st = QueueStatus()
        pcq = eng.queues.cluster_queues.get(name)
        if pcq is not None:
            st.pending_workloads = len(pcq.items) + len(pcq.inadmissible)
        for key, info in eng.cache.workloads.items():
            if info.cluster_queue != name:
                continue
            wl = eng.workloads.get(key)
            admitted = wl is not None and wl.is_admitted
            st.reserving_workloads += 1
            st.admitted_workloads += 1 if admitted else 0
            for fr, v in info.usage().items():
                st.flavors_reservation.setdefault(
                    fr.flavor, {}).setdefault(fr.resource, 0)
                st.flavors_reservation[fr.flavor][fr.resource] += v
                if admitted:
                    st.flavors_usage.setdefault(
                        fr.flavor, {}).setdefault(fr.resource, 0)
                    st.flavors_usage[fr.flavor][fr.resource] += v
        st.active, st.active_reason, st.active_message = \
            self.cq_active_condition(cq)
        if cq.fair_sharing is not None:
            from kueue_tpu.cache.snapshot import dominant_resource_share

            if snap is None:
                snap = eng.cache.snapshot()
            node = snap.cluster_queues.get(name)
            if node is not None:
                drs = dominant_resource_share(node, None)
                # Same formula as the cluster_queue_weighted_share gauge
                # (engine.sync_resource_metrics) — the two surfaces must
                # agree.
                st.weighted_share = (drs.precise_weighted_share()
                                     if node.fair_weight
                                     else drs.unweighted_ratio)
        return st

    def lq_status(self, key: str) -> Optional[QueueStatus]:
        """localqueue_controller.go status: the LQ-scoped mirror."""
        eng = self.engine
        lq = eng.queues.local_queues.get(key)
        if lq is None:
            return None
        st = QueueStatus()
        cq = eng.cache.cluster_queues.get(lq.cluster_queue)
        if cq is None:
            st.active = False
            st.active_reason = "ClusterQueueDoesNotExist"
            st.active_message = "Can't submit new workloads to clusterQueue"
        else:
            ok, reason, _ = self.cq_active_condition(cq)
            if not ok:
                st.active = False
                st.active_reason = "ClusterQueueIsInactive"
                st.active_message = \
                    "Can't submit new workloads to clusterQueue"
            if lq.stop_policy != StopPolicy.NONE:
                st.active = False
                st.active_reason = "Stopped"
                st.active_message = "LocalQueue is stopped"
        pcq = eng.queues.cluster_queues.get(lq.cluster_queue)
        if pcq is not None:
            for info in list(pcq.items.values()) \
                    + list(pcq.inadmissible.values()):
                if f"{info.obj.namespace}/{info.obj.queue_name}" == key:
                    st.pending_workloads += 1
        for wkey, info in eng.cache.workloads.items():
            wl = eng.workloads.get(wkey)
            if wl is None or f"{wl.namespace}/{wl.queue_name}" != key:
                continue
            st.reserving_workloads += 1
            st.admitted_workloads += 1 if wl.is_admitted else 0
            for fr, v in info.usage().items():
                st.flavors_reservation.setdefault(
                    fr.flavor, {}).setdefault(fr.resource, 0)
                st.flavors_reservation[fr.flavor][fr.resource] += v
                if wl.is_admitted:
                    st.flavors_usage.setdefault(
                        fr.flavor, {}).setdefault(fr.resource, 0)
                    st.flavors_usage[fr.flavor][fr.resource] += v
        return st

    def reconcile_all(self) -> None:
        """Refresh every CQ/LQ status + the status gauges."""
        g = self.engine.registry.gauge
        g("cluster_queue_status").clear()
        g("local_queue_status").clear()
        # One snapshot shared across every CQ (snapshot construction is
        # the expensive step; N CQs must not cost N snapshots).
        snap = (self.engine.cache.snapshot()
                if any(cq.fair_sharing is not None for cq in
                       self.engine.cache.cluster_queues.values())
                else None)
        self.cq_statuses = {
            name: self.cq_status(name, snap=snap)
            for name in self.engine.cache.cluster_queues}
        for name, st in self.cq_statuses.items():
            g("cluster_queue_status").set(
                (name, "active" if st.active else "inactive"), 1)
        self.lq_statuses = {
            key: self.lq_status(key)
            for key in self.engine.queues.local_queues}
        for key, st in self.lq_statuses.items():
            g("local_queue_status").set(
                (key, "active" if st.active else "inactive"), 1)

    # -- retention sweep (objectRetentionPolicies) --

    def sweep_retention(self) -> list[str]:
        """Delete finished workloads past afterFinished and
        kueue-deactivated ones past afterDeactivatedByKueue
        (workload_controller.go retention handling). Returns deleted
        keys."""
        if self.retention is None:
            return []
        eng = self.engine
        deleted = []
        for key, wl in list(eng.workloads.items()):
            if wl.is_finished and self.retention.after_finished is not None:
                fin = wl.condition("Finished")
                if fin and eng.clock - fin.last_transition_time \
                        >= self.retention.after_finished:
                    deleted.append(key)
                    continue
            if (not wl.active and not wl.is_finished
                    and self.retention.after_deactivated_by_kueue
                    is not None):
                ev = wl.condition("Evicted")
                # The kueue-initiated deactivation reasons (the analog of
                # the reference's DeactivatedDueTo* family): each eviction
                # site that also flips active=False.
                if ev and ev.reason in (
                        "AdmissionCheckRejected", "RequeuingLimitExceeded",
                        "MaximumExecutionTimeExceeded") \
                        and eng.clock - ev.last_transition_time \
                        >= self.retention.after_deactivated_by_kueue:
                    deleted.append(key)
        for key in deleted:
            wl = eng.workloads.pop(key)
            eng.cache.delete_workload(key)
            eng.queues.delete_workload(wl)
            eng.unadmitted.remove(key)
            eng._evicted_once.discard(wl.uid)
            if eng.journal is not None:
                eng.journal.delete("workload", key, ts=eng.clock)
            eng._event("Deleted", key, detail="retention")
        return deleted
