"""MultiKueue per-framework job adapters.

Reference: pkg/controller/jobframework/multikueue.go (MultiKueueAdapter —
SyncJob / DeleteRemoteObject / IsJobManagedByKueue / GVK) and the
per-integration implementations (e.g.
pkg/controller/jobs/job/job_multikueue_adapter.go). The manager mirrors
the *job object* (not just the Workload) to the winning worker cluster:
the remote job carries a prebuilt-workload reference so the worker's
jobframework adopts the mirrored Workload instead of creating its own,
and the remote job's status is copied back to the manager's job on every
sync.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Optional, Protocol

MULTIKUEUE_ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


class MultiKueueAdapter(Protocol):
    """multikueue.go:31 (MultiKueueAdapter)."""

    def gvk(self) -> str: ...

    def is_job_managed_by_kueue(self, job) -> tuple[bool, str]: ...

    def sync_job(self, local_job, worker_reconciler, workload_name: str,
                 origin: str): ...

    def delete_remote_object(self, worker_reconciler, job_key: str) -> None: ...


@dataclass
class GenericJobAdapter:
    """A shape-generic adapter: works for any GenericJob whose dataclass
    can be deep-copied. Per-framework adapters subclass to refine the
    status sync (job_multikueue_adapter.go copies .Status verbatim
    guarded by start-suspension rules)."""

    kind: str = "batch/job"
    # Status fields copied remote -> local on sync.
    status_fields: tuple[str, ...] = ("active_pods", "succeeded", "failed")

    def gvk(self) -> str:
        return self.kind

    def is_job_managed_by_kueue(self, job) -> tuple[bool, str]:
        """job_multikueue_adapter.go IsJobManagedByKueue: the job must be
        queue-managed (or carry a prebuilt workload)."""
        if getattr(job, "queue_name", "") or getattr(
                job, "prebuilt_workload_name", None):
            return True, ""
        return False, "no queue name"

    def sync_job(self, local_job, worker_reconciler, workload_name: str,
                 origin: str):
        """SyncJob: create the remote job if absent (labeled with the
        origin + bound to the prebuilt mirrored Workload), else copy the
        remote status back onto the local job. Returns the remote job."""
        remote = worker_reconciler.jobs.get(local_job.key)
        if remote is None:
            remote = copy.deepcopy(local_job)
            remote.prebuilt_workload_name = workload_name
            remote.origin = origin
            # Remote jobs start unsuspended only via their own admission.
            if hasattr(remote, "suspended"):
                remote.suspended = True
            for f in self.status_fields:
                if hasattr(remote, f):
                    setattr(remote, f, 0 if isinstance(
                        getattr(remote, f), int) else None)
            worker_reconciler.create_job(remote)
            return remote
        # Status sync-back: the reference defers while the local job is
        # suspended (suspend-validation); here local status mirrors are
        # plain fields, safe to copy when running or finished.
        for f in self.status_fields:
            if hasattr(remote, f) and hasattr(local_job, f):
                setattr(local_job, f, getattr(remote, f))
        for flag in ("done", "success"):
            if hasattr(remote, flag) and hasattr(local_job, flag):
                setattr(local_job, flag, getattr(remote, flag))
        return remote

    def delete_remote_object(self, worker_reconciler, job_key: str) -> None:
        worker_reconciler.delete_job(job_key)


@dataclass
class BatchJobAdapter(GenericJobAdapter):
    """pkg/controller/jobs/job/job_multikueue_adapter.go."""

    kind: str = "batch/job"
    status_fields: tuple[str, ...] = ("active_pods", "succeeded", "failed")


@dataclass
class JobSetAdapter(GenericJobAdapter):
    """pkg/controller/jobs/jobset/jobset_multikueue_adapter.go."""

    kind: str = "jobset.x-k8s.io/jobset"
    status_fields: tuple[str, ...] = ("active",)


DEFAULT_ADAPTERS: dict[str, MultiKueueAdapter] = {
    "batch/job": BatchJobAdapter(),
    "jobset.x-k8s.io/jobset": JobSetAdapter(),
}

# Every other integration's jobs share the _BaseJob status shape
# (active/done/success), so the generic adapter with those fields covers
# them — the analog of the reference's per-framework
# <kind>_multikueue_adapter.go files, which differ only in the status
# stanza they copy.
for _kind in ("kubeflow.org/trainingjob", "kubeflow.org/trainjob",
              "kubeflow.org/mpijob", "ray.io/raycluster", "ray.io/rayjob",
              "ray.io/rayservice", "workload.codeflare.dev/appwrapper",
              "leaderworkerset.x-k8s.io/leaderworkerset", "core/pod",
              "core/podgroup", "apps/statefulset", "apps/deployment",
              "sparkoperator.k8s.io/sparkapplication", "apps/serving"):
    DEFAULT_ADAPTERS[_kind] = GenericJobAdapter(
        kind=_kind, status_fields=("active",))


def adapter_for(job, adapters: Optional[dict] = None,
                integrations=None) -> Optional[MultiKueueAdapter]:
    """Resolve the adapter for a job via the integration registry
    (multikueue.go GVK dispatch)."""
    table = adapters if adapters is not None else DEFAULT_ADAPTERS
    if integrations is None:
        return None
    kind = integrations.kind_of(job)
    return table.get(kind) if kind is not None else None
