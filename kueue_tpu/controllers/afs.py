"""Admission fair sharing (AFS): order workloads *within* a ClusterQueue
by their LocalQueue's exponentially-decayed historical usage, with
penalties applied at admission time.

Reference: pkg/util/admissionfairsharing + the queue-cache hooks
(pkg/cache/queue/manager.go:68, cluster_queue.go:208-218) and the
scheduler integration (scheduler.go:308-311,897,930).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kueue_tpu.api.types import FlavorResource, Workload
from kueue_tpu.config.api import AdmissionFairSharingConfig


@dataclass
class _LqUsage:
    value: float = 0.0
    last_update: float = 0.0


class AfsManager:
    """Per-LocalQueue decayed usage + admission penalties."""

    def __init__(self, engine, config: AdmissionFairSharingConfig = None):
        self.engine = engine
        self.config = config or AdmissionFairSharingConfig()
        self.usage: dict[str, _LqUsage] = {}  # lq key -> usage
        engine.afs = self
        # order within CQ by LQ usage (manager.go:68 hooks)
        engine.queues.lq_usage_fn = self.current_usage
        prev = engine.on_admit
        engine.on_admit = self._chain(prev, self._on_admit)

    @staticmethod
    def _chain(prev, new):
        if prev is None:
            return new

        def both(*a, **k):
            prev(*a, **k)
            new(*a, **k)
        return both

    def _decay(self, entry: _LqUsage, now: float) -> None:
        half_life = self.config.usage_half_life_seconds
        if half_life <= 0 or now <= entry.last_update:
            return
        dt = now - entry.last_update
        entry.value *= math.pow(0.5, dt / half_life)
        entry.last_update = now

    def current_usage(self, lq_key: str) -> float:
        entry = self.usage.get(lq_key)
        if entry is None:
            return 0.0
        self._decay(entry, self.engine.clock)
        return entry.value

    def _workload_weight(self, wl: Workload) -> float:
        total = 0.0
        for ps in wl.pod_sets:
            for res, q in ps.requests.items():
                w = self.config.resource_weights.get(res, 1.0)
                total += w * q * ps.count
        return total

    def _on_admit(self, wl: Workload, admission) -> None:
        """Entry penalty at admission (cluster_queue.go:208-218)."""
        lq_key = f"{wl.namespace}/{wl.queue_name}"
        entry = self.usage.setdefault(
            lq_key, _LqUsage(last_update=self.engine.clock))
        self._decay(entry, self.engine.clock)
        entry.value += self._workload_weight(wl)
