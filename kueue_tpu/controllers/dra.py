"""DRA (Dynamic Resource Allocation) mapping.

Reference: pkg/dra — DeviceClass -> extended-resource mapping
(extended_resource_cache.go:30, mapper.go) and per-workload ResourceClaim
counting (claims.go). Workloads request devices via claims; the mapper
translates them into the quota-space resource names the scheduler
understands."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class DeviceClass:
    """A device class exposed as an extended resource."""

    name: str  # e.g. "tpu.google.com/v5e"
    extended_resource: str  # e.g. "tpu-v5e"


@dataclass
class ResourceClaim:
    """A claim for N devices of a class (claims.go)."""

    device_class: str
    count: int = 1


class DeviceClassMapper:
    """extended_resource_cache.go + mapper.go."""

    def __init__(self) -> None:
        self.classes: dict[str, DeviceClass] = {}

    def add_device_class(self, dc: DeviceClass) -> None:
        self.classes[dc.name] = dc

    def delete_device_class(self, name: str) -> None:
        self.classes.pop(name, None)

    def resolve(self, claims: list[ResourceClaim]) -> dict[str, int]:
        """Claims -> extended-resource requests; raises on unknown class."""
        out: dict[str, int] = {}
        for claim in claims:
            dc = self.classes.get(claim.device_class)
            if dc is None:
                raise KeyError(
                    f"unknown device class {claim.device_class}")
            out[dc.extended_resource] = out.get(dc.extended_resource, 0) \
                + claim.count
        return out

    def apply_claims(self, pod_set, claims: list[ResourceClaim]):
        """Merge claim-derived requests into a pod set's requests."""
        resolved = self.resolve(claims)
        merged = dict(pod_set.requests)
        for res, count in resolved.items():
            merged[res] = merged.get(res, 0) + count
        from dataclasses import replace as _replace

        return _replace(pod_set, requests=merged)
