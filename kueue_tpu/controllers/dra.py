"""DRA (Dynamic Resource Allocation): claims, device classes, counters.

Reference: pkg/dra —
  * ``ResourceMapper`` (mapper.go:36): DeviceClass -> logical extended
    resource, populated from Configuration deviceClassMappings, with
    optional counter definitions (per-device counter charges);
  * claims (claims.go:58 countDevicesPerClass, :155
    GetResourceRequestsForResourceClaimTemplates): a pod's claim
    templates request N devices per class, optionally filtered by
    selectors — the counts become quota-space requests;
  * resource slices / pools (counters.go:224 poolInfo, :243
    groupSlicesByPool): drivers publish device inventories in slices; a
    pool is usable only when all its slices arrived;
  * counter charges (counters.go:36 GetCounterResourcesForWorkload):
    counter-based logical resources (e.g. gpu memory) are charged per
    matched device from the pool's counter sets;
  * workload integration (workload.go:625-645): claim-derived resources
    replace the raw extended resources in each PodSet's effective
    requests.

Device selectors come in two forms, as in the reference: plain
attribute-equality maps, and CEL expressions (claims.go:235
validateCELSelectors / :411 validateCELSelectorsAgainstDevices)
evaluated per device with ``device.driver`` / ``device.attributes`` /
``device.capacity`` in scope (utils/cel.py implements the expression
subset; compile errors reject the claim before quota admission, and an
insufficient match count is surfaced exactly like the reference's
"insufficient matching devices" error).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DeviceClass:
    """A device class exposed as an extended resource, optionally
    charging per-device counters (configapi DeviceClassMapping)."""

    name: str  # e.g. "tpu.google.com/v5e"
    extended_resource: str  # e.g. "tpu-v5e"
    # counter name -> per-device charge (deviceClassCounterConfig).
    counters: dict[str, int] = field(default_factory=dict)


@dataclass
class Device:
    """One device in a ResourceSlice (resourcev1.Device)."""

    name: str
    attributes: dict[str, str] = field(default_factory=dict)
    # counter set: counter name -> capacity this device consumes.
    counters: dict[str, int] = field(default_factory=dict)
    driver: str = ""  # stamped from the slice for the CEL env

    def cel_env(self) -> dict:
        return {"device": {"driver": self.driver,
                           "attributes": dict(self.attributes),
                           "capacity": dict(self.counters)}}


def validate_cel_selectors(requests) -> list[str]:
    """claims.go:235 validateCELSelectors: compile every expression up
    front; syntax errors reject the claim before quota admission."""
    from kueue_tpu.utils import cel

    errs = []
    for i, req in enumerate(requests):
        for j, expr in enumerate(getattr(req, "cel_selectors", ()) or ()):
            try:
                cel.compile_cel(expr)
            except cel.CelCompileError as e:
                errs.append(f"devices.requests[{i}].selectors[{j}]: "
                            f"CEL compilation failed: {e}")
    return errs


def _device_matches(dev: Device, req: DeviceRequest) -> bool:
    """All attribute-equality AND all CEL selectors must hold; a CEL
    runtime error (missing key, type mismatch) means no-match for that
    device, the upstream evaluator's per-device error behavior."""
    if any(dev.attributes.get(k) != v for k, v in req.selectors.items()):
        return False
    if req.cel_selectors:
        from kueue_tpu.utils import cel

        env = dev.cel_env()
        for expr in req.cel_selectors:
            try:
                if not cel.evaluate_predicate(expr, env):
                    return False
            except cel.CelEvalError:
                # Upstream evaluates per device and an evaluation error
                # (missing attribute, bad regex, non-bool result) means
                # this device doesn't match.
                return False
    return True


@dataclass
class ResourceSlice:
    """A driver-published inventory shard (counters.go:243).
    ``name`` is the slice's object identity: re-publishing the same name
    upserts rather than duplicates."""

    driver: str
    pool: str
    pool_slice_count: int  # total slices the pool publishes
    devices: list[Device] = field(default_factory=list)
    name: str = ""


@dataclass
class DeviceRequest:
    """One request inside a claim template (claims.go:47)."""

    device_class: str
    count: int = 1
    # Attribute-equality selectors (the fast path).
    selectors: dict[str, str] = field(default_factory=dict)
    # CEL selector expressions, ALL of which must match a device
    # (resourcev1.DeviceSelector.CEL; claims.go:45 celDeviceRequest).
    cel_selectors: tuple[str, ...] = ()


@dataclass
class ResourceClaim:
    """A claim for devices (claims.go countDevicesPerClass input)."""

    device_class: str = ""
    count: int = 1
    requests: tuple[DeviceRequest, ...] = ()

    def device_requests(self) -> list[DeviceRequest]:
        if self.requests:
            return list(self.requests)
        return [DeviceRequest(self.device_class, self.count)]


class DeviceClassMapper:
    """mapper.go:36 (ResourceMapper) + the slice/pool inventory."""

    def __init__(self) -> None:
        self.classes: dict[str, DeviceClass] = {}
        # (driver, pool, slice name) -> slice: controller upserts
        # replace, never duplicate.
        self._slices: dict[tuple, ResourceSlice] = {}

    @property
    def slices(self) -> list[ResourceSlice]:
        return list(self._slices.values())

    # -- registry (PopulateFromConfiguration) --

    def add_device_class(self, dc: DeviceClass) -> None:
        self.classes[dc.name] = dc

    def delete_device_class(self, name: str) -> None:
        self.classes.pop(name, None)

    @classmethod
    def from_mappings(cls, mappings: list[dict]) -> "DeviceClassMapper":
        """mapper.go:65 PopulateFromConfiguration."""
        m = cls()
        for entry in mappings:
            m.add_device_class(DeviceClass(
                name=entry["name"],
                extended_resource=entry.get("logicalResourceName",
                                            entry["name"]),
                counters={k: int(v) for k, v in
                          (entry.get("counters") or {}).items()}))
        return m

    # -- inventory (groupSlicesByPool / poolInfo) --

    def add_resource_slice(self, s: ResourceSlice) -> None:
        for d in s.devices:
            if not d.driver:
                d.driver = s.driver
        if not s.name:
            # Anonymous slices get a collision-free generated identity
            # (a monotonic counter — dict length would reuse names
            # after deletes and clobber live inventory).
            self._anon_counter = getattr(self, "_anon_counter", 0) + 1
            s.name = f"anon-slice-{self._anon_counter}"
        self._slices[(s.driver, s.pool, s.name)] = s

    def delete_resource_slice(self, driver: str, pool: str,
                              name: str) -> None:
        self._slices.pop((driver, pool, name), None)

    def complete_pools(self, driver: Optional[str] = None
                       ) -> dict[str, list[Device]]:
        """counters.go:231 isComplete: a pool counts only when every
        published slice has arrived."""
        groups: dict[str, list[ResourceSlice]] = {}
        for s in self.slices:
            if driver is not None and s.driver != driver:
                continue
            groups.setdefault(f"{s.driver}/{s.pool}", []).append(s)
        out: dict[str, list[Device]] = {}
        for pool, slices in groups.items():
            if len(slices) >= slices[0].pool_slice_count:
                out[pool] = [d for s in slices for d in s.devices]
        return out

    # -- claim resolution --

    def resolve(self, claims: list[ResourceClaim]) -> dict[str, int]:
        """countDevicesPerClass -> extended-resource requests; raises on
        unmapped classes. Gated: kube_features.go KueueDRAIntegration
        (+ KueueDRAIntegrationExtendedResource for the mapping itself);
        with the gate off, claims are rejected rather than silently
        dropped (KueueDRARejectWorkloadsWhenDRADisabled semantics)."""
        from kueue_tpu.config import features
        if claims and not features.enabled("KueueDRAIntegration"):
            raise KeyError(
                "workload carries ResourceClaims but the"
                " KueueDRAIntegration feature gate is disabled")
        if claims and not features.enabled(
                "KueueDRAIntegrationExtendedResource"):
            raise KeyError(
                "extended-resource mapping disabled"
                " (KueueDRAIntegrationExtendedResource)")
        out: dict[str, int] = {}
        for claim in claims:
            for req in claim.device_requests():
                dc = self.classes.get(req.device_class)
                if dc is None:
                    raise KeyError(
                        f"unknown device class {req.device_class}")
                out[dc.extended_resource] = out.get(
                    dc.extended_resource, 0) + req.count
        return out

    def counter_resources(self, claims: list[ResourceClaim]
                          ) -> dict[str, int]:
        """counters.go:36 GetCounterResourcesForWorkload: charge
        counter-based logical resources for the devices each request
        would match, taken greedily from complete pools."""
        pools = self.complete_pools()
        matched: set[tuple[str, str]] = set()  # (pool, device name)
        charges: dict[str, int] = {}
        for claim in claims:
            for req in claim.device_requests():
                dc = self.classes.get(req.device_class)
                if dc is None:
                    raise KeyError(
                        f"unknown device class {req.device_class}")
                needed = req.count
                for pool, devices in pools.items():
                    for dev in devices:
                        if needed == 0:
                            break
                        if (pool, dev.name) in matched:
                            continue
                        if not _device_matches(dev, req):
                            continue
                        matched.add((pool, dev.name))
                        needed -= 1
                        for counter, per_dev in dc.counters.items():
                            cap = dev.counters.get(counter, per_dev)
                            charges[counter] = charges.get(counter, 0) \
                                + cap
                    if needed == 0:
                        break
                if needed > 0:
                    raise LookupError(
                        f"not enough devices for class "
                        f"{req.device_class}: {needed} short")
        return charges

    def validate_against_devices(self, claims: list[ResourceClaim]
                                 ) -> list[str]:
        """claims.go:411 validateCELSelectorsAgainstDevices: compile the
        selectors, count matching devices across complete pools, and
        report shortages so quota is never held by workloads whose pods
        can never be scheduled."""
        errs = []
        for claim in claims:
            errs.extend(validate_cel_selectors(claim.device_requests()))
        if errs:
            return errs
        pools = self.complete_pools()
        matched: set[tuple[str, str]] = set()
        for claim in claims:
            for i, req in enumerate(claim.device_requests()):
                # Selector-less requests still CONSUME devices from the
                # pools (counter_resources allocates greedily in claim
                # order), so they participate in the matched-set
                # accounting — skipping them would validate claims that
                # allocation must reject.
                dc = self.classes.get(req.device_class)
                if dc is None:
                    errs.append(f"unknown device class "
                                f"{req.device_class}")
                    continue
                count = 0
                for pool, devices in pools.items():
                    for dev in devices:
                        if (pool, dev.name) in matched:
                            continue
                        if _device_matches(dev, req):
                            matched.add((pool, dev.name))
                            count += 1
                            if count >= req.count:
                                break
                    if count >= req.count:
                        break
                if count < req.count:
                    errs.append(
                        f"insufficient matching devices for selector in "
                        f"DeviceClass {req.device_class}: {count} "
                        f"device(s) match in the cluster but "
                        f"{req.count} requested")
        return errs

    def apply_claims(self, pod_set, claims: list[ResourceClaim],
                     with_counters: bool = False):
        """workload.go:625-645: merge claim-derived requests into a pod
        set's requests, REPLACING any raw request for the mapped
        extended resources (replacedExtendedResources)."""
        resolved = self.resolve(claims)
        merged = {r: q for r, q in pod_set.requests.items()
                  if r not in resolved}
        for res, count in resolved.items():
            merged[res] = merged.get(res, 0) + count
        if with_counters:
            for counter, charge in self.counter_resources(claims).items():
                merged[counter] = merged.get(counter, 0) + charge
        from dataclasses import replace as _replace

        return _replace(pod_set, requests=merged)
