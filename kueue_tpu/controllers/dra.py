"""DRA (Dynamic Resource Allocation): claims, device classes, counters.

Reference: pkg/dra —
  * ``ResourceMapper`` (mapper.go:36): DeviceClass -> logical extended
    resource, populated from Configuration deviceClassMappings, with
    optional counter definitions (per-device counter charges);
  * claims (claims.go:58 countDevicesPerClass, :155
    GetResourceRequestsForResourceClaimTemplates): a pod's claim
    templates request N devices per class, optionally filtered by
    selectors — the counts become quota-space requests;
  * resource slices / pools (counters.go:224 poolInfo, :243
    groupSlicesByPool): drivers publish device inventories in slices; a
    pool is usable only when all its slices arrived;
  * counter charges (counters.go:36 GetCounterResourcesForWorkload):
    counter-based logical resources (e.g. gpu memory) are charged per
    matched device from the pool's counter sets;
  * workload integration (workload.go:625-645): claim-derived resources
    replace the raw extended resources in each PodSet's effective
    requests.

The reference matches devices with CEL expressions; the rebuild uses
plain attribute-equality selectors (CEL is a host-language detail, not
framework behavior).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DeviceClass:
    """A device class exposed as an extended resource, optionally
    charging per-device counters (configapi DeviceClassMapping)."""

    name: str  # e.g. "tpu.google.com/v5e"
    extended_resource: str  # e.g. "tpu-v5e"
    # counter name -> per-device charge (deviceClassCounterConfig).
    counters: dict[str, int] = field(default_factory=dict)


@dataclass
class Device:
    """One device in a ResourceSlice (resourcev1.Device)."""

    name: str
    attributes: dict[str, str] = field(default_factory=dict)
    # counter set: counter name -> capacity this device consumes.
    counters: dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """A driver-published inventory shard (counters.go:243).
    ``name`` is the slice's object identity: re-publishing the same name
    upserts rather than duplicates."""

    driver: str
    pool: str
    pool_slice_count: int  # total slices the pool publishes
    devices: list[Device] = field(default_factory=list)
    name: str = ""


@dataclass
class DeviceRequest:
    """One request inside a claim template (claims.go:47)."""

    device_class: str
    count: int = 1
    # Attribute-equality selectors (the CEL analog).
    selectors: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceClaim:
    """A claim for devices (claims.go countDevicesPerClass input)."""

    device_class: str = ""
    count: int = 1
    requests: tuple[DeviceRequest, ...] = ()

    def device_requests(self) -> list[DeviceRequest]:
        if self.requests:
            return list(self.requests)
        return [DeviceRequest(self.device_class, self.count)]


class DeviceClassMapper:
    """mapper.go:36 (ResourceMapper) + the slice/pool inventory."""

    def __init__(self) -> None:
        self.classes: dict[str, DeviceClass] = {}
        # (driver, pool, slice name) -> slice: controller upserts
        # replace, never duplicate.
        self._slices: dict[tuple, ResourceSlice] = {}

    @property
    def slices(self) -> list[ResourceSlice]:
        return list(self._slices.values())

    # -- registry (PopulateFromConfiguration) --

    def add_device_class(self, dc: DeviceClass) -> None:
        self.classes[dc.name] = dc

    def delete_device_class(self, name: str) -> None:
        self.classes.pop(name, None)

    @classmethod
    def from_mappings(cls, mappings: list[dict]) -> "DeviceClassMapper":
        """mapper.go:65 PopulateFromConfiguration."""
        m = cls()
        for entry in mappings:
            m.add_device_class(DeviceClass(
                name=entry["name"],
                extended_resource=entry.get("logicalResourceName",
                                            entry["name"]),
                counters={k: int(v) for k, v in
                          (entry.get("counters") or {}).items()}))
        return m

    # -- inventory (groupSlicesByPool / poolInfo) --

    def add_resource_slice(self, s: ResourceSlice) -> None:
        if not s.name:
            # Anonymous slices get a collision-free generated identity
            # (a monotonic counter — dict length would reuse names
            # after deletes and clobber live inventory).
            self._anon_counter = getattr(self, "_anon_counter", 0) + 1
            s.name = f"anon-slice-{self._anon_counter}"
        self._slices[(s.driver, s.pool, s.name)] = s

    def delete_resource_slice(self, driver: str, pool: str,
                              name: str) -> None:
        self._slices.pop((driver, pool, name), None)

    def complete_pools(self, driver: Optional[str] = None
                       ) -> dict[str, list[Device]]:
        """counters.go:231 isComplete: a pool counts only when every
        published slice has arrived."""
        groups: dict[str, list[ResourceSlice]] = {}
        for s in self.slices:
            if driver is not None and s.driver != driver:
                continue
            groups.setdefault(f"{s.driver}/{s.pool}", []).append(s)
        out: dict[str, list[Device]] = {}
        for pool, slices in groups.items():
            if len(slices) >= slices[0].pool_slice_count:
                out[pool] = [d for s in slices for d in s.devices]
        return out

    # -- claim resolution --

    def resolve(self, claims: list[ResourceClaim]) -> dict[str, int]:
        """countDevicesPerClass -> extended-resource requests; raises on
        unmapped classes. Gated: kube_features.go KueueDRAIntegration
        (+ KueueDRAIntegrationExtendedResource for the mapping itself);
        with the gate off, claims are rejected rather than silently
        dropped (KueueDRARejectWorkloadsWhenDRADisabled semantics)."""
        from kueue_tpu.config import features
        if claims and not features.enabled("KueueDRAIntegration"):
            raise KeyError(
                "workload carries ResourceClaims but the"
                " KueueDRAIntegration feature gate is disabled")
        if claims and not features.enabled(
                "KueueDRAIntegrationExtendedResource"):
            raise KeyError(
                "extended-resource mapping disabled"
                " (KueueDRAIntegrationExtendedResource)")
        out: dict[str, int] = {}
        for claim in claims:
            for req in claim.device_requests():
                dc = self.classes.get(req.device_class)
                if dc is None:
                    raise KeyError(
                        f"unknown device class {req.device_class}")
                out[dc.extended_resource] = out.get(
                    dc.extended_resource, 0) + req.count
        return out

    def counter_resources(self, claims: list[ResourceClaim]
                          ) -> dict[str, int]:
        """counters.go:36 GetCounterResourcesForWorkload: charge
        counter-based logical resources for the devices each request
        would match, taken greedily from complete pools."""
        pools = self.complete_pools()
        matched: set[tuple[str, str]] = set()  # (pool, device name)
        charges: dict[str, int] = {}
        for claim in claims:
            for req in claim.device_requests():
                dc = self.classes.get(req.device_class)
                if dc is None:
                    raise KeyError(
                        f"unknown device class {req.device_class}")
                needed = req.count
                for pool, devices in pools.items():
                    for dev in devices:
                        if needed == 0:
                            break
                        if (pool, dev.name) in matched:
                            continue
                        if any(dev.attributes.get(k) != v
                               for k, v in req.selectors.items()):
                            continue
                        matched.add((pool, dev.name))
                        needed -= 1
                        for counter, per_dev in dc.counters.items():
                            cap = dev.counters.get(counter, per_dev)
                            charges[counter] = charges.get(counter, 0) \
                                + cap
                    if needed == 0:
                        break
                if needed > 0:
                    raise LookupError(
                        f"not enough devices for class "
                        f"{req.device_class}: {needed} short")
        return charges

    def apply_claims(self, pod_set, claims: list[ResourceClaim],
                     with_counters: bool = False):
        """workload.go:625-645: merge claim-derived requests into a pod
        set's requests, REPLACING any raw request for the mapped
        extended resources (replacedExtendedResources)."""
        resolved = self.resolve(claims)
        merged = {r: q for r, q in pod_set.requests.items()
                  if r not in resolved}
        for res, count in resolved.items():
            merged[res] = merged.get(res, 0) + count
        if with_counters:
            for counter, charge in self.counter_resources(claims).items():
                merged[counter] = merged.get(counter, 0) + charge
        from dataclasses import replace as _replace

        return _replace(pod_set, requests=merged)
