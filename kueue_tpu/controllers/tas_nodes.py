"""TAS node-health controller: detect failed nodes and trigger workload
re-placement.

Reference: pkg/controller/tas/node_controller.go — watches Nodes, and when
one becomes unfit (deleted, NotReady longer than a fixed window, tainted
with NoSchedule/NoExecute, or a workload pod on it terminates — gates
``TASReplaceNodeOnNodeTaints`` / ``TASReplaceNodeOnPodTermination`` /
``TASReplaceNodeNotReadyOverFixedTime``), records the node in the status
of every admitted TAS workload placed on it (``status.unhealthyNodes``,
workload_types.go:766) and pushes those workloads into the second-pass
queue. The scheduler's next pass runs the replacement algorithm
(tas_flavor_snapshot.go:747 findReplacementAssignment); with
``TASFailedNodeReplacementFailFast`` a failed replacement evicts instead
of retrying (scheduler.go:403,804-817).
"""

from __future__ import annotations

from dataclasses import dataclass

from kueue_tpu.config import features

NOT_READY_REPLACEMENT_WINDOW = 30.0  # nodeReplacementTimeout (seconds)


@dataclass
class _NodeHealth:
    ready: bool = True
    not_ready_since: float = 0.0
    tainted: bool = False


class NodeHealthController:
    """Feeds node failures into Engine.mark_node_unhealthy."""

    def __init__(self, engine):
        self.engine = engine
        self._health: dict[str, _NodeHealth] = {}

    # -- event intake (node_controller.go Reconcile) --

    def node_ready(self, name: str) -> None:
        self._health.pop(name, None)

    def node_not_ready(self, name: str, now: float) -> None:
        h = self._health.setdefault(name, _NodeHealth())
        if h.ready:
            h.ready = False
            h.not_ready_since = now

    def node_tainted(self, name: str) -> None:
        """NoSchedule/NoExecute taint added."""
        h = self._health.setdefault(name, _NodeHealth())
        h.tainted = True
        if features.enabled("TASReplaceNodeOnNodeTaints"):
            self.engine.mark_node_unhealthy(name, reason="NodeTainted")

    def node_deleted(self, name: str) -> None:
        self._health.pop(name, None)
        self.engine.mark_node_unhealthy(name, reason="NodeDeleted")

    def pod_terminated(self, node_name: str) -> None:
        """A workload pod on the node failed (e.g. device fault)."""
        if features.enabled("TASReplaceNodeOnPodTermination"):
            self.engine.mark_node_unhealthy(node_name,
                                            reason="PodTerminated")

    def tick(self, now: float) -> None:
        """NotReady-over-fixed-time detection
        (TASReplaceNodeNotReadyOverFixedTime)."""
        if not features.enabled("TASReplaceNodeNotReadyOverFixedTime"):
            return
        for name, h in list(self._health.items()):
            if not h.ready and \
                    now - h.not_ready_since >= NOT_READY_REPLACEMENT_WINDOW:
                self._health.pop(name, None)
                self.engine.mark_node_unhealthy(name,
                                                reason="NodeNotReady")
