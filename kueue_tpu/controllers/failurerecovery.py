"""Failure detection and recovery.

Reference: pkg/controller/tas/node_controller.go (unhealthy-node
detection), workload unhealthyNodes status (workload_types.go:766),
fail-fast eviction (scheduler.go:403,804-817), and the
FailureRecoveryPolicy controller (pkg/controller/failurerecovery): on
node failure, reschedule affected workloads — to a replacement domain,
a different flavor, or (MultiKueue) a different cluster.

Round-1 behavior: mark workloads with placements on failed nodes
unhealthy; recovery evicts + requeues them (the scheduler then finds a
new placement — possibly another flavor/cluster). In-place replacement
search lands with the TAS replacement path in a later round.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.api.types import WorkloadConditionType
from kueue_tpu.tas.snapshot import HOSTNAME_LABEL


@dataclass
class FailureRecoveryPolicy:
    """FailureRecoveryPolicy CRD equivalent."""

    name: str = "default"
    # evict & requeue on the same queue (other flavors/clusters are
    # naturally retried by the scheduler / MultiKueue).
    action: str = "Requeue"


class FailureRecoveryController:
    def __init__(self, engine, policy: FailureRecoveryPolicy = None):
        self.engine = engine
        self.policy = policy or FailureRecoveryPolicy()
        self.unhealthy_nodes: set[str] = set()

    def node_failed(self, node_name: str) -> list[str]:
        """Node health event (tas/node_controller.go). Returns affected
        workload keys."""
        self.unhealthy_nodes.add(node_name)
        node = self.engine.cache.nodes.get(node_name)
        if node is not None:
            node.ready = False
        affected = self._workloads_on_node(node_name)
        for key in affected:
            wl = self.engine.workloads.get(key)
            if wl is None or wl.is_finished:
                continue
            wl.set_condition(WorkloadConditionType.EVICTED, False,
                             reason="", now=self.engine.clock)
            self.engine.evict(wl, "NodeFailure")
        self.engine.queues.queue_inadmissible_workloads()
        return affected

    def node_recovered(self, node_name: str) -> None:
        self.unhealthy_nodes.discard(node_name)
        node = self.engine.cache.nodes.get(node_name)
        if node is not None:
            node.ready = True
        self.engine.queues.queue_inadmissible_workloads()

    def _workloads_on_node(self, node_name: str) -> list[str]:
        """Workloads whose topology assignment lands on the node (matched
        by the hostname level value)."""
        affected = []
        for key, info in list(self.engine.cache.workloads.items()):
            wl = self.engine.workloads.get(key)
            if wl is None or wl.status.admission is None:
                continue
            for psa in wl.status.admission.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is None:
                    continue
                if HOSTNAME_LABEL not in ta.levels:
                    continue
                idx = list(ta.levels).index(HOSTNAME_LABEL)
                if any(d.values[idx] == node_name for d in ta.domains):
                    affected.append(key)
                    break
        return affected
