"""Failure detection and recovery.

Reference: pkg/controller/tas/node_controller.go (unhealthy-node
detection), workload unhealthyNodes status (workload_types.go:766),
fail-fast eviction (scheduler.go:403,804-817), and the
FailureRecoveryPolicy controller (pkg/controller/failurerecovery): on
node failure, reschedule affected workloads — to a replacement domain,
a different flavor, or (MultiKueue) a different cluster.

Round-1 behavior: mark workloads with placements on failed nodes
unhealthy; recovery evicts + requeues them (the scheduler then finds a
new placement — possibly another flavor/cluster). In-place replacement
search lands with the TAS replacement path in a later round.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.api.types import WorkloadConditionType
from kueue_tpu.tas.snapshot import HOSTNAME_LABEL


@dataclass
class FailureRecoveryPolicy:
    """FailureRecoveryPolicy CRD equivalent.

    Actions:
      * "Replace" — in-place TAS node replacement first
        (tas_flavor_snapshot.go:747 via the engine's second pass);
        pods on healthy nodes keep running. Falls back to the
        second-pass retry/evict semantics when no replacement exists.
      * "Requeue" — evict affected workloads immediately; the
        scheduler's next pass finds a new placement (possibly another
        flavor, or another cluster under MultiKueue).
    ``max_failures`` bounds per-workload churn: a workload evicted for
    node failures more than this many times is deactivated
    (fail-fast, scheduler.go:804-817)."""

    name: str = "default"
    action: str = "Replace"
    max_failures: int = 0  # 0 = unbounded


class FailureRecoveryController:
    def __init__(self, engine, policy: FailureRecoveryPolicy = None):
        self.engine = engine
        self.policy = policy or FailureRecoveryPolicy()
        self.unhealthy_nodes: set[str] = set()
        self.failure_counts: dict[str, int] = {}

    def node_failed(self, node_name: str) -> list[str]:
        """Node health event (tas/node_controller.go). Returns affected
        workload keys. Gated: kube_features.go FailureRecoveryPolicy."""
        from kueue_tpu.config import features
        if not features.enabled("FailureRecoveryPolicy"):
            return []
        self.unhealthy_nodes.add(node_name)
        self.engine.cache.set_node_ready(node_name, False)
        affected = self._workloads_on_node(node_name)
        over_limit = []
        for key in affected:
            self.failure_counts[key] = self.failure_counts.get(key, 0) + 1
            if self.policy.max_failures \
                    and self.failure_counts[key] > self.policy.max_failures:
                over_limit.append(key)
        if self.policy.action == "Replace":
            # In-place replacement path: annotate unhealthyNodes + arm
            # the second pass (engine.mark_node_unhealthy); keeps healthy
            # pods running while only the failed domains re-place.
            self.engine.mark_node_unhealthy(node_name, reason="NodeFailure")
        else:
            for key in affected:
                if key in over_limit:
                    continue  # deactivated below, under the right reason
                wl = self.engine.workloads.get(key)
                if wl is None or wl.is_finished:
                    continue
                wl.set_condition(WorkloadConditionType.EVICTED, False,
                                 reason="", now=self.engine.clock)
                self.engine.evict(wl, "NodeFailure")
        # Fail-fast deactivation for churners (scheduler.go:804-817).
        for key in over_limit:
            wl = self.engine.workloads.get(key)
            if wl is None or wl.is_finished:
                continue
            wl.active = False
            if wl.status.admission is not None or wl.has_quota_reservation:
                self.engine.evict(wl, "NodeFailureLimitExceeded",
                                  requeue=False)
            else:
                self.engine.queues.delete_workload(wl)
        self.engine.queues.queue_inadmissible_workloads()
        return affected

    def node_recovered(self, node_name: str) -> None:
        self.unhealthy_nodes.discard(node_name)
        self.engine.cache.set_node_ready(node_name, True)
        self.engine.queues.queue_inadmissible_workloads()

    def _workloads_on_node(self, node_name: str) -> list[str]:
        """Workloads whose topology assignment lands on the node (matched
        by the hostname level value)."""
        affected = []
        for key, info in list(self.engine.cache.workloads.items()):
            wl = self.engine.workloads.get(key)
            if wl is None or wl.status.admission is None:
                continue
            for psa in wl.status.admission.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is None:
                    continue
                if HOSTNAME_LABEL not in ta.levels:
                    continue
                idx = list(ta.levels).index(HOSTNAME_LABEL)
                if any(d.values[idx] == node_name for d in ta.domains):
                    affected.append(key)
                    break
        return affected
