"""MultiKueue: multi-cluster workload dispatch, modeled as an
AdmissionCheck on the manager cluster.

Reference: pkg/controller/admissionchecks/multikueue (workload.go:185
wlReconciler, multikueuecluster.go remote clients) and
pkg/controller/workloaddispatcher (AllAtOnce / Incremental strategies,
incrementaldispatcher.go:50).

Semantics:
  * a manager-side Workload that reserves quota and carries the MultiKueue
    check is mirrored to the nominated worker clusters;
  * the first worker to ADMIT the copy wins; the other copies are removed
    (wlGroup.RemoveRemoteObjects :159) and the manager check flips Ready
    with clusterName recorded;
  * remote finish/failure is synced back to the manager workload;
  * losing a worker cluster evicts the manager workloads placed there and
    requeues them (worker-lost timeout, multikueuecluster.go:98).

Worker "clusters" are Engine instances — the same way the reference tests
multi-cluster with two envtest apiservers (SURVEY.md §4).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import Workload, WorkloadConditionType
from kueue_tpu.controllers.admissionchecks import CheckState


# multikueue_types.go:177 (MultiKueueConfigQuotaManagementMode).
QUOTA_MANAGEMENT_MANUAL = "Manual"
QUOTA_MANAGEMENT_AUTOMATED = "Automated"

# The CQ condition type (multikueue/clusterqueue.go).
QUOTA_AUTOMATION_CONDITION = "MultiKueueManagerQuotaAutomation"


@dataclass
class MultiKueueConfig:
    """multikueue_types.go:124 (MultiKueueConfig): ordered cluster list +
    quotaManagement mode (:166)."""

    clusters: list[str] = field(default_factory=list)
    quota_management: str = QUOTA_MANAGEMENT_MANUAL


MULTIKUEUE_PREEMPTION_GATE = "kueue.x-k8s.io/multikueue-preemption"

# workload.go:67: after opening one cluster's gate, wait this long before
# opening another (one cluster preempts at a time).
SINGLE_CLUSTER_PREEMPTION_TIMEOUT = 300.0


@dataclass
class _RemoteState:
    nominated: list[str] = field(default_factory=list)
    created: dict[str, str] = field(default_factory=dict)  # cluster -> key
    cluster_name: Optional[str] = None
    last_round_time: float = 0.0


class Dispatcher:
    """pkg/controller/workloaddispatcher strategies."""

    ALL_AT_ONCE = "AllAtOnce"
    INCREMENTAL = "Incremental"


class MultiKueueController:
    def __init__(self, manager_engine, check_name: str,
                 config: MultiKueueConfig,
                 dispatcher: str = Dispatcher.ALL_AT_ONCE,
                 increment: int = 1, round_seconds: float = 300.0,
                 orchestrated_preemption: bool = False):
        self.engine = manager_engine
        self.check_name = check_name
        self.config = config
        self.dispatcher = dispatcher
        self.increment = increment
        self.round_seconds = round_seconds
        self.clusters: dict[str, object] = {}  # name -> worker Engine
        # RemoteClient-managed clusters (multikueue_cluster.py):
        # connect/reconnect/hot-reload lifecycles live here; plain
        # connect_cluster() workers bypass it.
        self.remote_clients: dict[str, object] = {}
        # ClusterProfile objects (cluster-inventory-api) for
        # profile-sourced RemoteClients (MultiKueueClusterProfile gate).
        from kueue_tpu.controllers.multikueue_cluster import (
            ClusterProfileRegistry,
        )
        self.cluster_profiles = ClusterProfileRegistry()
        self.states: dict[str, _RemoteState] = {}
        # MultiKueueOrchestratedPreemption: remote copies carry a closed
        # preemption gate; the manager opens one cluster's gate at a time
        # (workload.go:1186 workloadToOpenPreemptionGate).
        self.orchestrated_preemption = orchestrated_preemption
        # Job-object mirroring (jobframework MultiKueueAdapter): manager
        # JobReconciler + per-cluster worker reconcilers + adapter table.
        self.manager_jobs = None
        self.worker_jobs: dict[str, object] = {}
        self.adapters: dict[str, object] = {}
        self.origin = "multikueue"
        # Manager-side quota automation (multikueue/clusterqueue.go
        # cqReconciler): per-CQ MultiKueueManagerQuotaAutomation condition
        # as (status, reason, message); absent = condition removed.
        self.cq_conditions: dict[str, tuple[bool, str, str]] = {}

    def attach_job_framework(self, manager_reconciler,
                             worker_reconcilers: dict,
                             adapters: Optional[dict] = None,
                             origin: str = "multikueue") -> None:
        """Enable per-framework job mirroring: for workloads owned by a
        job, SyncJob creates the remote job object on the winning cluster
        (bound to the mirrored Workload via prebuilt reference) and copies
        remote job status back on every reconcile."""
        from kueue_tpu.controllers.multikueue_adapters import DEFAULT_ADAPTERS

        self.manager_jobs = manager_reconciler
        self.worker_jobs = dict(worker_reconcilers)
        self.adapters = adapters if adapters is not None \
            else dict(DEFAULT_ADAPTERS)
        self.origin = origin

    def connect_cluster(self, name: str, engine) -> None:
        self.clusters[name] = engine

    def add_remote_cluster(self, name: str, kubeconfig_path: str = None,
                           connect=None, retry_increment: float = 1.0,
                           cluster_profile: str = None) -> None:
        """Register a worker reached through a RemoteClient
        (multikueuecluster.go): reconcile_clusters() drives connect /
        exponential reconnect / source hot-reload. ClusterSource is
        exactly one of ``kubeconfig_path`` (file-backed, fswatch) or
        ``cluster_profile`` (a name in ``self.cluster_profiles``, gated
        by MultiKueueClusterProfile)."""
        from kueue_tpu.controllers.multikueue_cluster import RemoteClient

        self.remote_clients[name] = RemoteClient(
            name, kubeconfig_path, connect,
            clock=lambda: self.engine.clock,
            retry_increment=retry_increment,
            cluster_profile=cluster_profile,
            profiles=self.cluster_profiles)

    def cluster_connection_lost(self, name: str, reason: str) -> None:
        """Watch-ended / transport-failure event for a managed cluster:
        tear down placements there (the workers-lost eviction,
        multikueuecluster.go) and schedule a backed-off reconnect."""
        rc = self.remote_clients.get(name)
        if rc is not None:
            rc.mark_lost(reason)
        self.disconnect_cluster(name)

    def reconcile_clusters(self) -> None:
        """Drive every RemoteClient's lifecycle; newly (re)connected
        workers plug back into the dispatch set."""
        for name, rc in self.remote_clients.items():
            event = rc.tick()
            if event in ("reconfigured", "disconnected"):
                # The old client (and its credentials) is gone:
                # placements made through it tear down like a
                # disconnect — stale state.created entries must not
                # block re-dispatch to the rebuilt cluster.
                self.disconnect_cluster(name)
            if event in ("connected", "reconfigured"):
                self.connect_cluster(name, rc.worker)

    def cluster_active(self, name: str):
        """The MultiKueueCluster Active condition for a managed
        cluster (None when the cluster is not RemoteClient-managed)."""
        rc = self.remote_clients.get(name)
        return None if rc is None else rc.active

    @staticmethod
    def _clear_placement_status(wl: Workload) -> None:
        """Reset clusterName/nominatedClusterNames when a placement is
        torn down — a later re-nomination must not coexist with a stale
        placement (the workload_types.go:613 mutual-exclusion rule)."""
        wl.status.cluster_name = None
        wl.status.nominated_cluster_names = ()

    def disconnect_cluster(self, name: str) -> None:
        """Worker lost: evict manager workloads placed there."""
        self.clusters.pop(name, None)
        for wl_key, state in list(self.states.items()):
            if state.cluster_name == name:
                wl = self.engine.workloads.get(wl_key)
                del self.states[wl_key]
                if wl is not None and not wl.is_finished:
                    self._clear_placement_status(wl)
                    self.engine.evict(wl, "MultiKueueClusterLost")
            else:
                state.created.pop(name, None)

    # -- the reconcile pass (workload.go:185) --

    def reconcile(self) -> None:
        self.reconcile_clusters()
        self.reconcile_cluster_queues()
        # The reference runs runGC on a timer per connected cluster
        # (multikueuecluster.go:608); the engine's tick IS the timer
        # here, so every reconcile sweeps origin-labeled orphans.
        self.run_gc()
        acm = self.engine.admission_checks
        for wl in list(self.engine.workloads.values()):
            if wl.is_finished:
                self._gc(wl)
                continue
            if not wl.has_quota_reservation:
                if wl.key in self.states:
                    self._remove_remotes(wl.key, except_cluster=None)
                    del self.states[wl.key]
                    self._clear_placement_status(wl)
                continue
            cq = wl.status.admission.cluster_queue
            if self.check_name not in acm.required_for(cq, wl):
                continue
            state = self.states.setdefault(wl.key, _RemoteState())
            if state.cluster_name is None:
                self._nominate(wl, state)
                self._sync_remotes(wl, state)
                self._check_remote_admission(wl, state, acm)
                if (state.cluster_name is None
                        and self.orchestrated_preemption):
                    self._maybe_open_preemption_gate(state)
            else:
                self._sync_back(wl, state)

    # -- manager quota automation (multikueue/clusterqueue.go) --

    def _cq_has_mk_check(self, cq) -> bool:
        """getMultiKueueAdmissionCheck: the CQ references this controller's
        check directly or through its admissionChecksStrategy."""
        if self.check_name in (cq.admission_checks or ()):
            return True
        strategy = getattr(cq, "admission_checks_strategy", None) or {}
        return self.check_name in strategy

    def reconcile_cluster_queues(self) -> None:
        """cqReconciler.Reconcile for every manager ClusterQueue: with
        quotaManagement=Automated (and the MultiKueueManagerQuotaAutomation
        gate), the single flavor's nominal quotas are overwritten with the
        sum of the connected workers' quotas reachable through same-named
        LocalQueues (aggregateWorkerQuotas)."""
        from dataclasses import replace

        from kueue_tpu.api.types import FlavorQuotas, ResourceQuota
        from kueue_tpu.config import features

        # Deleted CQs shed their condition (removeQuotaAutomationCondition
        # fires on the delete event in the reference).
        for stale in set(self.cq_conditions) \
                - set(self.engine.cache.cluster_queues):
            del self.cq_conditions[stale]
        for name, cq in list(self.engine.cache.cluster_queues.items()):
            if not self._cq_has_mk_check(cq):
                self.cq_conditions.pop(name, None)
                continue
            if (self.config.quota_management != QUOTA_MANAGEMENT_AUTOMATED
                    or not features.enabled(
                        "MultiKueueManagerQuotaAutomation")):
                self.cq_conditions[name] = (
                    False, "NotRequested",
                    "MultiKueue manager quota automation has not been "
                    "requested.")
                continue
            if len(cq.resource_groups) != 1 \
                    or len(cq.resource_groups[0].flavors) != 1:
                self.cq_conditions[name] = (
                    False, "UnsupportedConfiguration",
                    "Quota automation requires that the manager-side "
                    "ClusterQueue has exactly one ResourceFlavor")
                continue
            rg = cq.resource_groups[0]
            aggregated = self._aggregate_worker_quotas(name)
            missing = set(aggregated) - set(rg.covered_resources)
            if missing:
                self.cq_conditions[name] = (
                    False, "UnsupportedConfiguration",
                    "manager-side coveredResources is missing resources "
                    f"configured on workers: {sorted(missing)}")
                continue
            flavor = rg.flavors[0]
            # Only the nominal quota is automated; operator-set
            # borrowing/lending limits survive. (Deliberate deviation:
            # clusterqueue.go:136-142 rebuilds ResourceQuota{nominal}
            # outright, which would silently reset borrowingLimit=None =
            # unlimited — dangerous in a cohort.)
            new_resources = {
                res: (replace(flavor.resources[res],
                              nominal=aggregated.get(res, 0))
                      if res in flavor.resources
                      else ResourceQuota(nominal=aggregated.get(res, 0)))
                for res in rg.covered_resources}
            if {r: q.nominal for r, q in flavor.resources.items()} != \
                    {r: q.nominal for r, q in new_resources.items()}:
                new_cq = replace(cq, resource_groups=(replace(
                    rg, flavors=(FlavorQuotas(
                        flavor.name, new_resources),)),))
                # Propagates to cache + queues; the queue manager's
                # update path keeps the pending heap and retries
                # inadmissible workloads (manager.go:402
                # UpdateClusterQueue), so a quota increase unparks
                # waiting workloads.
                self.engine.create_cluster_queue(new_cq)
            self.cq_conditions[name] = (
                True, "QuotaAutomated",
                "ClusterQueue quota is automatically managed based on "
                "MultiKueue workers.")

    def _aggregate_worker_quotas(self, cq_name: str) -> dict[str, int]:
        """aggregateWorkerQuotas (clusterqueue.go:176): manager LocalQueues
        feeding this CQ name remote CQs through same-namespace/name worker
        LocalQueues; sum those CQs' nominal quotas per resource."""
        lq_keys = {lq.key for lq in
                   self.engine.queues.local_queues.values()
                   if lq.cluster_queue == cq_name}
        total: dict[str, int] = {}
        for cluster in self.config.clusters:
            worker = self.clusters.get(cluster)
            if worker is None:
                continue  # not connected: skipped in aggregation
            remote_cq_names = {
                rlq.cluster_queue
                for rlq in worker.queues.local_queues.values()
                if rlq.key in lq_keys}
            for rcq_name in remote_cq_names:
                rcq = worker.cache.cluster_queues.get(rcq_name)
                if rcq is None:
                    continue
                for rg in rcq.resource_groups:
                    for fq in rg.flavors:
                        for res, quota in fq.resources.items():
                            total[res] = total.get(res, 0) + quota.nominal
        return total

    # -- internals --

    def _nominate(self, wl: Workload, state: _RemoteState) -> None:
        from kueue_tpu.config import features

        available = [c for c in self.config.clusters if c in self.clusters]
        # Incremental rounds are gated (kube_features.go
        # MultiKueueIncrementalDispatcherConfig); off = AllAtOnce.
        if (self.dispatcher == Dispatcher.ALL_AT_ONCE
                or not features.enabled(
                    "MultiKueueIncrementalDispatcherConfig")):
            state.nominated = available
            wl.status.nominated_cluster_names = tuple(state.nominated)
            return
        # Incremental: +increment clusters every round_seconds
        # (incrementaldispatcher.go:50).
        if not state.nominated:
            state.nominated = available[:self.increment]
            state.last_round_time = self.engine.clock
        elif (self.engine.clock - state.last_round_time
              >= self.round_seconds
              and len(state.nominated) < len(available)):
            n = len(state.nominated) + self.increment
            state.nominated = available[:n]
            state.last_round_time = self.engine.clock
        wl.status.nominated_cluster_names = tuple(state.nominated)

    def _sync_remotes(self, wl: Workload, state: _RemoteState) -> None:
        from kueue_tpu.controllers.multikueue_cluster import ORIGIN_LABEL

        for cluster in state.nominated:
            if cluster in state.created:
                continue
            worker = self.clusters.get(cluster)
            if worker is None:
                continue
            existing = worker.workloads.get(wl.key)
            if (existing is not None
                    and existing.labels.get(ORIGIN_LABEL) == self.origin):
                # Reconnect after a connection loss: the remote copy is
                # ADOPTED, not recreated — the reference's wlReconciler
                # only creates missing remote objects (workload.go:609).
                state.created[cluster] = existing.key
                if existing.is_finished:
                    # It finished during the outage: propagate the
                    # result instead of running the job a second time.
                    state.cluster_name = cluster
                    cond = existing.condition(
                        WorkloadConditionType.FINISHED)
                    wl.set_condition(
                        WorkloadConditionType.FINISHED, True,
                        reason=cond.reason if cond else "Finished",
                        now=self.engine.clock)
                    self.engine.finish(wl.key)
                    return
                continue
            copy_wl = copy.deepcopy(wl)
            copy_wl.status = type(copy_wl.status)()
            # Origin mark (kueue.MultiKueueOriginLabel): run_gc only
            # collects this manager's own orphans.
            copy_wl.labels[ORIGIN_LABEL] = self.origin
            if self.orchestrated_preemption:
                # cloneForCreate (workload.go:1254): remotes manage gates
                # independently — drop the manager's, add the MK gate
                # Closed so remotes can't preempt until ungated.
                copy_wl.preemption_gates = ()
                copy_wl.ensure_preemption_gate(MULTIKUEUE_PREEMPTION_GATE)
            if worker.submit(copy_wl):
                state.created[cluster] = copy_wl.key
                self.engine.registry.counter(
                    "workloads_dispatched_total").inc(
                    (self.dispatcher, cluster))

    def _maybe_open_preemption_gate(self, state: _RemoteState) -> None:
        """workload.go:1186 workloadToOpenPreemptionGate: among remotes
        blocked on the gate, open the one whose blocked signal is oldest
        — but only one cluster per SINGLE_CLUSTER_PREEMPTION_TIMEOUT."""
        now = self.engine.clock
        best: Optional[tuple[float, Workload]] = None
        previous_open: Optional[float] = None
        for cluster in state.nominated:
            key = state.created.get(cluster)
            worker = self.clusters.get(cluster)
            if key is None or worker is None:
                continue
            remote = worker.workloads.get(key)
            if remote is None:
                continue
            opened = remote.status.open_preemption_gates.get(
                MULTIKUEUE_PREEMPTION_GATE)
            if opened is not None:
                if previous_open is None or opened > previous_open:
                    previous_open = opened
                continue
            cond = remote.condition(
                WorkloadConditionType.BLOCKED_ON_PREEMPTION_GATES)
            if cond is None or not cond.status:
                continue
            if best is None or cond.last_transition_time < best[0]:
                best = (cond.last_transition_time, remote)
        if best is None:
            return
        if (previous_open is not None and now - previous_open
                < SINGLE_CLUSTER_PREEMPTION_TIMEOUT):
            return  # an earlier cluster's preemption attempt still runs
        # Once the timeout lapses the next gate opens WITHOUT closing the
        # previous one — the reference presumes the stale attempt stuck
        # and lets both race (workload.go:1227-1242 never re-closes).
        best[1].open_preemption_gate(MULTIKUEUE_PREEMPTION_GATE, now)

    def _check_remote_admission(self, wl: Workload, state: _RemoteState,
                                acm) -> None:
        for cluster in state.nominated:
            key = state.created.get(cluster)
            worker = self.clusters.get(cluster)
            if key is None or worker is None:
                continue
            remote = worker.workloads.get(key)
            if remote is not None and remote.is_admitted:
                state.cluster_name = cluster
                # clusterName and nominatedClusterNames are mutually
                # exclusive once placed (workload_types.go:613 CEL rule).
                wl.status.cluster_name = cluster
                wl.status.nominated_cluster_names = ()
                self._remove_remotes(wl.key, except_cluster=cluster)
                self._sync_remote_job(wl, state)
                acm.set_state(wl.key, self.check_name, CheckState.READY)
                return

    def _adapter_and_job(self, wl: Workload):
        """Resolve (local job, adapter, winning worker reconciler) for a
        job-owned workload, or (None, None, None)."""
        if self.manager_jobs is None:
            return None, None, None
        job_key = self.manager_jobs.workload_to_job.get(wl.key)
        job = self.manager_jobs.jobs.get(job_key) if job_key else None
        if job is None:
            return None, None, None
        from kueue_tpu.controllers.multikueue_adapters import adapter_for

        adapter = adapter_for(job, self.adapters,
                              self.manager_jobs.integrations)
        return job, adapter, None

    def _sync_remote_job(self, wl: Workload, state: _RemoteState) -> None:
        """SyncJob on the winning cluster (workload.go:609): create the
        remote job object bound to the mirrored Workload, or copy its
        status back to the manager's job."""
        job, adapter, _ = self._adapter_and_job(wl)
        worker_rec = self.worker_jobs.get(state.cluster_name)
        if job is None or adapter is None or worker_rec is None:
            return
        managed, _reason = adapter.is_job_managed_by_kueue(job)
        if not managed:
            return
        remote_key = state.created.get(state.cluster_name)
        remote_name = remote_key.split("/", 1)[1] if remote_key else wl.name
        adapter.sync_job(job, worker_rec, remote_name, self.origin)

    def _sync_back(self, wl: Workload, state: _RemoteState) -> None:
        worker = self.clusters.get(state.cluster_name)
        key = state.created.get(state.cluster_name)
        if worker is None or key is None:
            return
        remote = worker.workloads.get(key)
        if remote is None:
            # Remote object lost: evict & retry.
            del self.states[wl.key]
            self._clear_placement_status(wl)
            self.engine.evict(wl, "MultiKueueRemoteLost")
            return
        # Keep the remote job object in sync (create if the win happened
        # before the job existed; copy status back otherwise).
        self._sync_remote_job(wl, state)
        if remote.is_finished:
            cond = remote.condition(WorkloadConditionType.FINISHED)
            wl.set_condition(WorkloadConditionType.FINISHED, True,
                             reason=cond.reason if cond else "Finished",
                             now=self.engine.clock)
            self.engine.finish(wl.key)

    def _delete_remote(self, cluster: str, key: str) -> None:
        """Delete one remote workload copy and its mirrored job object
        (wlGroup.RemoveRemoteObjects / DeleteRemoteObject). Shared by
        the per-workload teardown and the orphan GC."""
        worker = self.clusters.get(cluster)
        if worker is not None:
            remote = worker.workloads.pop(key, None)
            if remote is not None:
                worker.cache.delete_workload(key)
                worker.queues.delete_workload(remote)
        worker_rec = self.worker_jobs.get(cluster)
        if worker_rec is not None:
            wl = self.engine.workloads.get(key)
            job, adapter, _ = (self._adapter_and_job(wl)
                               if wl is not None else (None, None, None))
            if job is None and adapter is None:
                # Manager workload gone (orphan GC): resolve the remote
                # job through the worker's own registry.
                job_key = getattr(worker_rec, "workload_to_job",
                                  {}).get(key)
                if job_key is not None and job_key in worker_rec.jobs:
                    from kueue_tpu.controllers.multikueue_adapters import (
                        adapter_for,
                    )
                    job = worker_rec.jobs[job_key]
                    adapter = adapter_for(job, self.adapters,
                                          worker_rec.integrations)
            if job is not None and adapter is not None \
                    and job.key in worker_rec.jobs:
                adapter.delete_remote_object(worker_rec, job.key)

    def _remove_remotes(self, wl_key: str,
                        except_cluster: Optional[str]) -> None:
        state = self.states.get(wl_key)
        if state is None:
            return
        for cluster, key in list(state.created.items()):
            if cluster == except_cluster:
                continue
            self._delete_remote(cluster, key)
            del state.created[cluster]

    def _gc(self, wl: Workload) -> None:
        """Orphan GC of remote objects for finished workloads."""
        if wl.key in self.states:
            self._remove_remotes(wl.key, except_cluster=None)
            del self.states[wl.key]

    def run_gc(self) -> None:
        """multikueuecluster.go:608 (runGC): on every connected worker,
        remote workloads carrying THIS manager's origin label whose
        local counterpart is gone (deleted manager workload, or a
        manager that crashed between remote-create and journaling) are
        deleted, along with their mirrored job objects."""
        from kueue_tpu.controllers.multikueue_cluster import ORIGIN_LABEL

        for cluster, worker in self.clusters.items():
            for key, remote in list(worker.workloads.items()):
                if remote.labels.get(ORIGIN_LABEL) != self.origin:
                    continue
                local = self.engine.workloads.get(key)
                if local is not None and not local.is_finished:
                    continue
                self._delete_remote(cluster, key)
                state = self.states.get(key)
                if state is not None and \
                        state.created.get(cluster) == key:
                    del state.created[cluster]
