"""MultiKueue: multi-cluster workload dispatch, modeled as an
AdmissionCheck on the manager cluster.

Reference: pkg/controller/admissionchecks/multikueue (workload.go:185
wlReconciler, multikueuecluster.go remote clients) and
pkg/controller/workloaddispatcher (AllAtOnce / Incremental strategies,
incrementaldispatcher.go:50).

Semantics:
  * a manager-side Workload that reserves quota and carries the MultiKueue
    check is mirrored to the nominated worker clusters;
  * the first worker to ADMIT the copy wins; the other copies are removed
    (wlGroup.RemoveRemoteObjects :159) and the manager check flips Ready
    with clusterName recorded;
  * remote finish/failure is synced back to the manager workload;
  * losing a worker cluster evicts the manager workloads placed there and
    requeues them (worker-lost timeout, multikueuecluster.go:98).

Worker "clusters" are Engine instances — the same way the reference tests
multi-cluster with two envtest apiservers (SURVEY.md §4).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import Workload, WorkloadConditionType
from kueue_tpu.controllers.admissionchecks import CheckState


@dataclass
class MultiKueueConfig:
    """multikueue_types.go:124 (MultiKueueConfig): ordered cluster list."""

    clusters: list[str] = field(default_factory=list)


@dataclass
class _RemoteState:
    nominated: list[str] = field(default_factory=list)
    created: dict[str, str] = field(default_factory=dict)  # cluster -> key
    cluster_name: Optional[str] = None
    last_round_time: float = 0.0


class Dispatcher:
    """pkg/controller/workloaddispatcher strategies."""

    ALL_AT_ONCE = "AllAtOnce"
    INCREMENTAL = "Incremental"


class MultiKueueController:
    def __init__(self, manager_engine, check_name: str,
                 config: MultiKueueConfig,
                 dispatcher: str = Dispatcher.ALL_AT_ONCE,
                 increment: int = 1, round_seconds: float = 300.0):
        self.engine = manager_engine
        self.check_name = check_name
        self.config = config
        self.dispatcher = dispatcher
        self.increment = increment
        self.round_seconds = round_seconds
        self.clusters: dict[str, object] = {}  # name -> worker Engine
        self.states: dict[str, _RemoteState] = {}

    def connect_cluster(self, name: str, engine) -> None:
        self.clusters[name] = engine

    def disconnect_cluster(self, name: str) -> None:
        """Worker lost: evict manager workloads placed there."""
        self.clusters.pop(name, None)
        for wl_key, state in list(self.states.items()):
            if state.cluster_name == name:
                wl = self.engine.workloads.get(wl_key)
                del self.states[wl_key]
                if wl is not None and not wl.is_finished:
                    self.engine.evict(wl, "MultiKueueClusterLost")
            else:
                state.created.pop(name, None)

    # -- the reconcile pass (workload.go:185) --

    def reconcile(self) -> None:
        acm = self.engine.admission_checks
        for wl in list(self.engine.workloads.values()):
            if wl.is_finished:
                self._gc(wl)
                continue
            if not wl.has_quota_reservation:
                if wl.key in self.states:
                    self._remove_remotes(wl.key, except_cluster=None)
                    del self.states[wl.key]
                continue
            cq = wl.status.admission.cluster_queue
            if self.check_name not in acm.required_for(cq):
                continue
            state = self.states.setdefault(wl.key, _RemoteState())
            if state.cluster_name is None:
                self._nominate(wl, state)
                self._sync_remotes(wl, state)
                self._check_remote_admission(wl, state, acm)
            else:
                self._sync_back(wl, state)

    # -- internals --

    def _nominate(self, wl: Workload, state: _RemoteState) -> None:
        available = [c for c in self.config.clusters if c in self.clusters]
        if self.dispatcher == Dispatcher.ALL_AT_ONCE:
            state.nominated = available
            return
        # Incremental: +increment clusters every round_seconds
        # (incrementaldispatcher.go:50).
        if not state.nominated:
            state.nominated = available[:self.increment]
            state.last_round_time = self.engine.clock
        elif (self.engine.clock - state.last_round_time
              >= self.round_seconds
              and len(state.nominated) < len(available)):
            n = len(state.nominated) + self.increment
            state.nominated = available[:n]
            state.last_round_time = self.engine.clock

    def _sync_remotes(self, wl: Workload, state: _RemoteState) -> None:
        for cluster in state.nominated:
            if cluster in state.created:
                continue
            worker = self.clusters.get(cluster)
            if worker is None:
                continue
            copy_wl = copy.deepcopy(wl)
            copy_wl.status = type(copy_wl.status)()
            if worker.submit(copy_wl):
                state.created[cluster] = copy_wl.key

    def _check_remote_admission(self, wl: Workload, state: _RemoteState,
                                acm) -> None:
        for cluster in state.nominated:
            key = state.created.get(cluster)
            worker = self.clusters.get(cluster)
            if key is None or worker is None:
                continue
            remote = worker.workloads.get(key)
            if remote is not None and remote.is_admitted:
                state.cluster_name = cluster
                self._remove_remotes(wl.key, except_cluster=cluster)
                acm.set_state(wl.key, self.check_name, CheckState.READY)
                return

    def _sync_back(self, wl: Workload, state: _RemoteState) -> None:
        worker = self.clusters.get(state.cluster_name)
        key = state.created.get(state.cluster_name)
        if worker is None or key is None:
            return
        remote = worker.workloads.get(key)
        if remote is None:
            # Remote object lost: evict & retry.
            del self.states[wl.key]
            self.engine.evict(wl, "MultiKueueRemoteLost")
            return
        if remote.is_finished:
            cond = remote.condition(WorkloadConditionType.FINISHED)
            wl.set_condition(WorkloadConditionType.FINISHED, True,
                             reason=cond.reason if cond else "Finished",
                             now=self.engine.clock)
            self.engine.finish(wl.key)

    def _remove_remotes(self, wl_key: str,
                        except_cluster: Optional[str]) -> None:
        state = self.states.get(wl_key)
        if state is None:
            return
        for cluster, key in list(state.created.items()):
            if cluster == except_cluster:
                continue
            worker = self.clusters.get(cluster)
            if worker is not None:
                remote = worker.workloads.pop(key, None)
                if remote is not None:
                    worker.cache.delete_workload(key)
                    worker.queues.delete_workload(remote)
            del state.created[cluster]

    def _gc(self, wl: Workload) -> None:
        """Orphan GC of remote objects for finished workloads."""
        if wl.key in self.states:
            self._remove_remotes(wl.key, except_cluster=None)
            del self.states[wl.key]
