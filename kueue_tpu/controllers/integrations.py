"""Additional job integrations on the GenericJob contract.

Reference: pkg/controller/jobs/* — 15 adapters. Beyond BatchJob and
JobSetJob (jobframework.py), these cover the common framework shapes:
  * TrainingJob — Kubeflow TFJob/PyTorchJob/XGBoost/Paddle/JAXJob style
    (named replica specs, a master/chief plus workers);
  * RayClusterJob — head + worker groups;
  * PodJob — a single plain pod (scheduling-gate based in the reference);
  * ServingJob — Deployment/StatefulSet style (no completion; runs until
    deleted).
Each is a thin shape over pod sets; the jobframework reconciler owns the
Workload lifecycle for all of them identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_tpu.controllers.jobframework import (
    DEFAULT_INTEGRATIONS,
    PodSetInfo,
)


@dataclass
class _BaseJob:
    name: str
    namespace: str = "default"
    queue_name: str = ""
    priority: int = 0
    suspended: bool = True
    active: bool = False
    done: bool = False
    success: bool = False
    injected_info: Optional[list[PodSetInfo]] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.active = False

    def run_with_pod_sets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected_info = infos
        self.suspended = False
        self.active = True

    def restore_pod_sets_info(self, infos) -> None:
        self.injected_info = None

    def is_active(self) -> bool:
        return self.active

    def finished(self) -> tuple[bool, bool]:
        return self.done, self.success


@dataclass
class TrainingJob(_BaseJob):
    """Kubeflow-style job: replica specs {name: (replicas, requests)}.
    (pkg/controller/jobs/kubeflow/*)."""

    framework: str = "pytorch"  # tf | pytorch | xgboost | paddle | jax
    replica_specs: dict = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None

    def pod_sets(self) -> list[PodSet]:
        out = []
        for rname in sorted(self.replica_specs):
            replicas, requests = self.replica_specs[rname]
            out.append(PodSet(name=rname, count=replicas,
                              requests=dict(requests),
                              topology_request=self.topology_request))
        return out


@dataclass
class RayClusterJob(_BaseJob):
    """Ray cluster: head + worker groups (pkg/controller/jobs/raycluster)."""

    head_requests: dict = field(default_factory=dict)
    worker_groups: list = field(default_factory=list)  # (name, n, requests)

    def pod_sets(self) -> list[PodSet]:
        out = [PodSet(name="head", count=1,
                      requests=dict(self.head_requests))]
        for gname, replicas, requests in self.worker_groups:
            out.append(PodSet(name=gname, count=replicas,
                              requests=dict(requests)))
        return out


@dataclass
class PodJob(_BaseJob):
    """A plain pod (pkg/controller/jobs/pod, scheduling gates)."""

    requests: dict = field(default_factory=dict)
    pod_group: Optional[str] = None
    group_total_count: int = 1

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=self.pod_group or "main",
                       count=self.group_total_count,
                       requests=dict(self.requests))]


@dataclass
class ServingJob(_BaseJob):
    """Deployment/StatefulSet-style serving workload: admission-managed,
    never 'finishes' (pkg/controller/jobs/{deployment,statefulset})."""

    replicas: int = 1
    requests: dict = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="pods", count=self.replicas,
                       requests=dict(self.requests))]

    def finished(self) -> tuple[bool, bool]:
        return False, False


DEFAULT_INTEGRATIONS.register("kubeflow.org/trainingjob", TrainingJob)
DEFAULT_INTEGRATIONS.register("ray.io/raycluster", RayClusterJob)
DEFAULT_INTEGRATIONS.register("core/pod", PodJob)
DEFAULT_INTEGRATIONS.register("apps/serving", ServingJob)
