"""Additional job integrations on the GenericJob contract.

Reference: pkg/controller/jobs/* — 15 adapters. Beyond BatchJob and
JobSetJob (jobframework.py), these cover:
  * TrainingJob — Kubeflow TFJob/PyTorchJob/XGBoost/Paddle/JAXJob style
    (named replica specs, a master/chief plus workers);
  * TrainJobV2 — Kubeflow TrainJob (trainer + optional initializer);
  * MPIJob — launcher + workers;
  * RayClusterJob / RayJob / RayServiceJob — head + worker groups, with
    the job/serving lifecycles on top;
  * AppWrapperJob — a wrapper over heterogeneous components;
  * LeaderWorkerSetJob — replicated leader+workers groups, co-placed via
    the TAS pod-set group (the leader rides with its workers);
  * PodJob / PodGroup — plain pods with scheduling-gate semantics;
    PodGroup composes N pods into one gang Workload (ComposableJob);
  * StatefulSetJob / DeploymentJob — serving shapes (never finish);
  * SparkApplicationJob — driver + executors.
Each is a thin shape over pod sets; the jobframework reconciler owns the
Workload lifecycle for all of them identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_tpu.controllers.jobframework import (
    DEFAULT_INTEGRATIONS,
    PodSetInfo,
)


@dataclass
class _BaseJob:
    name: str
    namespace: str = "default"
    queue_name: str = ""
    priority: int = 0
    suspended: bool = True
    active: bool = False
    done: bool = False
    success: bool = False
    injected_info: Optional[list[PodSetInfo]] = None
    # Object annotations seen by the admission webhooks (the elastic
    # workload-slice opt-in, admission-gated-by, ...).
    annotations: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.active = False

    def run_with_pod_sets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected_info = infos
        self.suspended = False
        self.active = True

    def restore_pod_sets_info(self, infos) -> None:
        self.injected_info = None

    def is_active(self) -> bool:
        return self.active

    def finished(self) -> tuple[bool, bool]:
        return self.done, self.success


@dataclass
class TrainingJob(_BaseJob):
    """Kubeflow-style job: replica specs {name: (replicas, requests)}.
    (pkg/controller/jobs/kubeflow/*)."""

    framework: str = "pytorch"  # tf | pytorch | xgboost | paddle | jax
    replica_specs: dict = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None

    def pod_sets(self) -> list[PodSet]:
        out = []
        for rname in sorted(self.replica_specs):
            replicas, requests = self.replica_specs[rname]
            out.append(PodSet(name=rname, count=replicas,
                              requests=dict(requests),
                              topology_request=self.topology_request))
        return out


@dataclass
class RayClusterJob(_BaseJob):
    """Ray cluster: head + worker groups (pkg/controller/jobs/raycluster,
    common.go head/worker pod sets). The in-tree autoscaler
    (enableInTreeAutoscaling) is only admissible for elastic jobs under
    ElasticJobsViaWorkloadSlices (raycluster_webhook.go:141) — the
    autoscaler's replica changes then flow through workload slices;
    scale_group() is the RayCluster workerGroup replicas update."""

    head_requests: dict = field(default_factory=dict)
    # (name, n, requests[, pod_template_annotations]) per worker group.
    worker_groups: list = field(default_factory=list)
    enable_in_tree_autoscaling: bool = False
    elastic: bool = False
    head_annotations: dict = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        out = [PodSet(name="head", count=1,
                      requests=dict(self.head_requests))]
        for gname, replicas, requests, *_ann in self.worker_groups:
            out.append(PodSet(name=gname, count=replicas,
                              requests=dict(requests)))
        return out

    def scale_group(self, group: str, replicas: int) -> None:
        self.worker_groups = [
            (g[0], replicas if g[0] == group else g[1], *g[2:])
            for g in self.worker_groups]


@dataclass
class MPIJob(_BaseJob):
    """MPI launcher + workers (pkg/controller/jobs/mpijob)."""

    launcher_requests: dict = field(default_factory=dict)
    worker_replicas: int = 1
    worker_requests: dict = field(default_factory=dict)
    run_launcher_as_worker: bool = False
    # slotsPerWorker scales each worker's share of the MPI world; the
    # webhook rejects non-positive values (mpijob_webhook.go).
    slots_per_worker: int = 1
    topology_request: Optional[PodSetTopologyRequest] = None

    def pod_sets(self) -> list[PodSet]:
        out = []
        if not self.run_launcher_as_worker:
            out.append(PodSet(name="launcher", count=1,
                              requests=dict(self.launcher_requests)))
        out.append(PodSet(name="worker", count=self.worker_replicas,
                          requests=dict(self.worker_requests),
                          topology_request=self.topology_request))
        return out


@dataclass
class TrainJobV2(_BaseJob):
    """Kubeflow TrainJob v2 (pkg/controller/jobs/trainjob): trainer nodes
    plus an optional dataset/model initializer."""

    num_nodes: int = 1
    trainer_requests: dict = field(default_factory=dict)
    initializer_requests: Optional[dict] = None
    topology_request: Optional[PodSetTopologyRequest] = None

    def pod_sets(self) -> list[PodSet]:
        out = []
        if self.initializer_requests is not None:
            out.append(PodSet(name="initializer", count=1,
                              requests=dict(self.initializer_requests)))
        out.append(PodSet(name="node", count=self.num_nodes,
                          requests=dict(self.trainer_requests),
                          topology_request=self.topology_request))
        return out


@dataclass
class RayJob(_BaseJob):
    """RayJob: a batch job over an ephemeral Ray cluster
    (pkg/controller/jobs/rayjob): optional submitter pod + head +
    worker groups; finishes when the job completes."""

    submitter_requests: Optional[dict] = None
    head_requests: dict = field(default_factory=dict)
    worker_groups: list = field(default_factory=list)  # (name, n, requests)

    def pod_sets(self) -> list[PodSet]:
        out = []
        if self.submitter_requests is not None:
            out.append(PodSet(name="submitter", count=1,
                              requests=dict(self.submitter_requests)))
        out.append(PodSet(name="head", count=1,
                          requests=dict(self.head_requests)))
        for gname, replicas, requests, *_ann in self.worker_groups:
            out.append(PodSet(name=gname, count=replicas,
                              requests=dict(requests)))
        return out


@dataclass
class RayServiceJob(_BaseJob):
    """RayService: a serving Ray cluster
    (pkg/controller/jobs/rayservice) — admission-managed, never
    finishes."""

    head_requests: dict = field(default_factory=dict)
    worker_groups: list = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        out = [PodSet(name="head", count=1,
                      requests=dict(self.head_requests))]
        for gname, replicas, requests, *_ann in self.worker_groups:
            out.append(PodSet(name=gname, count=replicas,
                              requests=dict(requests)))
        return out

    def finished(self) -> tuple[bool, bool]:
        return False, False


@dataclass
class AppWrapperJob(_BaseJob):
    """AppWrapper (pkg/controller/jobs/appwrapper): wraps heterogeneous
    components, each contributing its pod sets."""

    # components: list of (name, replicas, per-pod requests)
    components: list = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=cname, count=replicas, requests=dict(requests))
                for cname, replicas, requests in self.components]


@dataclass
class LeaderWorkerSetJob(_BaseJob):
    """LeaderWorkerSet (pkg/controller/jobs/leaderworkerset): N replicated
    groups of 1 leader + (size-1) workers. Leader and workers of a group
    are co-placed via the TAS pod-set group
    (findLeaderAndWorkers, tas_flavor_snapshot.go:729)."""

    replicas: int = 1  # number of groups
    size: int = 2  # pods per group incl. leader
    leader_requests: dict = field(default_factory=dict)
    worker_requests: dict = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None
    leader_annotations: dict = field(default_factory=dict)
    worker_annotations: dict = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        from dataclasses import replace as _replace
        out = []
        for g in range(self.replicas):
            tr = self.topology_request or PodSetTopologyRequest()
            tr = _replace(tr, pod_set_group_name=f"group-{g}")
            out.append(PodSet(name=f"leader-{g}", count=1,
                              requests=dict(self.leader_requests),
                              topology_request=tr))
            if self.size > 1:
                out.append(PodSet(name=f"workers-{g}",
                                  count=self.size - 1,
                                  requests=dict(self.worker_requests),
                                  topology_request=tr))
        return out

    def finished(self) -> tuple[bool, bool]:
        return False, False  # serving semantics


@dataclass
class LWSGroupJob(_BaseJob):
    """ONE replica group of a LeaderWorkerSet as its own GenericJob —
    the reference creates one Workload PER GROUP
    (pkg/controller/jobs/leaderworkerset: workloads named
    <lws>-<group-index>), so groups admit, evict, and recover
    independently while leader+workers stay co-placed via the TAS
    pod-set group."""

    group_index: int = 0
    size: int = 2
    leader_requests: dict = field(default_factory=dict)
    worker_requests: dict = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None
    leader_annotations: dict = field(default_factory=dict)
    worker_annotations: dict = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        from dataclasses import replace as _replace
        tr = self.topology_request or PodSetTopologyRequest(mode=None)
        tr = _replace(tr, pod_set_group_name=f"group-{self.group_index}")
        out = [PodSet(name="leader", count=1,
                      requests=dict(self.leader_requests),
                      topology_request=tr)]
        if self.size > 1:
            out.append(PodSet(name="workers", count=self.size - 1,
                              requests=dict(self.worker_requests),
                              topology_request=tr))
        return out

    def finished(self) -> tuple[bool, bool]:
        return False, False  # serving semantics


def lws_group_jobs(lws: "LeaderWorkerSetJob") -> list[LWSGroupJob]:
    """Split a LeaderWorkerSet into its per-group jobs (the reference's
    per-group Workload construction)."""
    return [LWSGroupJob(
        name=f"{lws.name}-{g}", namespace=lws.namespace,
        queue_name=lws.queue_name, priority=lws.priority,
        group_index=g, size=lws.size,
        leader_requests=dict(lws.leader_requests),
        worker_requests=dict(lws.worker_requests),
        topology_request=lws.topology_request)
        for g in range(lws.replicas)]


@dataclass
class PodJob(_BaseJob):
    """A plain pod (pkg/controller/jobs/pod): starts behind a scheduling
    gate; admission ungates it. Carries the kueue finalizer the way real
    group pods do (pod_controller.go:577 Finalize strips them)."""

    requests: dict = field(default_factory=dict)
    pod_group: Optional[str] = None
    group_total_count: int = 1
    gated: bool = True
    failed: bool = False
    # RetriableInGroupAnnotation (pod_controller.go:225): "false" means a
    # single pod failure fails the whole group.
    retriable: bool = True
    finalizers: list = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=self.pod_group or "main",
                       count=self.group_total_count,
                       requests=dict(self.requests))]

    def run_with_pod_sets_info(self, infos) -> None:
        super().run_with_pod_sets_info(infos)
        self.gated = False  # gate removed on admission

    def suspend(self) -> None:
        super().suspend()
        self.gated = True


POD_FINALIZER = "kueue.x-k8s.io/managed"


class PodGroup:
    """Pod groups (pkg/controller/jobs/pod pod-group mode, ComposableJob):
    pods sharing a group name compose into ONE gang Workload with one pod
    set per distinct shape (constructGroupPodSets). Reference edge
    semantics carried over from pod_controller.go:

      * gate-based assembly — the Workload exists only once all
        ``total_count`` pods are created, unless ``fast_admission``
        (GroupFastAdmissionAnnotation :717) builds it from the first pod
        with the full count;
      * replacement pods — a Failed pod makes the group report
        WaitingForReplacementPods (:1394) while the Workload stays
        admitted; a newly created pod replaces it and is ungated
        immediately; an unretriable group (RetriableInGroup=false, :225)
        fails the whole Workload instead;
      * excess pods — pods beyond ``total_count`` are finalized and
        removed, gated pods first, newest first (removeExcessPods :984);
      * per-pod finalizers — every member carries the kueue finalizer
        until the group finishes or is deleted (Finalize :577);
      * reclaimable pods — Succeeded members release their quota share
        (ReclaimablePods :1350) for non-serving groups.
    """

    def __init__(self, name: str, namespace: str = "default",
                 queue_name: str = "", total_count: int = 1,
                 fast_admission: bool = False, serving: bool = False):
        self.name = name
        self.namespace = namespace
        self.queue_name = queue_name
        self.total_count = total_count
        self.fast_admission = fast_admission
        self.serving = serving
        self.pods: list[PodJob] = []
        self.removed_excess: list[PodJob] = []
        self.suspended = True
        self.active = False
        self.injected_info = None
        self.priority = 0
        # The gang's pod sets are FROZEN at Workload construction: pod
        # failures awaiting replacement must not change the declared
        # shapes/counts (the Workload keeps its pod sets; only
        # reclaimablePods adjust, pod_controller.go:1308
        # equivalentToWorkload ignores absent pods).
        self._frozen_pod_sets: Optional[list[PodSet]] = None
        self._shape_names: dict[tuple, str] = {}

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    # -- membership --

    def add_pod(self, pod: PodJob) -> None:
        if POD_FINALIZER not in pod.finalizers:
            pod.finalizers.append(POD_FINALIZER)
        needed = self.active and self.absent_count() > 0
        self.pods.append(pod)
        if needed:
            # Replacement for a failed member of a running group: ungate
            # immediately (the group's admission already covers it). A
            # pod added to a FULL group stays gated so it never runs
            # outside the admitted quota and sync_excess trims it as a
            # never-started pod (pod_controller.go removeExcessPods).
            pod.gated = False

    def live_pods(self) -> list[PodJob]:
        return [p for p in self.pods if not p.failed]

    def absent_count(self) -> int:
        """How many replacement pods the group is waiting for."""
        return max(0, self.total_count - len(self.live_pods()))

    def sync_excess(self) -> list[PodJob]:
        """Drop pods beyond total_count: gated (never-started) pods
        first, newest first; their finalizers are stripped
        (removeExcessPods + finalizePods)."""
        removed: list[PodJob] = []
        live = self.live_pods()
        excess = len(live) - self.total_count
        if excess <= 0:
            return removed
        for pod in sorted(
                live, key=lambda p: (not p.gated,
                                     -self.pods.index(p)))[:excess]:
            self.pods.remove(pod)
            if POD_FINALIZER in pod.finalizers:
                pod.finalizers.remove(POD_FINALIZER)
            removed.append(pod)
        self.removed_excess.extend(removed)
        return removed

    def is_unretriable(self) -> bool:
        """pod_controller.go:231 isUnretriableGroup."""
        return any(not p.retriable for p in self.pods)

    def finalize(self) -> None:
        """Strip the kueue finalizer from every member (Finalize :577).
        The frozen gang shape unfreezes with it — a re-created group
        re-declares its pod sets."""
        for pod in self.pods:
            if POD_FINALIZER in pod.finalizers:
                pod.finalizers.remove(POD_FINALIZER)
        self._frozen_pod_sets = None
        self._shape_names = {}

    # -- GenericJob contract --

    def complete(self) -> bool:
        if self.fast_admission:
            return bool(self.pods)
        return len(self.live_pods()) >= self.total_count

    def pod_sets(self) -> list[PodSet]:
        # One pod set per distinct resource shape (pod/pod_controller.go
        # constructGroupPodSets), FROZEN once the Workload exists —
        # a failed member awaiting replacement must not reshape the
        # admitted gang. Under fast admission the absent pods are
        # assumed to share the first pod's shape so the gang reserves
        # its full quota up front.
        if self._frozen_pod_sets is not None:
            return self._frozen_pod_sets
        shapes: dict[tuple, int] = {}
        for pod in self.live_pods():
            shape = tuple(sorted(pod.requests.items()))
            shapes[shape] = shapes.get(shape, 0) + 1
        missing = self.total_count - sum(shapes.values())
        if missing > 0 and self.pods:
            # ANY member's shape anchors the backfill (the sole
            # fast-admission pod may itself be Failed).
            first = tuple(sorted(self.pods[0].requests.items()))
            shapes[first] = shapes.get(first, 0) + missing
        out = [PodSet(name=f"shape-{i}", count=n, requests=dict(shape))
               for i, (shape, n) in enumerate(sorted(shapes.items()))]
        if self.complete() and out:
            self._frozen_pod_sets = out
            self._shape_names = {shape: f"shape-{i}" for i, (shape, _n)
                                 in enumerate(sorted(shapes.items()))}
        return out

    def reclaimable_pods(self) -> dict[str, int]:
        """JobWithReclaimablePods: Succeeded members release their share
        (serving groups never reclaim, :1342-1350)."""
        if self.serving:
            return {}
        out: dict[str, int] = {}
        for pod in self.live_pods():
            if not (pod.done and pod.success):
                continue
            shape = tuple(sorted(pod.requests.items()))
            # Keyed by the FROZEN shape->pod-set-name mapping so a
            # reclaim never lands on the wrong pod set even when whole
            # shapes have failed out of the live set.
            name = self._shape_names.get(shape)
            if name is None:
                continue
            out[name] = out.get(name, 0) + 1
        return out

    def custom_workload_conditions(self, now: float) -> list[tuple]:
        """CustomWorkloadConditions (:1380): the
        WaitingForReplacementPods signal, as (type, status, reason) the
        reconciler applies to the group's Workload."""
        absent = self.absent_count()
        if absent > 0:
            return [("WaitingForReplacementPods", True,
                     "PodsFailed")]
        return [("WaitingForReplacementPods", False, "PodsReady")]

    def is_suspended(self) -> bool:
        return self.suspended

    def suspend(self) -> None:
        self.suspended = True
        self.active = False
        for pod in self.pods:
            pod.gated = True

    def run_with_pod_sets_info(self, infos) -> None:
        self.injected_info = infos
        self.suspended = False
        self.active = True
        for pod in self.pods:
            if not pod.failed:
                pod.gated = False

    def restore_pod_sets_info(self, infos) -> None:
        self.injected_info = None

    def is_active(self) -> bool:
        return self.active

    def finished(self) -> tuple[bool, bool]:
        # An unretriable group fails outright on the first pod failure
        # (:231); a retriable group keeps its admission and waits for
        # replacements.
        if self.is_unretriable() and any(p.failed for p in self.pods):
            return True, False
        live = self.live_pods()
        if (len(live) >= self.total_count
                and all(p.done for p in live)):
            return True, all(p.success for p in live)
        return False, False


@dataclass
class StatefulSetJob(_BaseJob):
    """StatefulSet (pkg/controller/jobs/statefulset): a serving job with
    a prebuilt-workload lifecycle. Scale semantics
    (statefulset_reconciler.go:187):
      * scale to ZERO releases the reservation with reason OnHold and
        parks the Workload (:295 releaseScaleDownReservation) — pods
        gone, quota freed, Workload kept;
      * scale back up clears the hold (:274 clearOnHold) and requeues;
      * replica changes on a RUNNING set flow through elastic workload
        slices when the job is elastic (ElasticJobsViaWorkloadSlices),
        otherwise re-create the Workload (stop-and-requeue).
    """

    replicas: int = 1
    requests: dict = field(default_factory=dict)
    # statefulset jobs are scale-to-zero serving objects.
    hold_at_zero: bool = True
    # ElasticJobsViaWorkloadSlices opt-in (the elastic-job annotation).
    elastic: bool = False
    # status.readyReplicas: the webhook freezes queue/priority labels
    # once any replica is ready (statefulset_webhook.go).
    ready_replicas: int = 0

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="pods", count=self.replicas,
                       requests=dict(self.requests))]

    def scale(self, replicas: int) -> None:
        """The Scale-subresource update; the reconciler turns it into
        hold/clear-hold or a slice/recreate on the next pass."""
        self.replicas = replicas

    def finished(self) -> tuple[bool, bool]:
        return False, False


@dataclass
class DeploymentJob(_BaseJob):
    """Deployment (pkg/controller/jobs/deployment): each replica is
    admitted independently in the reference; modeled as one pod set with
    per-replica pods. Serving semantics like StatefulSet: scale-to-zero
    releases the reservation with an engine hold
    (deployment_reconciler.go scale handling), scale while running
    replaces the workload (elastic: via a workload slice)."""

    replicas: int = 1
    requests: dict = field(default_factory=dict)
    hold_at_zero: bool = True

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="pods", count=self.replicas,
                       requests=dict(self.requests))]

    def scale(self, replicas: int) -> None:
        self.replicas = replicas

    def finished(self) -> tuple[bool, bool]:
        return False, False


@dataclass
class SparkApplicationJob(_BaseJob):
    """SparkApplication (pkg/controller/jobs/sparkapplication): one
    driver pod set + one executor pod set sized by spec.executor
    .instances (sparkapplication_podset.go). dynamicAllocation is only
    admissible for elastic jobs under ElasticJobsViaWorkloadSlices
    (sparkapplication_webhook.go:125) — the operator's executor-count
    changes then flow through workload slices via scale_executors()."""

    driver_requests: dict = field(default_factory=dict)
    executor_instances: int = 1
    executor_requests: dict = field(default_factory=dict)
    dynamic_allocation: bool = False
    elastic: bool = False
    driver_annotations: dict = field(default_factory=dict)
    executor_annotations: dict = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [
            PodSet(name="driver", count=1,
                   requests=dict(self.driver_requests)),
            PodSet(name="executor", count=self.executor_instances,
                   requests=dict(self.executor_requests)),
        ]

    def scale_executors(self, instances: int) -> None:
        self.executor_instances = instances


@dataclass
class ServingJob(_BaseJob):
    """Deployment/StatefulSet-style serving workload: admission-managed,
    never 'finishes' (pkg/controller/jobs/{deployment,statefulset})."""

    replicas: int = 1
    requests: dict = field(default_factory=dict)
    ready_replicas: int = 0

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="pods", count=self.replicas,
                       requests=dict(self.requests))]

    def finished(self) -> tuple[bool, bool]:
        return False, False


DEFAULT_INTEGRATIONS.register("kubeflow.org/trainingjob", TrainingJob)
DEFAULT_INTEGRATIONS.register("kubeflow.org/trainjob", TrainJobV2)
DEFAULT_INTEGRATIONS.register("kubeflow.org/mpijob", MPIJob)
DEFAULT_INTEGRATIONS.register("ray.io/raycluster", RayClusterJob)
DEFAULT_INTEGRATIONS.register("ray.io/rayjob", RayJob)
DEFAULT_INTEGRATIONS.register("ray.io/rayservice", RayServiceJob)
DEFAULT_INTEGRATIONS.register("workload.codeflare.dev/appwrapper",
                              AppWrapperJob)
DEFAULT_INTEGRATIONS.register("leaderworkerset.x-k8s.io/leaderworkerset",
                              LeaderWorkerSetJob)
DEFAULT_INTEGRATIONS.register("core/pod", PodJob)
DEFAULT_INTEGRATIONS.register("core/podgroup", PodGroup)
DEFAULT_INTEGRATIONS.register("apps/statefulset", StatefulSetJob)
DEFAULT_INTEGRATIONS.register("apps/deployment", DeploymentJob)
DEFAULT_INTEGRATIONS.register("sparkoperator.k8s.io/sparkapplication",
                              SparkApplicationJob)
DEFAULT_INTEGRATIONS.register("apps/serving", ServingJob)
