"""Two-phase admission: AdmissionChecks.

Reference: apis/kueue AdmissionCheck CRD + pkg/controller/core
(reconcileSyncAdmissionChecks / reconcileCheckBasedEviction,
workload_controller.go:901-951) + the ProvisioningRequest check controller
(pkg/controller/admissionchecks/provisioning/controller.go:123).

Flow (SURVEY.md §3.4): the scheduler reserves quota (QuotaReserved);
check controllers then flip their AdmissionCheckState to Ready /
Retry / Rejected; the workload controller admits when ALL required
checks are Ready, and evicts + requeues (Retry) or deactivates
(Rejected) otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from kueue_tpu.api.types import Workload, WorkloadConditionType


class CheckState(str, Enum):
    PENDING = "Pending"
    READY = "Ready"
    RETRY = "Retry"
    REJECTED = "Rejected"


@dataclass
class AdmissionCheck:
    """Reference: admissioncheck_types.go:48."""

    name: str
    controller_name: str = ""
    retry_delay_seconds: int = 60


@dataclass(frozen=True)
class PodSetUpdate:
    """Additive per-PodSet modifications an admission check suggests
    (workload_types.go:845 PodSetUpdate): merged into the job's pod sets
    when it starts; conflicting keys across checks fail admission."""

    name: str
    labels: tuple = ()  # ((key, value), ...) — hashable
    annotations: tuple = ()
    node_selector: tuple = ()
    tolerations: tuple = ()

    @classmethod
    def make(cls, name, labels=None, annotations=None, node_selector=None,
             tolerations=()) -> "PodSetUpdate":
        return cls(name=name,
                   labels=tuple(sorted((labels or {}).items())),
                   annotations=tuple(sorted((annotations or {}).items())),
                   node_selector=tuple(sorted((node_selector or {}).items())),
                   tolerations=tuple(tolerations))


@dataclass
class ProvisioningRequestRetryStrategy:
    """provisioningrequestconfig_types.go:127: retry backoff is
    min(base * 2^(attempt-1), max), capped at backoff_limit_count
    attempts before the check rejects."""

    backoff_limit_count: int = 3
    backoff_base_seconds: int = 60
    backoff_max_seconds: int = 1800

    def delay(self, attempt: int) -> float:
        return min(self.backoff_base_seconds * (2 ** max(attempt - 1, 0)),
                   self.backoff_max_seconds)


@dataclass
class ProvisioningRequestConfig:
    """provisioningrequestconfig_types.go:35: how the check controller
    shapes ProvisioningRequests and what it injects back.
    ``pod_set_update_node_selectors`` maps a node-selector key to the
    ProvisioningClassDetails detail it reads the value from
    (controller.go:652 podSetUpdates)."""

    name: str = "default"
    provisioning_class_name: str = "queued-provisioning.gke.io"
    pod_set_update_node_selectors: dict[str, str] = field(
        default_factory=dict)
    retry_strategy: ProvisioningRequestRetryStrategy = field(
        default_factory=ProvisioningRequestRetryStrategy)


class AdmissionCheckManager:
    """Holds check definitions and per-workload states; drives the
    admit-when-all-ready rule for the engine."""

    def __init__(self, engine):
        self.engine = engine
        self.checks: dict[str, AdmissionCheck] = {}
        engine.admission_checks = self
        # CQs referencing undefined checks are inactive
        # (inactiveReason AdmissionCheckNotFound).
        engine.cache.admission_check_names = lambda: set(self.checks)

    def _requeue_after_registry_change(self) -> None:
        self.engine.queues.queue_inadmissible_workloads()

    def create_admission_check(self, check: AdmissionCheck) -> None:
        self.checks[check.name] = check
        self._requeue_after_registry_change()

    def delete_admission_check(self, name: str) -> None:
        self.checks.pop(name, None)
        self._requeue_after_registry_change()

    def required_for(self, cq_name: str,
                     wl: Optional[Workload] = None) -> tuple[str, ...]:
        """The CQ's checks plus the admissionChecksStrategy checks whose
        flavor scope matches the workload's assigned flavors
        (clusterqueue_types.go:166-189, workload.AdmissionChecksForWorkload)."""
        cq = self.engine.cache.cluster_queues.get(cq_name)
        if cq is None:
            return ()
        out = list(cq.admission_checks)
        strategy = getattr(cq, "admission_checks_strategy", None) or {}
        if strategy:
            assigned: set[str] = set()
            if wl is not None and wl.status.admission is not None:
                for psa in wl.status.admission.pod_set_assignments:
                    assigned |= set(psa.flavors.values())
            for check, flavors in strategy.items():
                if not flavors or (assigned & set(flavors)):
                    if check not in out:
                        out.append(check)
        return tuple(out)

    def sync_states(self, wl: Workload, cq_name: str) -> None:
        """reconcileSyncAdmissionChecks: seed Pending states for the CQ's
        checks (workload_controller.go:934)."""
        for name in self.required_for(cq_name, wl):
            wl.status.admission_check_states.setdefault(
                name, CheckState.PENDING)

    def all_ready(self, wl: Workload, cq_name: str) -> bool:
        """workload.HasAllRequiredChecks (scheduler.go:914)."""
        return all(
            wl.status.admission_check_states.get(name) == CheckState.READY
            for name in self.required_for(cq_name, wl))

    def set_state(self, wl_key: str, check: str, state: CheckState) -> None:
        """A check controller reporting its verdict; triggers the workload
        controller pass."""
        wl = self.engine.workloads.get(wl_key)
        if wl is None:
            return
        wl.status.admission_check_states[check] = state
        self.engine.reconcile_workload(wl)


@dataclass
class ProvisioningRequest:
    """The external provisioning object the check controller creates
    (provisioning/controller.go:248 syncOwnedProvisionRequest)."""

    name: str
    workload_key: str
    check_name: str
    provisioned: bool = False
    failed: bool = False
    attempts: int = 1
    # What the autoscaler reports about the provisioned capacity
    # (autoscaling ProvisioningRequest.Status.ProvisioningClassDetails),
    # the source of injected node-selector values.
    provisioning_class_details: dict[str, str] = field(default_factory=dict)


class ProvisioningController:
    """admissionchecks/provisioning: creates a ProvisioningRequest per
    quota-reserved workload carrying this check, then mirrors the
    request's outcome into the check state; on success it attaches
    PodSetUpdates (provisioning annotations + node selectors resolved
    from the request's ProvisioningClassDetails, controller.go:652)."""

    def __init__(self, engine, check_name: str, max_retries: int = None,
                 config: ProvisioningRequestConfig = None):
        import copy as _copy

        self.engine = engine
        self.check_name = check_name
        # Deep-copy so a max_retries override can't mutate a config
        # object shared with other controllers.
        self.config = _copy.deepcopy(config) if config is not None \
            else ProvisioningRequestConfig()
        if max_retries is not None:
            self.config.retry_strategy.backoff_limit_count = max_retries
        self.requests: dict[str, ProvisioningRequest] = {}

    def _pod_set_updates(self, wl: Workload,
                         req: ProvisioningRequest) -> tuple:
        """controller.go:652 podSetUpdates: every PodSet gets the
        provisioning-request annotations; node selectors are looked up in
        the request's ProvisioningClassDetails (missing details are
        skipped, not errors)."""
        annotations = {
            "autoscaling.x-k8s.io/provisioning-request": req.name,
            "autoscaling.x-k8s.io/provisioning-class":
                self.config.provisioning_class_name,
        }
        selector = {}
        for key, detail in self.config.pod_set_update_node_selectors.items():
            value = req.provisioning_class_details.get(detail)
            if value is not None:
                selector[key] = value
        return tuple(
            PodSetUpdate.make(ps.name, annotations=annotations,
                              node_selector=selector)
            for ps in wl.pod_sets)

    def reconcile(self) -> None:
        """provisioning/controller.go:123 (Reconcile over workloads)."""
        acm = self.engine.admission_checks
        retry = self.config.retry_strategy
        for wl in list(self.engine.workloads.values()):
            if wl.is_finished or not wl.has_quota_reservation:
                continue
            cq = (wl.status.admission.cluster_queue
                  if wl.status.admission else "")
            if self.check_name not in acm.required_for(cq, wl):
                continue
            state = wl.status.admission_check_states.get(self.check_name)
            if state in (CheckState.READY, CheckState.REJECTED):
                continue
            req = self.requests.get(wl.key)
            if req is None:
                req = ProvisioningRequest(
                    name=f"prov-{wl.name}", workload_key=wl.key,
                    check_name=self.check_name)
                self.requests[wl.key] = req
            if req.provisioned:
                wl.status.admission_check_updates[self.check_name] = \
                    self._pod_set_updates(wl, req)
                acm.set_state(wl.key, self.check_name, CheckState.READY)
            elif req.failed:
                if req.attempts > retry.backoff_limit_count:
                    acm.set_state(wl.key, self.check_name,
                                  CheckState.REJECTED)
                else:
                    # UpdateAdmissionCheckRequeueState
                    # (controller.go:576): exponential backoff before the
                    # next attempt. Concurrent Retry verdicts from other
                    # checks keep the longest backoff.
                    wl.status.check_retry_after_seconds = max(
                        wl.status.check_retry_after_seconds,
                        retry.delay(req.attempts))
                    req.attempts += 1
                    req.failed = False
                    acm.set_state(wl.key, self.check_name, CheckState.RETRY)

    # -- the "cluster autoscaler" side, driven by tests/mimics --

    def mark_provisioned(self, wl_key: str, details=None) -> None:
        req = self.requests.get(wl_key)
        if req is not None:
            req.provisioned = True
            if details:
                req.provisioning_class_details.update(details)
        self.reconcile()

    def mark_failed(self, wl_key: str) -> None:
        req = self.requests.get(wl_key)
        if req is not None:
            req.failed = True
        self.reconcile()
