"""Two-phase admission: AdmissionChecks.

Reference: apis/kueue AdmissionCheck CRD + pkg/controller/core
(reconcileSyncAdmissionChecks / reconcileCheckBasedEviction,
workload_controller.go:901-951) + the ProvisioningRequest check controller
(pkg/controller/admissionchecks/provisioning/controller.go:123).

Flow (SURVEY.md §3.4): the scheduler reserves quota (QuotaReserved);
check controllers then flip their AdmissionCheckState to Ready /
Retry / Rejected; the workload controller admits when ALL required
checks are Ready, and evicts + requeues (Retry) or deactivates
(Rejected) otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from kueue_tpu.api.types import Workload, WorkloadConditionType


class CheckState(str, Enum):
    PENDING = "Pending"
    READY = "Ready"
    RETRY = "Retry"
    REJECTED = "Rejected"


@dataclass
class AdmissionCheck:
    """Reference: admissioncheck_types.go:48."""

    name: str
    controller_name: str = ""
    retry_delay_seconds: int = 60


class AdmissionCheckManager:
    """Holds check definitions and per-workload states; drives the
    admit-when-all-ready rule for the engine."""

    def __init__(self, engine):
        self.engine = engine
        self.checks: dict[str, AdmissionCheck] = {}
        engine.admission_checks = self

    def create_admission_check(self, check: AdmissionCheck) -> None:
        self.checks[check.name] = check

    def delete_admission_check(self, name: str) -> None:
        self.checks.pop(name, None)

    def required_for(self, cq_name: str) -> tuple[str, ...]:
        cq = self.engine.cache.cluster_queues.get(cq_name)
        return cq.admission_checks if cq else ()

    def sync_states(self, wl: Workload, cq_name: str) -> None:
        """reconcileSyncAdmissionChecks: seed Pending states for the CQ's
        checks (workload_controller.go:934)."""
        for name in self.required_for(cq_name):
            wl.status.admission_check_states.setdefault(
                name, CheckState.PENDING)

    def all_ready(self, wl: Workload, cq_name: str) -> bool:
        """workload.HasAllRequiredChecks (scheduler.go:914)."""
        return all(
            wl.status.admission_check_states.get(name) == CheckState.READY
            for name in self.required_for(cq_name))

    def set_state(self, wl_key: str, check: str, state: CheckState) -> None:
        """A check controller reporting its verdict; triggers the workload
        controller pass."""
        wl = self.engine.workloads.get(wl_key)
        if wl is None:
            return
        wl.status.admission_check_states[check] = state
        self.engine.reconcile_workload(wl)


@dataclass
class ProvisioningRequest:
    """The external provisioning object the check controller creates
    (provisioning/controller.go:248 syncOwnedProvisionRequest)."""

    name: str
    workload_key: str
    check_name: str
    provisioned: bool = False
    failed: bool = False
    attempts: int = 1


class ProvisioningController:
    """admissionchecks/provisioning: creates a ProvisioningRequest per
    quota-reserved workload carrying this check, then mirrors the
    request's outcome into the check state."""

    def __init__(self, engine, check_name: str, max_retries: int = 3):
        self.engine = engine
        self.check_name = check_name
        self.max_retries = max_retries
        self.requests: dict[str, ProvisioningRequest] = {}

    def reconcile(self) -> None:
        """provisioning/controller.go:123 (Reconcile over workloads)."""
        acm = self.engine.admission_checks
        for wl in self.engine.workloads.values():
            if wl.is_finished or not wl.has_quota_reservation:
                continue
            cq = (wl.status.admission.cluster_queue
                  if wl.status.admission else "")
            if self.check_name not in acm.required_for(cq):
                continue
            state = wl.status.admission_check_states.get(self.check_name)
            if state in (CheckState.READY, CheckState.REJECTED):
                continue
            req = self.requests.get(wl.key)
            if req is None:
                req = ProvisioningRequest(
                    name=f"prov-{wl.name}", workload_key=wl.key,
                    check_name=self.check_name)
                self.requests[wl.key] = req
            if req.provisioned:
                acm.set_state(wl.key, self.check_name, CheckState.READY)
            elif req.failed:
                if req.attempts >= self.max_retries:
                    acm.set_state(wl.key, self.check_name,
                                  CheckState.REJECTED)
                else:
                    req.attempts += 1
                    req.failed = False
                    acm.set_state(wl.key, self.check_name, CheckState.RETRY)

    # -- the "cluster autoscaler" side, driven by tests/mimics --

    def mark_provisioned(self, wl_key: str) -> None:
        req = self.requests.get(wl_key)
        if req is not None:
            req.provisioned = True
        self.reconcile()

    def mark_failed(self, wl_key: str) -> None:
        req = self.requests.get(wl_key)
        if req is not None:
            req.failed = True
        self.reconcile()
