"""Columnar diff application for the serving cycle's admitted batch.

The serial assume path (Engine.bulk_assume_batch) walks the batch one
entry at a time: each admission pays its own rowcache release (four
single-element numpy writes), its own second-pass delete, its own
expectation-store lock round trip and its own admitted-dirty mark. At
1k admissions/cycle those per-entry round trips dominate the apply
span (obs/perf.py ``apply.rowcache_writeback``).

This module applies the same diff in COLUMNS:

  * pending-world exits release their tensor rows through
    ``WorkloadRowCache.on_remove_batch`` — four vectorized column
    writes for the whole batch instead of four numpy scalar writes per
    entry;
  * admitted-dirty marks flush as one ``set.update``;
  * preemption-expectation observations take the store lock once for
    the whole batch (``Store.observed_uids``) and skip it entirely
    when the store is empty;
  * the second-pass delete column is skipped when the delayed-reeval
    queue is empty (the steady serving shape).

Every observable mutation lands in the same order and with the same
values as the serial loop: the per-entry dict pops happen inline in
entry order, and a rare fallback ``delete_workload`` (stale LocalQueue
mapping) flushes the pending row column first so the tensor-row
free-list order — which future row allocation reads — matches the
serial path byte for byte. tests/test_colapply.py drains the same
world both ways and asserts identical decision digests.

``KUEUE_TPU_COLUMNAR=0`` is the escape hatch back to the per-entry
loop (Engine._assume_batch_serial).
"""

from __future__ import annotations

import os

from kueue_tpu.api.types import Admission, PodSetAssignmentStatus


def columnar_enabled() -> bool:
    """Columnar apply is on unless KUEUE_TPU_COLUMNAR=0."""
    return os.environ.get("KUEUE_TPU_COLUMNAR", "1") != "0"


def _psa_columns(pod_sets) -> tuple:
    """The CQ-independent half of admission_from_assignment: the
    PodSetAssignmentStatus tuple and the per-podset flavor dicts depend
    only on the assignment's pod sets, so they flyweight by assignment
    identity and an Admission for a new (CQ, assignment) pair costs one
    two-field dataclass.

    The flavor dicts are the admission statuses' flavor-NAME maps
    (res -> str), exactly what the serial loop writes into
    PodSetResources.flavors — a requeued workload re-encodes its rows
    from those, so assignment objects must never leak in. They are
    SHARED across every equivalent admission (the serial loop copies
    one per entry) — safe because nothing mutates a
    PodSetResources.flavors dict in place, only rebinds it wholesale."""
    statuses = tuple(
        PodSetAssignmentStatus(
            name=psa.name,
            flavors={res: getattr(fa, "name", fa)
                     for res, fa in psa.flavors.items()},
            resource_usage=dict(psa.requests),
            count=psa.count,
            topology_assignment=psa.topology_assignment,
        )
        for psa in pod_sets
    )
    flavor_dicts = [dict(st.flavors) for st in statuses]
    return statuses, flavor_dicts


def columnar_assume_batch(eng, entries, bulk) -> list:
    """Engine.bulk_assume_batch's hot loop, applied in columns.

    Returns the (entry, admission) pairs for bulk_finalize_batch,
    exactly as the serial loop does. Entries with reclaimable pods,
    preemption targets, or configured admission checks take the exact
    per-entry _admit path — only the hot plain-admission shape is
    flattened.
    """
    if not entries:
        return []
    cache = eng.cache
    queues = eng.queues
    rows = queues.rows
    second_pass = queues.second_pass
    checks = eng.admission_checks
    expectations = eng.preemption_expectations
    tas_names = cache._tas_flavor_names()
    workloads_reg = cache.workloads
    wl_usage = cache._wl_usage
    wl_tas = cache._wl_tas
    live_cqs = cache.cluster_queues
    cq_usage = cache.cq_usage
    cq_workloads = cache.cq_workloads
    pending_cqs = queues.cluster_queues

    # Persistent Admission flyweights (shared with the serial loop via
    # the same engine attribute): the stored assignment ref keeps its
    # id() from being recycled, so identity keys are safe.
    ver = cache.spec_version
    fly = getattr(eng, "_admission_fly", None)
    if fly is None or fly[0] != ver:
        fly = (ver, {})
        eng._admission_fly = fly
    fly = fly[1]
    if len(fly) > 65536:
        fly.clear()
    psa_fly = getattr(eng, "_psa_fly", None)
    if psa_fly is None or psa_fly[0] != ver:
        psa_fly = (ver, {})
        eng._psa_fly = psa_fly
    psa_fly = psa_fly[1]
    if len(psa_fly) > 65536:
        psa_fly.clear()

    # second-pass / expectation columns: when the delayed-reeval queue
    # (or the expectation store) is empty the per-entry call is a
    # guaranteed no-op — skip the whole column. The engine is
    # single-threaded within a cycle, so the emptiness snapshots cannot
    # race an insert.
    sp_live = bool(second_pass._prequeued or second_pass._queued
                   or second_pass._ready_at)
    exp_live = bool(expectations._store)

    pairs: list = []
    slow: list = []
    row_batch: list = []   # keys whose tensor rows release as one column
    dirty_keys: list = []  # admitted-dirty marks, flushed as one update
    observed: list = []    # (key, uid) for the expectation store
    if checks is not None:
        # Configured admission checks force every entry through the
        # exact per-entry path — no point classifying one at a time.
        entries, slow = (), list(entries)
    for entry in entries:
        info = entry.info
        wl = info.obj
        st = wl.status
        if (st.reclaimable_pods or entry.preemption_targets
                or st.admission_check_states):
            slow.append(entry)
            continue
        key = wl.namespace + "/" + wl.name  # Workload.key, inlined
        cq_name = info.cluster_queue
        assignment = entry.assignment
        akey = (cq_name, id(assignment))
        ent = fly.get(akey)
        # len(ent) guard: the serial escape hatch stores 2-tuples in the
        # same flyweight dict — rebuild those with the flavor column.
        if ent is None or ent[0] is not assignment or len(ent) != 4:
            pent = psa_fly.get(id(assignment))
            if pent is None or pent[0] is not assignment:
                psas_t, flavor_dicts = _psa_columns(assignment.pod_sets)
                psa_fly[id(assignment)] = (assignment, psas_t,
                                           flavor_dicts)
            else:
                psas_t, flavor_dicts = pent[1], pent[2]
            admission = Admission(cluster_queue=cq_name,
                                  pod_set_assignments=psas_t)
            ent = fly[akey] = (assignment, admission, flavor_dicts,
                              tuple(assignment.usage.items()))
        admission = ent[1]
        flavor_dicts = ent[2]
        usage_items = ent[3]
        # status.admission is part of the ASSUME state (the reference
        # sets quota reservation before assuming, scheduler.go:856-920):
        # cache accounting below reads it (tas_domains), and a stale
        # prior admission must never be accounted.
        wl.status.admission = admission
        # apply_admission, inlined for the fast shape (device verdicts
        # never reduce pod counts). The flavor dicts are the flyweight's
        # shared ones (see _psa_columns).
        trs = info.total_requests
        if len(trs) == len(flavor_dicts):
            for psr, fd in zip(trs, flavor_dicts):
                psr.flavors = fd
        else:
            info.apply_admission(admission)
        # Pending-world exit (delete_lazy, inlined): the dict pops run
        # here in entry order; the tensor-row release joins the batch
        # column. The fallback delete_workload releases rows itself, so
        # the pending column flushes FIRST — free-list push order stays
        # identical to the serial loop.
        pcq = pending_cqs.get(cq_name)
        if pcq is not None and (
                key in pcq.items or key in pcq.inadmissible
                or pcq.in_flight == key):
            pcq.items.pop(key, None)
            pcq.inadmissible.pop(key, None)
            if pcq.in_flight == key:
                pcq.in_flight = None
            row_batch.append(key)
        else:
            if row_batch:
                rows.on_remove_batch(row_batch)
                row_batch = []
            queues.delete_workload(wl)
        if sp_live:
            second_pass.delete(key)
        # Cache assume (add_or_update_workload inlined; usage dict is
        # the assignment flyweight's — shared and never mutated by
        # accounting).
        if cq_name in live_cqs:
            if key in wl_usage:
                cache._unaccount(key)
            workloads_reg[key] = info
            cqu = cq_usage.get(cq_name)
            if cqu is None:
                cqu = cq_usage[cq_name] = {}
            for fr, v in usage_items:
                cqu[fr] = cqu.get(fr, 0) + v
            cqw = cq_workloads.get(cq_name)
            if cqw is None:
                cqw = cq_workloads[cq_name] = {}
            cqw[key] = info
            wl_usage[key] = (cq_name, assignment.usage)
            dirty_keys.append(key)
            if tas_names:
                tas = info.tas_domains(tas_names)
                if tas:
                    wl_tas[key] = tas
                    cache._account_tas(tas)
        if exp_live:
            observed.append((key, wl.uid))
        pairs.append((entry, admission))

    if row_batch:
        rows.on_remove_batch(row_batch)
    if dirty_keys:
        # mark_admitted_dirty's overflow clamp, applied to the whole
        # column: under the cap the batched update is element-for-
        # element what the per-key adds would do; over it, fall back to
        # the per-key path so the clear fires at the same crossing.
        if len(cache.admitted_dirty) + len(dirty_keys) <= 100_000:
            cache.admitted_dirty.update(dirty_keys)
        else:
            for key in dirty_keys:
                cache.mark_admitted_dirty(key)
    if observed:
        expectations.observed_uids(observed)
    if pairs:
        cache.admitted_version += 1
    # Rare shapes: the exact per-entry path (assume + finalize).
    for entry in slow:
        queues.delete_workload(entry.info.obj)
        eng._admit(entry, bulk=bulk)
    return pairs
