"""The standalone control-plane engine: queue manager + cache + scheduler
cycle + workload lifecycle, wired together in-process.

This is the framework's equivalent of the reference's minimalkueue
(test/performance/scheduler/minimalkueue/main.go:73): core controllers and
the scheduler only, no API server. The full controller layer (job
integrations, admission checks, webhooks) builds on the same engine.

Lifecycle semantics mirrored from the reference:
  * admit: set QuotaReserved + Admitted, write Admission, assume in cache
    (scheduler.go:856 admit, :920 assumeWorkload).
  * preemption: targets get Evicted/Preempted conditions, their usage is
    released, and they are requeued pending
    (preemption.go:194 IssuePreemptions + core/workload_controller.go).
  * finish: Finished condition, removal from cache, and inadmissible
    workloads of the cohort are re-queued (workload event handlers,
    core/workload_controller.go:1228+).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu.api.types import (
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.cache.queues import QueueManager
from kueue_tpu.cache.scheduler_cache import Cache
from kueue_tpu.scheduler.cycle import (
    CycleResult,
    EntryStatus,
    RequeueReason,
    SchedulerCycle,
)
from kueue_tpu.obs import perf as _perf
from kueue_tpu.workload_info import WorkloadInfo, admission_from_assignment


@dataclass
class EngineEvent:
    time: float
    kind: str  # Admitted | Preempted | Requeued | Finished | Submitted
    workload: str
    cluster_queue: str = ""
    detail: str = ""


@dataclass
class EngineMetrics:
    """The north-star self-metrics (pkg/metrics/metrics.go:345-383)."""

    admission_attempts_total: int = 0
    admission_cycles: int = 0
    admissions_total: int = 0
    preemptions_total: int = 0
    admission_cycle_preemption_skips: dict[str, int] = field(
        default_factory=dict)
    cycle_durations: list[float] = field(default_factory=list)


class _BulkAdmitCtx:
    """Per-cycle accumulator for the batched serving path: shared
    Condition instances plus deferred metric / unadmitted / journal
    writes, flushed once by Engine.flush_bulk_admit."""

    __slots__ = ("qr_cond", "adm_cond", "reset_conds", "counts", "waits",
                 "removed_unadmitted", "journal_keys", "admissions")

    def __init__(self, now: float):
        from kueue_tpu.api.types import Condition, WorkloadConditionType

        self.qr_cond = Condition(
            type=WorkloadConditionType.QUOTA_RESERVED, status=True,
            reason="QuotaReserved", last_transition_time=now)
        self.adm_cond = Condition(
            type=WorkloadConditionType.ADMITTED, status=True,
            reason="Admitted", last_transition_time=now)
        self.reset_conds = tuple(
            (ct, Condition(type=ct, status=False, reason="QuotaReserved",
                           last_transition_time=now))
            for ct in (WorkloadConditionType.EVICTED,
                       WorkloadConditionType.PREEMPTED,
                       WorkloadConditionType.BLOCKED_ON_PREEMPTION_GATES))
        # Per-family aggregation: {name: {labels: n}} / {name: {labels:
        # [values]}} so the flush fetches each registry series ONCE and
        # walks its label map directly (the (name, labels)-tupled layout
        # paid a tuple construction + registry lookup per write).
        self.counts: dict = {}
        self.waits: dict = {}
        self.removed_unadmitted: list = []
        self.journal_keys: list = []
        self.admissions: dict = {}  # (cq, assignment-id) -> Admission

    def count(self, name: str, labels: tuple, n: int = 1) -> None:
        fam = self.counts.get(name)
        if fam is None:
            fam = self.counts[name] = {}
        fam[labels] = fam.get(labels, 0) + n

    def wait(self, name: str, labels: tuple, value: float) -> None:
        fam = self.waits.get(name)
        if fam is None:
            fam = self.waits[name] = {}
        lst = fam.get(labels)
        if lst is None:
            fam[labels] = [value]
        else:
            lst.append(value)


class Engine:
    def __init__(self, enable_fair_sharing: bool = False,
                 cycle: Optional[SchedulerCycle] = None,
                 config=None):
        """``config`` is an optional config.api.Configuration: fair
        sharing and the resources section (excluded prefixes +
        transformations) are applied from it, the way the reference's
        manager wires its loaded Configuration into the scheduler
        (cmd/kueue main.go setup)."""
        if config is not None and config.fair_sharing.enable:
            enable_fair_sharing = True
        self.config = config
        # One workload.Ordering shared by the pending heaps and the cycle
        # iterator so heap pops and entry ordering always agree
        # (requeuingTimestamp in waitForPodsReady config).
        workload_ordering = None
        if config is not None:
            ts = getattr(getattr(config, "wait_for_pods_ready", None),
                         "requeuing_timestamp", None)
            if ts:
                from kueue_tpu.workload_info import Ordering
                workload_ordering = Ordering(
                    pods_ready_requeuing_timestamp=ts)
        self.queues = QueueManager(workload_ordering=workload_ordering)
        self.cache = Cache()
        # When a cycle is active, cohort-inadmissible requeues triggered
        # by evictions are deferred to cycle end (one pass per distinct
        # cohort root instead of one per victim) — matching the
        # reference, where they ride watch events that land after
        # schedule() returns.
        self._deferred_cohort_requeue: Optional[set] = None
        self.cycle = cycle or SchedulerCycle(
            enable_fair_sharing=enable_fair_sharing,
            workload_ordering=workload_ordering)
        # Bound lazily: namespace_labels is initialized further down.
        self.cycle.namespace_labels_of = \
            lambda ns: self.namespace_labels.get(ns)
        self.clock: float = 0.0
        # Wall-clock source for phase timing / metrics. Purely
        # observational (never feeds a decision); the simulator
        # (kueue_tpu/sim) injects its virtual clock here so phase
        # histograms stay deterministic under time compression.
        import time as _time
        self.wall_clock: Callable[[], float] = _time.perf_counter
        self.events: list[EngineEvent] = []
        # Watch fan-out (client-go informer analog): called with each
        # EngineEvent as it is recorded.
        self.event_listeners: list[Callable] = []
        self.metrics = EngineMetrics()
        from kueue_tpu.metrics.registry import MetricsRegistry
        self.registry = MetricsRegistry()
        from kueue_tpu.cache.unadmitted import UnadmittedWorkloads
        self.unadmitted = UnadmittedWorkloads(self.registry)
        # Extra metric labels from CQ metadata (pkg/metrics/
        # custom_labels.go), configured via metrics.customLabels.
        from kueue_tpu.metrics.registry import CustomMetricLabels
        self.custom_labels = CustomMetricLabels(
            config.metrics_custom_labels
            if config is not None else [])
        self._cq_labels_cache = None  # (spec_version, {cq: labels})
        self._serving_gc = False  # apply_serving_gc_posture() active
        # First-eviction-per-workload tracking
        # (evicted_workloads_once_total, metrics.go:666).
        self._evicted_once: set[str] = set()
        # Last cycle's phase durations (scheduler.go:291-358 logs these;
        # the debugger/dashboard surface them here).
        self.last_cycle_phases: dict[str, float] = {}
        # Which path decided the last cycle: "sequential", "device", or
        # "hybrid" (device roots + host tail).
        self.last_cycle_mode: str = ""
        # Flight-recorder / fault-injection capture points (replay/):
        # pre_cycle_hooks fire before each schedule_once() attempt with
        # (seq, engine); cycle_listeners after, with (seq, result) —
        # result is None for an idle cycle.
        self.cycle_seq: int = 0
        self.pre_cycle_hooks: list[Callable] = []
        self.cycle_listeners: list[Callable] = []
        # pre_sync_hooks fire with (seq, result) after a NON-IDLE cycle
        # but BEFORE journal.sync(): records appended here ride inside
        # the cycle's fsync boundary (the HA digest checkpoint,
        # kueue_tpu/ha/digest.py, depends on this ordering).
        self.pre_sync_hooks: list[Callable] = []
        # Admission tracer (obs.CycleTracer attaches itself here); the
        # flight recorder and explain path read it via this slot.
        self.tracer = None
        # Perf telemetry (obs.perf.PerfRecorder) and SLO engine
        # (obs.slo.SLOEngine) attach themselves here.
        self.perf = None
        self.slo = None
        # HA serving plane (kueue_tpu/ha): the owning HAReplica, the
        # SSE fanout hub, and the submit-path shedder attach here.
        self.ha = None
        self.fanout = None
        self.shedder = None
        # Overload survival: the cycle watchdog (obs.watchdog) and the
        # degradation ladder (ha.ladder) attach themselves here; the
        # debug endpoints and the ladder's trigger scan read the slots.
        self.watchdog = None
        self.ladder = None
        self.workloads: dict[str, Workload] = {}
        # hook: called with (workload, admission) after each admission.
        self.on_admit: Optional[Callable] = None
        # AdmissionCheckManager attaches itself here (two-phase admission).
        self.admission_checks = None
        # PodsReadyManager attaches itself here (WaitForPodsReady).
        self.pods_ready = None
        # AfsManager attaches itself here (admission fair sharing).
        self.afs = None
        # OracleBridge (batched TPU fast path), via attach_oracle().
        self.oracle = None
        # StatusController attaches itself here (CQ/LQ status + object
        # retention, controllers/status.py).
        self.status_controller = None
        # WorkloadPriorityClass registry (workloadpriorityclass_types.go).
        self.workload_priority_classes: dict[str, int] = {}
        # Second-pass retry bookkeeping (second_pass_queue.go backoff).
        self._second_pass_attempts: dict[str, int] = {}
        # In-flight preemption tracking (preemption/expectations,
        # scheduler.go:151 WithPreemptionExpectations): never re-issue an
        # eviction whose observation is still pending.
        from kueue_tpu.utils.expectations import Store
        self.preemption_expectations = Store("preemptions")
        # Admission applies run through this wrapper (scheduler.go:870
        # admissionRoutineWrapper; default = the synchronous test-mode
        # wrapper since the in-memory engine has no apiserver latency).
        from kueue_tpu.utils.routine import SyncWrapper
        self.admission_routine = SyncWrapper()
        # Durable store (store/journal.py) — the "K8s API as durable
        # store" analog; attach via attach_journal().
        self.journal = None
        # Periodic sealed-checkpoint writer (store/checkpoint.py
        # Checkpointer attaches itself here; fault injection and the
        # serving endpoints read it through this slot).
        self.checkpointer = None
        # Effective-requests pipeline inputs (pkg/workload/resources.go):
        # namespaced LimitRanges, RuntimeClass overheads, namespace labels
        # for CQ namespace-selector admissibility, and the Info options
        # (excluded resource prefixes + transformations) from config.
        self.limit_ranges: dict[str, object] = {}
        self.runtime_class_overheads: dict[str, dict[str, int]] = {}
        self.namespace_labels: dict[str, dict[str, str]] = {}
        self.info_options = None
        if config is not None:
            self.set_info_options(config.info_options())
            if (config.retention_after_finished_seconds is not None
                    or config.retention_after_deactivated_seconds
                    is not None):
                from kueue_tpu.controllers.status import (
                    StatusController,
                    WorkloadRetentionPolicy,
                )
                StatusController(self, retention=WorkloadRetentionPolicy(
                    after_finished=config.retention_after_finished_seconds,
                    after_deactivated_by_kueue=config
                    .retention_after_deactivated_seconds))

    def set_info_options(self, options) -> None:
        """Propagate workload_info.InfoOptions to every Info construction
        site (queue manager + scheduler cache), the reference's
        InfoOptions plumbing (workload.go:139)."""
        self.info_options = options
        self.queues.info_options = options
        self.cache.info_options = options

    # -- durability (store/journal.py) --

    def attach_journal(self, journal, record_existing: bool = True) -> None:
        """Journal every object creation and workload status transition.
        With ``record_existing``, the engine's current state is
        snapshotted first (journal adoption after boot)."""
        self.journal = journal
        if record_existing:
            for cohort in self.cache.cohorts.values():
                journal.apply("cohort", cohort, ts=self.clock)
            for rf in self.cache.resource_flavors.values():
                journal.apply("resource_flavor", rf, ts=self.clock)
            for cq in self.cache.cluster_queues.values():
                journal.apply("cluster_queue", cq, ts=self.clock)
            for lq in self.queues.local_queues.values():
                journal.apply("local_queue", lq, ts=self.clock)
            for topo in self.cache.topologies.values():
                journal.apply("topology", topo, ts=self.clock)
            for node in self.cache.nodes.values():
                journal.apply("node", node, ts=self.clock)
            for name, value in self.workload_priority_classes.items():
                journal.apply("workload_priority_class",
                              {"name": name, "value": value},
                              ts=self.clock)
            for wl in self.workloads.values():
                journal.apply("workload", wl, ts=self.clock)

    def _journal_obj(self, kind: str, obj) -> None:
        if self.journal is not None:
            self.journal.apply(kind, obj, ts=self.clock)

    def restore_workload(self, wl: Workload) -> None:
        """The informer-rebuild path (restart recovery): re-register a
        workload from durable state WITHOUT resetting its status —
        admitted workloads re-assume cache usage, pending ones re-enter
        the queues with requeue backoff intact."""
        self.workloads[wl.key] = wl
        if wl.is_finished:
            return
        if wl.status.admission is not None:
            self.cache.add_or_update_workload(wl)
            if wl.status.unhealthy_nodes:
                # Pending node replacement: re-arm the second pass
                # (mark_node_unhealthy had queued it pre-restart).
                info = WorkloadInfo.from_workload(
                    wl, wl.status.admission.cluster_queue,
                    options=self.info_options)
                self.queues.second_pass.prequeue(wl.key)
                self.queues.second_pass.queue(info, now=self.clock)
        elif wl.active:
            self.queues.add_or_update_workload(wl)

    # -- object admin --

    def create_cluster_queue(self, cq: ClusterQueue) -> None:
        self.cache.add_or_update_cluster_queue(cq)
        self.queues.add_cluster_queue(cq)
        self._journal_obj("cluster_queue", cq)

    def create_cohort(self, cohort: Cohort) -> None:
        self.cache.add_or_update_cohort(cohort)
        self._journal_obj("cohort", cohort)

    def create_resource_flavor(self, rf: ResourceFlavor) -> None:
        self.cache.add_or_update_resource_flavor(rf)
        # A CQ may have been inactive for referencing this flavor
        # (inactiveReason FlavorNotFound): re-queue parked workloads.
        self.queues.queue_inadmissible_workloads()
        self._journal_obj("resource_flavor", rf)

    def create_local_queue(self, lq: LocalQueue) -> None:
        self.queues.add_local_queue(lq)
        self._journal_obj("local_queue", lq)

    def create_topology(self, topology) -> None:
        self.cache.add_or_update_topology(topology)
        self.queues.queue_inadmissible_workloads()
        self._journal_obj("topology", topology)

    def create_node(self, node) -> None:
        """Node lifecycle (tas/node_controller.go)."""
        self.cache.add_or_update_node(node)
        self.queues.queue_inadmissible_workloads()
        self._journal_obj("node", node)

    def observe_pod(self, pod) -> None:
        """Non-TAS pod usage intake (tas/non_tas_usage_controller.go):
        pods not managed by TAS consume node capacity that the TAS
        placement must not double-book. Re-queues inadmissible TAS
        workloads only when totals actually moved."""
        from kueue_tpu.tas.non_tas_usage import NonTASUsageController
        if NonTASUsageController(self.cache).pod_event(pod):
            self.queues.queue_inadmissible_workloads()

    def observe_pod_deleted(self, namespace: str, name: str) -> None:
        from kueue_tpu.tas.non_tas_usage import NonTASUsageController
        if NonTASUsageController(self.cache).pod_deleted(namespace, name):
            self.queues.queue_inadmissible_workloads()

    def delete_node(self, name: str) -> None:
        self.cache.delete_node(name)
        self.queues.queue_inadmissible_workloads()
        if self.journal is not None:
            self.journal.delete("node", name, ts=self.clock)

    def mark_node_unhealthy(self, name: str, reason: str = "") -> None:
        """tas/node_controller.go: a node failed — record it on every
        admitted TAS workload placed there (status.unhealthyNodes,
        workload_types.go:766) and arm the second-pass queue so the next
        scheduling pass runs the replacement algorithm.

        kube_features.go TASFailedNodeReplacement (the parent gate of
        the per-trigger TASReplaceNode* gates) disables only the
        REPLACEMENT machinery — the node still stops receiving new
        placements either way."""
        from kueue_tpu.config import features
        if not features.enabled("TASFailedNodeReplacement"):
            self.cache.set_node_ready(name, False)
            # Persist the not-ready state: a restart must not resurrect
            # the dead node as placeable.
            node = self.cache.nodes.get(name)
            if node is not None:
                self._journal_obj("node", node)
            self._event("NodeUnhealthy", "", detail=name)
            return
        self.cache.delete_node(name)
        if self.journal is not None:
            self.journal.delete("node", name, ts=self.clock)
        for wl in self.workloads.values():
            if wl.is_finished or wl.status.admission is None:
                continue
            touched = any(
                dom.values[-1] == name
                for psa in wl.status.admission.pod_set_assignments
                if psa.topology_assignment is not None
                for dom in psa.topology_assignment.domains)
            if touched and name not in wl.status.unhealthy_nodes:
                wl.status.unhealthy_nodes = \
                    wl.status.unhealthy_nodes + (name,)
                info = WorkloadInfo.from_workload(
                    wl, wl.status.admission.cluster_queue,
                    options=self.info_options)
                self.queues.second_pass.prequeue(wl.key)
                self.queues.second_pass.queue(info, now=self.clock)
                self._event("NodeUnhealthy", wl.key,
                            cluster_queue=info.cluster_queue,
                            detail=f"{name}: {reason}")
        self.queues.queue_inadmissible_workloads()

    def _process_second_pass(self) -> None:
        """Replacement pass for workloads with unhealthy nodes
        (scheduler.go second-pass handling + tas_flavor_snapshot.go:747).
        On success the admission's TopologyAssignments are patched in
        place (pods on healthy nodes keep running); on failure either
        fail-fast evict (TASFailedNodeReplacementFailFast) or retry with
        backoff."""
        from kueue_tpu.config import features
        from kueue_tpu.tas.snapshot import TASPodSetRequest

        for info in self.queues.second_pass.take_all_ready(self.clock):
            wl = self.workloads.get(info.key)
            if wl is None or wl.is_finished \
                    or wl.status.admission is None \
                    or not wl.status.unhealthy_nodes:
                continue
            snapshot = self.cache.snapshot()
            by_flavor: dict[str, list[TASPodSetRequest]] = {}
            for i, psa in enumerate(wl.status.admission.pod_set_assignments):
                if psa.topology_assignment is None:
                    continue
                flavor = next((f for f in psa.flavors.values()
                               if f in snapshot.tas_flavors), None)
                if flavor is None:
                    continue
                by_flavor.setdefault(flavor, []).append(TASPodSetRequest(
                    wl.pod_sets[i],
                    info.total_requests[i].single_pod_requests(),
                    psa.count))
            reason = ""
            patches: dict[str, object] = {}
            try:
                for flavor in sorted(by_flavor):
                    # One grouped call per flavor: the replacement path
                    # threads a shared assumed-usage dict across the
                    # workload's pod sets so two replacements can't
                    # double-book one free slot.
                    results, reason = snapshot.tas_flavors[flavor] \
                        .find_topology_assignments_for_flavor(
                            by_flavor[flavor], workload=wl)
                    if reason:
                        break
                    patches.update(results)
            finally:
                snapshot.close()
            if reason:
                if features.enabled("TASFailedNodeReplacementFailFast"):
                    # Clear before evicting so the journaled eviction
                    # state is final.
                    wl.status.unhealthy_nodes = ()
                    self.evict(wl, "NodeFailureReplacementFailed")
                else:
                    attempt = self._second_pass_attempts.get(info.key, 0) + 1
                    self._second_pass_attempts[info.key] = attempt
                    self.queues.second_pass.prequeue(info.key)
                    self.queues.second_pass.queue(info, now=self.clock,
                                                  iteration=attempt)
                continue
            from dataclasses import replace as _dc_replace
            adm = wl.status.admission
            wl.status.admission = _dc_replace(adm, pod_set_assignments=tuple(
                _dc_replace(psa, topology_assignment=patches[psa.name])
                if psa.name in patches else psa
                for psa in adm.pod_set_assignments))
            self._second_pass_attempts.pop(info.key, None)
            replaced = ", ".join(wl.status.unhealthy_nodes)
            wl.status.unhealthy_nodes = ()
            self.cache.add_or_update_workload(wl)
            self._event("NodeReplaced", wl.key,
                        cluster_queue=info.cluster_queue, detail=replaced)

    # -- workload lifecycle --

    def create_workload_priority_class(self, name: str, value: int) -> None:
        self.workload_priority_classes[name] = value
        self._journal_obj("workload_priority_class",
                          {"name": name, "value": value})

    def create_limit_range(self, lr) -> None:
        """Register a namespaced LimitRange (utils/limitrange.py)."""
        self.limit_ranges[f"{lr.namespace}/{lr.name}"] = lr

    def create_runtime_class(self, name: str,
                             overhead: dict[str, int]) -> None:
        """RuntimeClass pod overhead source (resources.go:59)."""
        self.runtime_class_overheads[name] = dict(overhead)

    def set_namespace_labels(self, namespace: str,
                             labels: dict[str, str]) -> None:
        """Namespace (re)labeled: workloads parked for a selector
        mismatch can only be cured by this event, so requeue the
        inadmissible sets of every selector-bearing CQ (the reference
        requeues on Namespace update events)."""
        self.namespace_labels[namespace] = dict(labels)
        sel_cqs = {n for n, cq in self.cache.cluster_queues.items()
                   if cq.namespace_selector is not None}
        if sel_cqs:
            self.queues.queue_inadmissible_workloads(sel_cqs)

    def submit(self, wl: Workload) -> bool:
        if not wl.creation_time:
            wl.creation_time = self.clock
        # Effective requests: overhead + LimitRange defaults +
        # limits-as-missing-requests (resources.go:141 AdjustResources),
        # then admissibility validation — inadmissible workloads are
        # registered inactive with an explanatory event rather than
        # queued (workload_controller.go admission checks).
        from kueue_tpu import workload_info as wi

        wi.adjust_resources(wl, list(self.limit_ranges.values()),
                            self.runtime_class_overheads)
        # Template/LimitRange admissibility only: the namespace-selector
        # check runs at NOMINATION time (scheduler.go:636), so a
        # mismatched workload still queues and parks inadmissible under
        # its CQ (RequeueReasonNamespaceMismatch).
        err = wi.validate_admissibility(
            wl, list(self.limit_ranges.values()),
            namespace_labels=self.namespace_labels.get(wl.namespace))
        if err is not None:
            # Deactivate so a journal restart can't resurrect it into the
            # queues (restore_workload requeues active pending workloads).
            wl.active = False
            self.workloads[wl.key] = wl
            self._event("Inadmissible", wl.key, detail=err)  # journals too
            return False
        # Resolve priorityClassRef (pkg/util/priority). An explicitly
        # named class always resolves — this is not gated.
        if (wl.priority_class_name
                and wl.priority_class_name in self.workload_priority_classes):
            wl.priority = self.workload_priority_classes[
                wl.priority_class_name]
        self.workloads[wl.key] = wl
        info = self.queues.add_or_update_workload(wl)
        if info is None:
            # Registered but unqueued (unknown LocalQueue): persist so a
            # restarted engine carries the same object.
            self._journal_obj("workload", wl)
            return False
        self.registry.histogram("workload_creation_latency_seconds").observe(
            max(0.0, self.clock - wl.creation_time))
        # status.resourceRequests: the effective (post-pipeline) totals
        # at consideration time (workload_types.go:886 PodSetRequest).
        wl.status.resource_requests = {
            psr.name: dict(psr.requests) for psr in info.total_requests}
        self._track_unadmitted(wl, info.cluster_queue, "NoReservation")
        self._event("Submitted", wl.key,
                    cluster_queue=info.cluster_queue)
        return True

    def _track_unadmitted(self, wl: Workload, cq_name: str,
                          reason: str, cause: str = "") -> None:
        """unadmitted_workloads.go:75 (update)."""
        from kueue_tpu.cache.unadmitted import UnadmittedStatus

        self.unadmitted.update(wl.key, UnadmittedStatus(
            cluster_queue=cq_name, local_queue=wl.queue_name,
            namespace=wl.namespace, reason=reason, cause=cause))

    def _lq_key(self, wl: Workload) -> tuple:
        return (f"{wl.namespace}/{wl.queue_name}",)

    def _lq_metrics_on(self) -> bool:
        # kube_features.go LocalQueueMetrics: every per-LocalQueue
        # series family, event-time and sync-time alike.
        from kueue_tpu.config import features
        return features.enabled("LocalQueueMetrics")

    def _custom_cq_labels(self, cq_name: str) -> tuple:
        # kube_features.go CustomMetricLabels. Memoized by (spec
        # version, gate state) — label values derive from CQ object
        # metadata, and a gate flip must invalidate.
        from kueue_tpu.config import features
        on = features.enabled("CustomMetricLabels")
        ver = (self.cache.spec_version, on)
        cached = self._cq_labels_cache
        if cached is None or cached[0] != ver:
            cached = (ver, {})
            self._cq_labels_cache = cached
        labels = cached[1].get(cq_name)
        if labels is None:
            if not on:
                labels = ()
            else:
                labels = self.custom_labels.for_object(
                    self.cache.cluster_queues.get(cq_name))
            cached[1][cq_name] = labels
        return labels

    def hold_workload(self, key: str, message: str = "") -> None:
        """statefulset_reconciler.go:295 (releaseScaleDownReservation):
        release the quota reservation with QuotaReserved=False reason
        OnHold and do NOT requeue — the workload stays parked out of
        every queue until clear_hold() (a scale-to-zero serving job
        keeps its Workload without consuming quota)."""
        wl = self.workloads.get(key)
        if wl is None or wl.is_finished or self.is_on_hold(wl):
            return
        cq = (wl.status.admission.cluster_queue
              if wl.status.admission is not None else "")
        if wl.status.admission is not None:
            self.cache.delete_workload(key)
        wl.status.admission = None
        if wl.is_admitted:
            wl.set_condition(WorkloadConditionType.ADMITTED, False,
                             reason="OnHold", now=self.clock)
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, False,
                         reason="OnHold", now=self.clock)
        self.queues.delete_workload(wl)
        self.unadmitted.remove(key)
        self._event("OnHold", key, cluster_queue=cq, detail=message)
        self._journal_obj("workload", wl)
        if cq:
            # Freed quota wakes the cohort's parked peers.
            self._requeue_cohort_inadmissible(cq)

    @staticmethod
    def is_on_hold(wl: Workload) -> bool:
        """workload.IsOnHold: QuotaReserved is False with reason
        OnHold."""
        cond = wl.condition(WorkloadConditionType.QUOTA_RESERVED)
        return (cond is not None and not cond.status
                and cond.reason == "OnHold")

    def clear_hold(self, key: str) -> None:
        """statefulset_reconciler.go:274 (clearOnHold): the workload
        becomes admissible again and requeues."""
        wl = self.workloads.get(key)
        if wl is None or not self.is_on_hold(wl):
            return
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, False,
                         reason="Pending", now=self.clock)
        info = self.queues.add_or_update_workload(wl)
        if info is not None:
            self._track_unadmitted(wl, info.cluster_queue,
                                   "NoReservation")
        self._event("HoldCleared", key)
        self._journal_obj("workload", wl)

    def finish(self, key: str) -> None:
        wl = self.workloads.get(key)
        if wl is None:
            return
        finished = wl.condition(WorkloadConditionType.FINISHED)
        reason = (finished.reason if finished is not None and finished.reason
                  else "Succeeded")
        wl.set_condition(WorkloadConditionType.FINISHED, True,
                         reason=reason, now=self.clock)
        cq_name = (wl.status.admission.cluster_queue
                   if wl.status.admission else "")
        self.cache.delete_workload(key)
        self.queues.delete_workload(wl)
        self.unadmitted.remove(key)
        self._evicted_once.discard(wl.uid)  # bound the set to live objects
        self.registry.counter("finished_workloads_total").inc(
            (cq_name, reason))
        if self._lq_metrics_on():
            self.registry.counter(
                "local_queue_finished_workloads_total").inc(
                self._lq_key(wl) + (reason,))
        self._event("Finished", key, cluster_queue=cq_name)
        self._requeue_cohort_inadmissible(cq_name)

    # -- the scheduling loop --

    def tick(self, dt: float) -> None:
        """Advance the clock and run time-based lifecycle: maximum
        execution time enforcement (workload_controller.go:838
        reconcileMaxExecutionTime)."""
        self.clock += dt
        # Only admitted workloads can exceed an execution budget, and
        # the admitted world is exactly the cache's workload set — at
        # churn scale iterating every known workload per tick dominated
        # the tick itself.
        for info in list(self.cache.workloads.values()):
            wl = self.workloads.get(info.key)
            if wl is None or not wl.is_admitted or wl.is_finished:
                continue
            max_s = wl.maximum_execution_time_seconds
            if max_s is None:
                continue
            adm = wl.condition(WorkloadConditionType.ADMITTED)
            # The budget spans admissions: past execution time counts
            # (workload_controller.go:838 + accumulatedPastExecutionTime).
            spent = wl.status.accumulated_past_execution_time_seconds
            if adm and spent + (self.clock - adm.last_transition_time) \
                    > max_s:
                wl.active = False
                self.evict(wl, "MaximumExecutionTimeExceeded",
                           requeue=False)
        if self.status_controller is not None:
            self.status_controller.sweep_retention()

    def attach_tracer(self, retain: int = 64, **kwargs):
        """Enable admission tracing: per-cycle span trees with decision
        rationale (obs.CycleTracer), retained in a bounded ring and
        served at /debug/trace, ``kueuectl explain`` and
        ``kueuectl trace export``."""
        from kueue_tpu.obs import attach_tracer
        return attach_tracer(self, retain=retain, **kwargs)

    def attach_perf(self):
        """Enable always-on perf telemetry (obs.perf.PerfRecorder):
        apply-phase sub-step histograms and device-side counters,
        surfaced on /metrics. Digest-neutral and cheap enough to leave
        on in production."""
        from kueue_tpu.obs.perf import attach_perf
        return attach_perf(self)

    def attach_slo(self, **kwargs):
        """Enable the SLO engine (obs.slo.SLOEngine): declarative
        objectives evaluated over multi-window burn rates, exported on
        /metrics and queryable via ``kueuectl slo``."""
        from kueue_tpu.obs.slo import attach_slo
        return attach_slo(self, **kwargs)

    def attach_oracle(self, max_depth: int = 4,
                      remote_address: Optional[tuple] = None) -> None:
        """Enable the batched TPU fast path for scheduling cycles. With
        ``remote_address`` ((host, port)), device programs run in a
        standalone oracle service process (oracle/service.py) over the
        socket boundary; transport failures fall back to the sequential
        path per cycle."""
        import jax

        # The dense quota math uses int64 quantities with an INF sentinel
        # (api.types.INF); the oracle is unusable without x64. This is a
        # process-global flip — deliberate: the engine is a control-plane
        # service that owns its process. Embedders sharing the process
        # with float32 JAX code should enable x64 themselves at startup.
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        from kueue_tpu.oracle.engine_bridge import OracleBridge
        executor = None
        if remote_address is not None:
            from kueue_tpu.oracle.service import RemoteExecutor
            executor = RemoteExecutor(*remote_address)
        self.oracle = OracleBridge(self, max_depth=max_depth,
                                   executor=executor)

    @contextmanager
    def profiled(self, trace_dir: Optional[str] = None):
        """Context manager: capture a JAX profiler trace (xprof-viewable)
        of everything inside — the reference's pprof server role
        (configuration_types.go:140 PprofBindAddress; SURVEY §5 names
        the JAX profiler as its analog). Directory precedence: explicit
        arg > Configuration.profile_dir > KUEUE_TPU_PROFILE env."""
        import os as _os

        from kueue_tpu.utils.structlog import device_trace

        trace_dir = (trace_dir
                     or (self.config.profile_dir if self.config else None)
                     or _os.environ.get("KUEUE_TPU_PROFILE"))
        with device_trace(trace_dir or None):
            yield

    def schedule_once(self) -> Optional[CycleResult]:
        """One schedule() cycle (scheduler.go:286), bracketed by the
        replay capture points: pre_cycle_hooks before (fault injection
        lands here), then the cycle, then the journal's crash-safe
        cycle-boundary sync, then cycle_listeners (the flight recorder's
        decision-stream capture)."""
        seq = self.cycle_seq
        for fn in tuple(self.pre_cycle_hooks):
            fn(seq, self)
        writable = getattr(self.journal, "writable", None)
        if writable is not None and not writable():
            # Disk budget exhausted (store/diskguard.py): scheduling
            # would admit workloads the journal cannot record. Park
            # this cycle as idle — seq still advances, listeners (the
            # degradation ladder, the watchdog) still run, and the
            # writable() probe re-arms the budget and resumes
            # scheduling the moment the filesystem has headroom.
            result = None
        elif not self._serving_gc:
            result = self._schedule_once_impl()
        else:
            try:
                result = self._schedule_once_impl()
            finally:
                # Serving GC posture: automatic collection is off; sweep
                # the young generation and re-freeze survivors after
                # EVERY cycle — device, hybrid, and sequential-fallback
                # alike (see apply_serving_gc_posture).
                import gc
                gc.collect(0)
                gc.freeze()
        self.cycle_seq = seq + 1
        if result is not None and self.journal is not None:
            # pre_sync_hooks append records that must be durably part
            # of THIS cycle (the HA ha_digest checkpoint): they run
            # before sync so the fsync below covers them.
            for fn in tuple(self.pre_sync_hooks):
                try:
                    fn(seq, result)
                except Exception as e:  # noqa: BLE001 — observers must
                    import warnings      # not unwind the scheduling loop
                    warnings.warn(f"pre-sync hook {fn!r} raised: {e!r}")
            # Crash-safe cycle boundary: every record this cycle wrote
            # (admissions, evictions, requeues) reaches the platter
            # before the decisions take further effect — a SIGKILL
            # between cycles can never lose an applied admission.
            self.journal.sync()
        for fn in tuple(self.cycle_listeners):
            try:
                fn(seq, result)
            except Exception as e:  # noqa: BLE001 — observers must not
                import warnings      # unwind the scheduling loop
                warnings.warn(f"cycle listener {fn!r} raised: {e!r}")
        return result

    def _schedule_once_impl(self) -> Optional[CycleResult]:
        self._process_second_pass()
        if self.oracle is not None:
            from kueue_tpu.oracle.service import RemoteOracleError

            t0 = self.wall_clock()
            try:
                result = self.oracle.try_cycle()
            except RemoteOracleError:
                # Transport failure before any verdict was applied: the
                # sequential path owns this cycle (the BestEffortFIFO
                # fallback contract).
                self.oracle._fallback("remote-error")
                result = None
            if result is not None:
                if not result.entries and not result.inadmissible:
                    return None  # idle
                self.metrics.admission_cycles += 1
                outcome = ("success" if result.stats.admitted
                           else "inadmissible")
                self.registry.report_admission_attempt(
                    outcome, self.wall_clock() - t0)
                return result
            self.oracle.cycles_fallback += 1
            try:
                self.registry.counter("oracle_cycles_total").inc(
                    ("fallback",))
            except KeyError:
                pass  # registry predates the oracle families

        heads = self.queues.heads(self.clock)
        if not heads:
            return None
        if self.pods_ready is not None and self.pods_ready.admission_blocked():
            # BlockAdmission: hold everything until admitted workloads are
            # ready (scheduler.go:535).
            for info in heads:
                self.queues.requeue_workload(info, RequeueReason.GENERIC)
            return None
        return self._sequential_cycle(heads)

    def _sequential_cycle(self, heads, count_cycle: bool = True) \
            -> CycleResult:
        """The sequential decision path for a set of popped heads. Also
        used by the oracle bridge for the host-handled cohort roots of a
        hybrid cycle (roots never interact, so running them after the
        device roots is cycle-equivalent). The bridge passes
        count_cycle=False: the host tail is part of ONE hybrid cycle,
        which schedule_once() counts and times as a whole."""
        t0 = self.wall_clock()
        if count_cycle:
            self.metrics.admission_cycles += 1
            self.last_cycle_mode = "sequential"
        snapshot = self.cache.snapshot()
        t_snap = self.wall_clock()
        already = set(self.cache.workloads)
        try:
            result = self.cycle.schedule(heads, snapshot, now=self.clock,
                                         already_admitted=already)
        finally:
            # Revert the cycle's in-place TAS mutations on the shared
            # live forests BEFORE the apply loop commits the assumed
            # entries through the cache (tas/snapshot.py begin_cycle).
            snapshot.close()
        t_decide = self.wall_clock()
        deferred: set = set()
        self._deferred_cohort_requeue = deferred
        try:
            for e in result.entries:
                self.metrics.admission_attempts_total += 1
                if e.status == EntryStatus.ASSUMED:
                    self._admit(e)
                elif e.status == EntryStatus.PREEMPTING:
                    self._issue_preemptions(e)
                    self._requeue(e)
                else:
                    self._requeue(e)
            for e in result.inadmissible:
                self._requeue(e)
        finally:
            self._deferred_cohort_requeue = None
        self._requeue_cohorts_bulk(deferred)
        for cq_name, skips in result.stats.preemption_skips.items():
            m = self.metrics.admission_cycle_preemption_skips
            m[cq_name] = m.get(cq_name, 0) + skips
            self.registry.counter("admission_cycle_preemption_skips").inc(
                (cq_name,), skips)
        # Per-phase durations (scheduler.go:291-358 logs snapshot/
        # nominate/commit splits; the debugger shows where a slow cycle
        # went). Gated on count_cycle: a hybrid cycle's host tail must
        # not overwrite the bridge's encode/device/apply record.
        if count_cycle:
            t_apply = self.wall_clock()
            phases = {"snapshot": t_snap - t0,
                      "decide": t_decide - t_snap,
                      "apply": t_apply - t_decide}
            self.last_cycle_phases = phases
            for phase, dur in phases.items():
                self.registry.histogram(
                    "scheduler_phase_duration_seconds").observe(
                    dur, (phase,))
        if count_cycle:
            outcome = "success" if result.assumed else "inadmissible"
            self.registry.report_admission_attempt(
                outcome, self.wall_clock() - t0)
        for name, pcq in self.queues.cluster_queues.items():
            self.registry.report_pending(name, len(pcq.items),
                                         len(pcq.inadmissible))
            self.registry.gauge("admitted_active_workloads").set(
                (name,), self.cache.admitted_count(name))
        return result

    def sync_resource_metrics(self) -> None:
        """Refresh the per-CQ / per-LQ / cohort resource and share gauges
        from a fresh snapshot (the metrics.go:796-948 families; the
        reference's cache controllers update these on reconcile). All
        values are collected into fresh tables first and swapped into the
        registry at the end: an exception mid-collection leaves the
        previous aggregates intact, and stale series for deleted objects
        vanish on swap."""
        from collections import defaultdict

        from kueue_tpu.cache.snapshot import dominant_resource_share

        snap = self.cache.snapshot()
        fams: dict[str, dict] = defaultdict(dict)
        # kube_features.go LocalQueueMetrics: skip the per-LQ aggregation
        # entirely when off (the family swap below still clears stale
        # series).
        lq_on = self._lq_metrics_on()

        lq_pending: dict = {}
        lq_reserving: dict = {}
        lq_admitted: dict = {}
        for name, cqs in snap.cluster_queues.items():
            fams["cluster_queue_info"][(name, cqs.spec.cohort or "")] = 1
            # Reservation = every quota-reserved workload's usage;
            # usage = admitted-only (metrics.go:796,814).
            admitted_usage: dict = {}
            reserving = 0
            admitted_n = 0
            lq_reservation: dict = {}
            lq_usage: dict = {}
            for key, info in cqs.workloads.items():
                wl = self.workloads.get(key)
                is_admitted = wl is not None and wl.is_admitted
                reserving += 1
                if lq_on:
                    lq = f"{info.obj.namespace}/{info.obj.queue_name}"
                    lq_reserving[lq] = lq_reserving.get(lq, 0) + 1
                    if is_admitted:
                        lq_admitted[lq] = lq_admitted.get(lq, 0) + 1
                if is_admitted:
                    admitted_n += 1
                for fr, v in info.usage().items():
                    if lq_on:
                        lq_reservation[(lq, fr)] = \
                            lq_reservation.get((lq, fr), 0) + v
                        if is_admitted:
                            lq_usage[(lq, fr)] = \
                                lq_usage.get((lq, fr), 0) + v
                    if is_admitted:
                        admitted_usage[fr] = admitted_usage.get(fr, 0) + v
            for fr, v in cqs.node.usage.items():
                fams["cluster_queue_resource_reservation"][
                    (name, fr.flavor, fr.resource)] = v
            for fr, v in admitted_usage.items():
                fams["cluster_queue_resource_usage"][
                    (name, fr.flavor, fr.resource)] = v
            for (lq, fr), v in lq_reservation.items():
                fams["local_queue_resource_reservation"][
                    (lq, fr.flavor, fr.resource)] = v
            for (lq, fr), v in lq_usage.items():
                fams["local_queue_resource_usage"][
                    (lq, fr.flavor, fr.resource)] = v
            fams["reserving_active_workloads"][(name,)] = reserving
            for fr, q in cqs.node.quotas.items():
                fams["cluster_queue_nominal_quota"][
                    (name, fr.flavor, fr.resource)] = q.nominal
                if q.borrowing_limit is not None:
                    fams["cluster_queue_borrowing_limit"][
                        (name, fr.flavor, fr.resource)] = q.borrowing_limit
                if q.lending_limit is not None:
                    fams["cluster_queue_lending_limit"][
                        (name, fr.flavor, fr.resource)] = q.lending_limit
            # Pending per resource + per LocalQueue (metrics.go:805,409).
            pcq = self.queues.cluster_queues.get(name)
            if pcq is not None:
                pending: dict = {}
                for status, table in (("active", pcq.items),
                                      ("inadmissible", pcq.inadmissible)):
                    for info in list(table.values()):
                        if lq_on:
                            lq = (f"{info.obj.namespace}/"
                                  f"{info.obj.queue_name}")
                            lq_pending[(lq, status)] = \
                                lq_pending.get((lq, status), 0) + 1
                        for psr in info.total_requests:
                            for res, v in psr.requests.items():
                                pending[res] = pending.get(res, 0) + v
                for res, v in pending.items():
                    fams["cluster_queue_resource_pending"][(name, res)] = v
            drs = dominant_resource_share(cqs, None)
            share = (drs.precise_weighted_share()
                     if cqs.fair_weight else drs.unweighted_ratio)
            fams["cluster_queue_weighted_share"][(name,)] = share

        for (lq, status), n in lq_pending.items():
            fams["local_queue_pending_workloads"][(lq, status)] = n
        for lq, n in lq_reserving.items():
            fams["local_queue_reserving_active_workloads"][(lq,)] = n
        for lq, n in lq_admitted.items():
            fams["local_queue_admitted_active_workloads"][(lq,)] = n
        if self.afs is not None and lq_on:
            for lq, entry in self.afs.usage.items():
                fams["local_queue_admission_fair_sharing_usage"][(lq,)] = \
                    self.afs.current_usage(lq)

        # kube_features.go MetricsForCohorts.
        from kueue_tpu.config import features
        cohort_items = (snap.cohorts.items()
                        if features.enabled("MetricsForCohorts") else ())
        for name, cohort in cohort_items:
            fams["cohort_info"][
                (name, cohort.parent.name if cohort.parent else "")] = 1
            for fr, v in cohort.node.subtree_quota.items():
                fams["cohort_subtree_quota"][
                    (name, fr.flavor, fr.resource)] = v
            for fr, v in cohort.node.usage.items():
                fams["cohort_subtree_resource_reservations"][
                    (name, fr.flavor, fr.resource)] = v
            admitted = sum(
                1 for cqs in cohort.subtree_cluster_queues()
                for key in cqs.workloads
                if (w := self.workloads.get(key)) is not None
                and w.is_admitted)
            fams["cohort_subtree_admitted_active_workloads"][
                (name,)] = admitted
            drs = dominant_resource_share(cohort, None)
            share = (drs.precise_weighted_share()
                     if cohort.fair_weight else drs.unweighted_ratio)
            fams["cohort_weighted_share"][(name,)] = share

        # Atomic swap per family (empty tables drop stale series too).
        for fam in ("cluster_queue_info", "cluster_queue_resource_usage",
                    "cluster_queue_resource_reservation",
                    "cluster_queue_resource_pending",
                    "cluster_queue_nominal_quota",
                    "cluster_queue_borrowing_limit",
                    "cluster_queue_lending_limit",
                    "cluster_queue_weighted_share",
                    "local_queue_resource_usage",
                    "local_queue_resource_reservation",
                    "local_queue_pending_workloads",
                    "local_queue_reserving_active_workloads",
                    "local_queue_admitted_active_workloads",
                    "local_queue_admission_fair_sharing_usage",
                    "reserving_active_workloads", "cohort_info",
                    "cohort_subtree_quota",
                    "cohort_subtree_resource_reservations",
                    "cohort_subtree_admitted_active_workloads",
                    "cohort_weighted_share"):
            self.registry.gauge(fam).values = fams.get(fam, {})

    def run_until_quiescent(self, max_cycles: int = 10_000) -> int:
        """Drive cycles until no progress is possible (tests/bench)."""
        cycles = 0
        while cycles < max_cycles:
            result = self.schedule_once()
            cycles += 1
            if result is None:
                break
            if not result.assumed and not any(
                    e.status == EntryStatus.PREEMPTING
                    for e in result.entries):
                break
        return cycles

    # -- internals --

    def apply_serving_gc_posture(self) -> None:
        """Serving-daemon GC posture: the admitted/pending world is
        long-lived state; freeze it so generational collections stop
        scanning millions of stable objects mid-cycle (the dominant
        cycle-latency p95 outlier source). Call once after the initial
        world is loaded; the bench harness applies it as part of the
        system under test.

        Automatic collection is then DISABLED and replaced by a small
        young-generation sweep + re-freeze after every serving cycle
        (schedule_once): each cycle's survivors (admitted infos,
        conditions, events) are long-lived by construction, so they move
        straight to the permanent generation and no full mark ever walks
        the multi-million-object world mid-cycle. Dead non-cyclic
        objects — the overwhelming majority here (dataclass trees with
        no back-references) — are reclaimed by refcounting as usual.
        This is the r03 p95 story: one gen-2 pause per ~7 cycles landed
        inside the apply span and set the p95 (162 ms vs a 66 ms p50)."""
        import gc

        gc.collect()
        gc.freeze()
        gc.disable()
        self._serving_gc = True

    def begin_bulk_admit(self) -> "_BulkAdmitCtx":
        """Open a bulk-admission context for one serving cycle: metric,
        unadmitted-gauge, and journal writes are accumulated and applied
        once in flush_bulk_admit. The reference pays this per entry at
        scheduler.go:856-910; the batched serving path amortizes it."""
        return _BulkAdmitCtx(self.clock)

    def flush_bulk_admit(self, ctx: "_BulkAdmitCtx") -> None:
        for name, fam in ctx.counts.items():
            values = self.registry.counter(name).values
            for labels, n in fam.items():
                values[labels] += n
        for name, fam in ctx.waits.items():
            hist = self.registry.histogram(name)
            for labels, values in fam.items():
                hist.observe_many(values, labels)
        if ctx.removed_unadmitted:
            self.unadmitted.remove_many(ctx.removed_unadmitted)
        if self.journal is not None:
            _pt = _perf.begin()
            wls = [wl for wl in (self.workloads.get(key)
                                 for key in dict.fromkeys(ctx.journal_keys))
                   if wl is not None]
            apply_many = getattr(self.journal, "apply_many", None)
            if apply_many is not None:
                # One encode + one locked write for the cycle's whole
                # admitted batch (same record stream as the per-record
                # loop, store/journal.py apply_many).
                apply_many("workload", wls, ts=self.clock)
            else:
                for wl in wls:
                    self.journal.apply("workload", wl, ts=self.clock)
            _perf.end("apply.journal_append", _pt)

    def bulk_assume_batch(self, entries, bulk: "_BulkAdmitCtx") -> list:
        """In-cycle half of a device cycle's admitted batch: remove the
        workloads from the pending world and assume them in the cache —
        the part the reference's cycle blocks on (scheduler.go:920
        assumeWorkload). Status/metric/event finalization is the
        reference's ASYNC status PATCH (scheduler.go:870
        admissionRoutineWrapper.Run in a goroutine); its analog here is
        bulk_finalize_batch, timed as its own phase.

        Returns the (entry, admission) pairs for finalization. Entries
        with reclaimable pods, preemption targets (slice replacement),
        or configured admission checks take the exact per-entry _admit
        path — only the hot plain-admission shape is flattened.

        The batch is applied columnar by default (controllers/colapply:
        vectorized rowcache release, batched dirty marks and
        expectation observations); KUEUE_TPU_COLUMNAR=0 falls back to
        the per-entry loop below. Both produce identical state —
        tests/test_colapply.py holds them to the same digests.
        """
        from kueue_tpu.controllers import colapply

        if colapply.columnar_enabled():
            return colapply.columnar_assume_batch(self, entries, bulk)
        return self._assume_batch_serial(entries, bulk)

    def _assume_batch_serial(self, entries, bulk: "_BulkAdmitCtx") -> list:
        """The reference per-entry assume loop (KUEUE_TPU_COLUMNAR=0
        escape hatch, and the semantic yardstick the columnar path is
        tested against)."""
        if not entries:
            return []
        cache = self.cache
        queues = self.queues
        second_pass = queues.second_pass
        checks = self.admission_checks
        expectations = self.preemption_expectations
        tas_names = cache._tas_flavor_names()
        workloads_reg = cache.workloads
        wl_usage = cache._wl_usage
        wl_tas = cache._wl_tas
        live_cqs = cache.cluster_queues
        # Persistent Admission flyweights: the stored assignment ref
        # keeps its id() from being recycled, so identity keys are safe.
        ver = cache.spec_version
        fly = getattr(self, "_admission_fly", None)
        if fly is None or fly[0] != ver:
            fly = (ver, {})
            self._admission_fly = fly
        fly = fly[1]
        if len(fly) > 65536:
            # Non-flyweighted assignments (equivalence hashing off) would
            # otherwise grow this without bound — cap and rebuild.
            fly.clear()
        pairs: list = []
        slow: list = []
        for entry in entries:
            info = entry.info
            wl = info.obj
            if (wl.status.reclaimable_pods or entry.preemption_targets
                    or checks is not None
                    or wl.status.admission_check_states):
                slow.append(entry)
                continue
            key = wl.key
            cq_name = info.cluster_queue
            assignment = entry.assignment
            akey = (cq_name, id(assignment))
            ent = fly.get(akey)
            if ent is None or ent[0] is not assignment:
                admission = admission_from_assignment(
                    cq_name, assignment.pod_sets)
                fly[akey] = (assignment, admission)
            else:
                admission = ent[1]
            # status.admission is part of the ASSUME state (the
            # reference sets quota reservation before assuming,
            # scheduler.go:856-920): cache accounting below reads it
            # (tas_domains), and a stale prior admission must never be
            # accounted.
            wl.status.admission = admission
            # apply_admission, inlined for the fast shape (device
            # verdicts never reduce pod counts).
            trs = info.total_requests
            psas = admission.pod_set_assignments
            if len(trs) == len(psas):
                for psr, psa in zip(trs, psas):
                    psr.flavors = dict(psa.flavors)
            else:
                info.apply_admission(admission)
            # Pending world exit (delete_workload, inlined: the
            # bridge resolved the CQ already).
            pcq = queues.cluster_queues.get(cq_name)
            if pcq is not None and (
                    key in pcq.items or key in pcq.inadmissible
                    or pcq.in_flight == key):
                pcq.delete_lazy(key)  # releases the tensor row too
            else:
                queues.delete_workload(wl)
            second_pass.delete(key)
            # Cache assume (add_or_update_workload inlined; usage
            # dict is the assignment flyweight's — shared and never
            # mutated by accounting).
            if cq_name in live_cqs:
                if key in wl_usage:
                    cache._unaccount(key)
                workloads_reg[key] = info
                usage = assignment.usage
                cqu = cache.cq_usage.get(cq_name)
                if cqu is None:
                    cqu = cache.cq_usage[cq_name] = {}
                for fr, v in usage.items():
                    cqu[fr] = cqu.get(fr, 0) + v
                cqw = cache.cq_workloads.get(cq_name)
                if cqw is None:
                    cqw = cache.cq_workloads[cq_name] = {}
                cqw[key] = info
                wl_usage[key] = (cq_name, usage)
                cache.mark_admitted_dirty(key)
                if tas_names:
                    tas = info.tas_domains(tas_names)
                    if tas:
                        wl_tas[key] = tas
                        cache._account_tas(tas)
            expectations.observed_uid(key, wl.uid)
            pairs.append((entry, admission))
        if pairs:
            cache.admitted_version += 1
        # Rare shapes: the exact per-entry path (assume + finalize).
        for entry in slow:
            self.queues.delete_workload(entry.info.obj)
            self._admit(entry, bulk=bulk)
        return pairs

    def bulk_finalize_batch(self, pairs, bulk: "_BulkAdmitCtx") -> None:
        """Async-PATCH analog for a device cycle's admitted batch
        (scheduler.go:870): status conditions, Admission on status,
        events, metrics, unadmitted gauges, journal records. Runs
        synchronously at cycle end (the engine is single-threaded by
        design) but outside the apply span, exactly as the reference's
        cycle does not block on its status PATCHes. The routine wrapper
        brackets the batch once, not per entry."""
        if not pairs:
            return
        now = self.clock
        qr_cond = bulk.qr_cond
        adm_cond = bulk.adm_cond
        reset_conds = bulk.reset_conds
        lq_on = self._lq_metrics_on()
        events = self.events
        # Snapshot: SSE handler threads append/remove listeners while
        # cycles iterate (client-go informers snapshot the same way).
        listeners = tuple(self.event_listeners)
        on_admit = self.on_admit
        journal_on = self.journal is not None
        QR = WorkloadConditionType.QUOTA_RESERVED
        ADM = WorkloadConditionType.ADMITTED
        # (cq, lq) -> [count, [wait values], [nonzero checks waits]]
        agg: dict[tuple, list] = {}
        removed_unadmitted = bulk.removed_unadmitted
        journal_keys = bulk.journal_keys

        def _batch() -> None:
            n_admitted = 0
            for entry, admission in pairs:
                info = entry.info
                wl = info.obj
                key = wl.key
                cq_name = info.cluster_queue
                conds = wl.status.conditions
                prev = conds.get(QR)
                if prev is None or not prev.status:
                    conds[QR] = qr_cond
                    checks_wait = 0.0
                else:
                    # A live reservation (second pass) keeps its
                    # transition time; the admission-checks wait spans
                    # from it (set_condition semantics).
                    checks_wait = now - prev.last_transition_time
                    if checks_wait < 0.0:
                        checks_wait = 0.0
                for ctype, cond in reset_conds:
                    # Reset only currently-True conditions (_admit uses
                    # has_condition): an already-False Evicted/Preempted
                    # keeps its original transition time.
                    pc = conds.get(ctype)
                    if pc is not None and pc.status:
                        conds[ctype] = cond
                ev_qr = EngineEvent(now, "QuotaReserved", key, cq_name)
                events.append(ev_qr)
                if journal_on:
                    journal_keys.append(key)
                adm_cond_prev = conds.get(ADM)
                if adm_cond_prev is not None and adm_cond_prev.status:
                    # Already admitted (_sync_admitted's early return):
                    # QuotaReserved bookkeeping only.
                    bulk.count("quota_reserved_workloads_total",
                               (cq_name,))
                    bulk.wait("quota_reserved_wait_time_seconds",
                              (cq_name,),
                              max(0.0, now - wl.creation_time))
                    if lq_on:
                        lq_l = (f"{wl.namespace}/{wl.queue_name}",)
                        bulk.count(
                            "local_queue_quota_reserved_workloads_total",
                            lq_l)
                        bulk.wait(
                            "local_queue_quota_reserved_wait_time_seconds",
                            lq_l, max(0.0, now - wl.creation_time))
                    if listeners:
                        for fn in listeners:
                            try:
                                fn(ev_qr)
                            except Exception as e:  # noqa: BLE001
                                import warnings
                                warnings.warn(
                                    f"event listener {fn!r} raised: {e!r}")
                    continue
                conds[ADM] = adm_cond
                n_admitted += 1
                wait = now - wl.creation_time
                if wait < 0.0:
                    wait = 0.0
                lq = f"{wl.namespace}/{wl.queue_name}"
                a = agg.get((cq_name, lq))
                if a is None:
                    a = agg[(cq_name, lq)] = [1, [wait], []]
                else:
                    a[0] += 1
                    a[1].append(wait)
                if checks_wait > 0.0:
                    a[2].append(checks_wait)
                removed_unadmitted.append(key)
                ev_adm = EngineEvent(now, "Admitted", key, cq_name)
                events.append(ev_adm)
                if listeners:
                    for ev in (ev_qr, ev_adm):
                        for fn in listeners:
                            try:
                                fn(ev)
                            except Exception as e:  # noqa: BLE001
                                import warnings
                                warnings.warn(
                                    f"event listener {fn!r} raised: {e!r}")
                if on_admit is not None:
                    on_admit(wl, admission)
            self.metrics.admissions_total += n_admitted
            self._flush_admission_metrics(agg, lq_on)

        _pt = _perf.begin()
        self.admission_routine.run(_batch)
        _perf.end("apply.listener_fanout", _pt)

    def _flush_admission_metrics(self, agg: dict, lq_on: bool) -> None:
        """Direct registry writes for a batch's admission metric series:
        the families are fetched once and their label maps updated in
        place (one layer, no per-write tuple/registry churn)."""
        import bisect as _bisect

        reg = self.registry
        qr_total = reg.counter("quota_reserved_workloads_total").values
        adm_total = reg.counter("admitted_workloads_total").values
        hists = [
            reg.histogram("quota_reserved_wait_time_seconds"),
            reg.histogram("admission_wait_time_seconds"),
        ]
        checks_h = reg.histogram("admission_checks_wait_time_seconds")
        if lq_on:
            lq_qr_total = reg.counter(
                "local_queue_quota_reserved_workloads_total").values
            lq_adm_total = reg.counter(
                "local_queue_admitted_workloads_total").values
            lq_hists = [
                reg.histogram("local_queue_quota_reserved_wait_time_seconds"),
                reg.histogram("local_queue_admission_wait_time_seconds"),
            ]
        for (cq_name, lq), (n, waits, checks_waits) in agg.items():
            cq_l = (cq_name,)
            qr_total[cq_l] += n
            adm_total[cq_l + self._custom_cq_labels(cq_name)] += n
            for h in hists:
                counts = h.counts.get(cq_l)
                if counts is None:
                    counts = h.counts[cq_l] = [0] * (len(h.buckets) + 1)
                s = 0.0
                for v in waits:
                    counts[_bisect.bisect_left(h.buckets, v)] += 1
                    s += v
                h.sums[cq_l] += s
                h.totals[cq_l] += n
            # admission-checks wait: 0.0 for immediate admissions,
            # the real reservation-to-now span for second-pass ones.
            ccounts = checks_h.counts.get(cq_l)
            if ccounts is None:
                ccounts = checks_h.counts[cq_l] = \
                    [0] * (len(checks_h.buckets) + 1)
            ccounts[0] += n - len(checks_waits)
            if checks_waits:
                s = 0.0
                for v in checks_waits:
                    ccounts[_bisect.bisect_left(checks_h.buckets, v)] += 1
                    s += v
                checks_h.sums[cq_l] += s
            checks_h.totals[cq_l] += n
            if lq_on:
                lq_l = (lq,)
                lq_qr_total[lq_l] += n
                lq_adm_total[lq_l] += n
                for h in lq_hists:
                    counts = h.counts.get(lq_l)
                    if counts is None:
                        counts = h.counts[lq_l] = [0] * (len(h.buckets) + 1)
                    s = 0.0
                    for v in waits:
                        counts[_bisect.bisect_left(h.buckets, v)] += 1
                        s += v
                    h.sums[lq_l] += s
                    h.totals[lq_l] += n

    def _admit(self, entry, bulk: "Optional[_BulkAdmitCtx]" = None) -> None:
        """scheduler.go:856 (admit): reserve quota, assume in cache; the
        Admitted condition follows only when all AdmissionChecks are Ready
        (prepareWorkload :912)."""
        wl = entry.obj
        _pt = _perf.begin()
        if bulk is not None:
            # Admission objects are immutable; flyweight them per
            # (CQ, assignment) — bridge assignments are themselves
            # flyweights over scheduling-equivalence classes.
            akey = (entry.info.cluster_queue, id(entry.assignment))
            admission = bulk.admissions.get(akey)
            if admission is None:
                admission = admission_from_assignment(
                    entry.info.cluster_queue, entry.assignment.pod_sets)
                bulk.admissions[akey] = admission
        else:
            admission = admission_from_assignment(
                entry.info.cluster_queue, entry.assignment.pod_sets)
        wl.status.admission = admission
        if bulk is not None:
            # Shared per-cycle Condition instances: every workload in the
            # batch transitions at the same clock with the same reason,
            # so one immutable instance serves them all. A live True
            # reservation (second-pass workloads) keeps its transition
            # time, matching set_condition's semantics.
            prev = wl.status.conditions.get(
                WorkloadConditionType.QUOTA_RESERVED)
            if prev is None or not prev.status:
                wl.status.conditions[
                    WorkloadConditionType.QUOTA_RESERVED] = bulk.qr_cond
            for ctype, cond in bulk.reset_conds:
                if wl.has_condition(ctype):
                    wl.status.conditions[ctype] = cond
        else:
            wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                             reason="QuotaReserved", now=self.clock)
            # Reservation resets the active Evicted / Preempted / blocked-
            # on-gates conditions (workload.go:852-862
            # resetActiveCondition) — without this a re-admitted former
            # victim would still read as evicted and _issue_preemptions'
            # "preemption ongoing" skip would never evict it again.
            for ctype in (WorkloadConditionType.EVICTED,
                          WorkloadConditionType.PREEMPTED,
                          WorkloadConditionType.BLOCKED_ON_PREEMPTION_GATES):
                if wl.has_condition(ctype):
                    wl.set_condition(ctype, False, reason="QuotaReserved",
                                     now=self.clock)
        entry.info.apply_admission(admission)
        _perf.end("apply.diff_build", _pt)
        _pt = _perf.begin()
        self.cache.add_or_update_workload(wl, info=entry.info)
        # The workload left the pending world: free its tensor row (the
        # pending heaps already dropped it at pop/delete time).
        self.queues.rows.on_remove(wl.key)
        _perf.end("apply.rowcache_writeback", _pt)
        # An assumed workload that was itself a pending preemption target
        # satisfies its expectation (scheduler.go:882, kueue#11480).
        self.preemption_expectations.observed_uid(wl.key, wl.uid)
        # The status finalization below is the reference's PATCH to the
        # apiserver (scheduler.go:870 admissionRoutineWrapper.Run). The
        # wrapper here is the before/after instrumentation hook the
        # reference's tests use (scheduler.go:220); it MUST execute the
        # closure inline (SyncWrapper): the closure mutates engine state
        # (conditions, unadmitted tracking, replaced-slice finish), and
        # the engine is lock-free single-threaded by design. ThreadWrapper
        # is for out-of-process appliers only (see utils/routine.py).
        def _finalize() -> None:
            cq_name = entry.info.cluster_queue
            wait = max(0.0, self.clock - wl.creation_time)
            lq = self._lq_key(wl)
            if bulk is not None:
                self._event("QuotaReserved", wl.key, cluster_queue=cq_name,
                            defer_journal=bulk)
                bulk.count("quota_reserved_workloads_total", (cq_name,))
                bulk.wait("quota_reserved_wait_time_seconds", (cq_name,),
                          wait)
                if self._lq_metrics_on():
                    bulk.count(
                        "local_queue_quota_reserved_workloads_total", lq)
                    bulk.wait(
                        "local_queue_quota_reserved_wait_time_seconds",
                        lq, wait)
            else:
                self._event("QuotaReserved", wl.key, cluster_queue=cq_name)
                self.registry.counter(
                    "quota_reserved_workloads_total").inc((cq_name,))
                self.registry.histogram(
                    "quota_reserved_wait_time_seconds").observe(
                    wait, (cq_name,))
                if self._lq_metrics_on():
                    self.registry.counter(
                        "local_queue_quota_reserved_workloads_total"
                    ).inc(lq)
                    self.registry.histogram(
                        "local_queue_quota_reserved_wait_time_seconds"
                    ).observe(wait, lq)
            if self.admission_checks is not None:
                # The UnsatisfiedChecks window only exists when admission
                # checks can actually defer the Admitted condition; with
                # none configured _sync_admitted resolves immediately and
                # the transition would be a wasted gauge round trip.
                self._track_unadmitted(wl, cq_name, "UnsatisfiedChecks")
                self.admission_checks.sync_states(wl,
                                                  entry.info.cluster_queue)
            self._sync_admitted(wl, entry.info.cluster_queue, bulk=bulk)
            # Replace-old-slice after successful admission
            # (scheduler.go:558 replaceOldWorkloadSlice).
            for target in entry.preemption_targets:
                if target.reason == "WorkloadSliceReplaced":
                    self.finish(target.workload.key)

        self.admission_routine.run(_finalize)

    def _sync_admitted(self, wl: Workload, cq_name: str,
                       bulk: "Optional[_BulkAdmitCtx]" = None) -> None:
        """workload.SyncAdmittedCondition."""
        if wl.is_admitted:
            return
        # EVERY check state present in status must be Ready — including
        # states injected by external controllers for checks the CQ
        # doesn't configure (workload/admissionchecks.go:130
        # HasAllChecksReady iterates status, not the CQ's list).
        from kueue_tpu.controllers.admissionchecks import CheckState
        if any(s != CheckState.READY
               for s in wl.status.admission_check_states.values()):
            return
        if (self.admission_checks is not None
                and not self.admission_checks.all_ready(wl, cq_name)):
            return
        self.metrics.admissions_total += 1
        wait = max(0.0, self.clock - wl.creation_time)
        lq = self._lq_key(wl)
        reserved = wl.condition(WorkloadConditionType.QUOTA_RESERVED)
        if bulk is not None:
            wl.status.conditions[WorkloadConditionType.ADMITTED] = \
                bulk.adm_cond
            bulk.count("admitted_workloads_total",
                       (cq_name,) + self._custom_cq_labels(cq_name))
            bulk.wait("admission_wait_time_seconds", (cq_name,), wait)
            if self._lq_metrics_on():
                bulk.count("local_queue_admitted_workloads_total", lq)
                bulk.wait("local_queue_admission_wait_time_seconds", lq,
                          wait)
            if reserved is not None:
                bulk.wait(
                    "admission_checks_wait_time_seconds", (cq_name,),
                    max(0.0, self.clock - reserved.last_transition_time))
            bulk.removed_unadmitted.append(wl.key)
            self._event("Admitted", wl.key, cluster_queue=cq_name,
                        defer_journal=bulk)
        else:
            wl.set_condition(WorkloadConditionType.ADMITTED, True,
                             reason="Admitted", now=self.clock)
            self.registry.counter("admitted_workloads_total").inc(
                (cq_name,) + self._custom_cq_labels(cq_name))
            self.registry.histogram("admission_wait_time_seconds").observe(
                wait, (cq_name,))
            if self._lq_metrics_on():
                self.registry.counter(
                    "local_queue_admitted_workloads_total").inc(lq)
                self.registry.histogram(
                    "local_queue_admission_wait_time_seconds").observe(
                    wait, lq)
            if reserved is not None:
                self.registry.histogram(
                    "admission_checks_wait_time_seconds").observe(
                    max(0.0, self.clock - reserved.last_transition_time),
                    (cq_name,))
            self.unadmitted.remove(wl.key)
            self._event("Admitted", wl.key, cluster_queue=cq_name)
        if self.on_admit is not None:
            self.on_admit(wl, wl.status.admission)

    def reconcile_workload(self, wl: Workload) -> None:
        """The workload-controller pass (core/workload_controller.go:257):
        check-based eviction (:901) and admitted-condition sync."""
        if wl.is_finished or wl.status.admission is None:
            return
        cq_name = wl.status.admission.cluster_queue
        from kueue_tpu.controllers.admissionchecks import CheckState
        states = wl.status.admission_check_states
        required = (self.admission_checks.required_for(cq_name, wl)
                    if self.admission_checks else ())
        if any(states.get(c) == CheckState.REJECTED for c in required):
            # Deactivate before evicting so the journaled eviction state
            # carries active=False (restart must not requeue it).
            wl.active = False
            self.evict(wl, "AdmissionCheckRejected", requeue=False)
            return
        if any(states.get(c) == CheckState.RETRY for c in required):
            # Honor the check's requeue backoff
            # (UpdateAdmissionCheckRequeueState, provisioning
            # controller.go:576): the next attempt waits out the delay.
            backoff = wl.status.check_retry_after_seconds
            wl.status.check_retry_after_seconds = 0.0
            self.evict(wl, "AdmissionCheckRetry", backoff_seconds=backoff)
            for c in required:
                if states.get(c) == CheckState.RETRY:
                    states[c] = CheckState.PENDING
            return
        self._sync_admitted(wl, cq_name)

    def evict(self, wl: Workload, reason: str, requeue: bool = True,
              backoff_seconds: float = 0.0, bulk=None) -> None:
        """Shared eviction path (pkg/workload/evict). ``bulk`` batches
        the observability writes the way bulk admission does; the
        cohort-inadmissible requeue is deferred per cycle when a cycle
        is active (the reference's requeue rides watch events that land
        after schedule() returns)."""
        cq_name = (wl.status.admission.cluster_queue
                   if wl.status.admission else "")
        _adm = wl.condition(WorkloadConditionType.ADMITTED)
        admitted_at = (_adm.last_transition_time
                       if _adm is not None and _adm.status else None)
        # schedulingStats (workload_types.go:728) + the cross-admission
        # execution-time budget (accumulatedPastExecutionTimeSeconds).
        wl.status.eviction_counts[reason] = \
            wl.status.eviction_counts.get(reason, 0) + 1
        if admitted_at is not None:
            wl.status.accumulated_past_execution_time_seconds += \
                max(0.0, self.clock - admitted_at)
        wl.set_condition(WorkloadConditionType.EVICTED, True,
                         reason=reason, now=self.clock)
        wl.set_condition(WorkloadConditionType.ADMITTED, False,
                         reason=reason, now=self.clock)
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, False,
                         reason=reason, now=self.clock)
        wl.status.admission = None
        wl.status.admission_check_states = {}
        wl.status.admission_check_updates = {}
        self.cache.delete_workload(wl.key)
        if bulk is not None:
            bulk.count("evicted_workloads_total",
                       (cq_name, reason) + self._custom_cq_labels(cq_name))
            if self._lq_metrics_on():
                bulk.count("local_queue_evicted_workloads_total",
                           self._lq_key(wl) + (reason,))
        else:
            self.registry.counter("evicted_workloads_total").inc(
                (cq_name, reason) + self._custom_cq_labels(cq_name))
            if self._lq_metrics_on():
                self.registry.counter(
                    "local_queue_evicted_workloads_total").inc(
                    self._lq_key(wl) + (reason,))
        if wl.uid not in self._evicted_once:
            # Keyed by UID: a re-created workload under the same name is
            # a new object with its own first eviction (metrics.go:666).
            self._evicted_once.add(wl.uid)
            if bulk is not None:
                bulk.count("evicted_workloads_once_total",
                           (cq_name, reason))
            else:
                self.registry.counter("evicted_workloads_once_total").inc(
                    (cq_name, reason))
        if admitted_at is not None:
            if bulk is not None:
                bulk.wait("workload_eviction_latency_seconds",
                          (cq_name, reason),
                          max(0.0, self.clock - admitted_at))
            else:
                self.registry.histogram(
                    "workload_eviction_latency_seconds").observe(
                    max(0.0, self.clock - admitted_at), (cq_name, reason))
        self._event("Evicted", wl.key, cluster_queue=cq_name, detail=reason,
                    defer_journal=bulk)
        # The event handlers have now observed the eviction — release any
        # in-flight preemption expectation (the workload_controller
        # Update-event ObservedUID in the reference).
        self.preemption_expectations.observed_uid(wl.key, wl.uid)
        if requeue and wl.active:
            wl.status.requeue_count += 1
            if backoff_seconds:
                wl.status.requeue_at = self.clock + backoff_seconds
            self.queues.add_or_update_workload(wl)
            self._track_unadmitted(wl, cq_name, "Evicted", cause=reason)
            # The requeue bookkeeping mutated status after the Evicted
            # event — persist the final state.
            if bulk is not None:
                bulk.journal_keys.append(wl.key)
            else:
                self._journal_obj("workload", wl)
        else:
            self.unadmitted.remove(wl.key)
        if self._deferred_cohort_requeue is not None:
            self._deferred_cohort_requeue.add(cq_name)
        else:
            self._requeue_cohort_inadmissible(cq_name)

    def _issue_preemptions(self, entry, bulk=None) -> None:
        """preemption.go:194 (IssuePreemptions) + the workload controller's
        requeue-after-evict."""
        for target in entry.preemption_targets:
            if target.reason == "WorkloadSliceReplaced":
                # The old slice keeps running until the replacement admits
                # (workloadslicing.FindReplacedSliceTarget,
                # scheduler.go:450-454).
                continue
            twl = self.workloads.get(target.workload.key)
            if twl is None or twl.is_finished:
                continue
            if twl.has_condition(WorkloadConditionType.EVICTED):
                # Preemption ongoing (preemption.go:209): the target is
                # already evicted — observe and count it preempted.
                self.preemption_expectations.observed_uid(twl.key, twl.uid)
                continue
            if not self.preemption_expectations.satisfied(twl.key):
                # Already issued, waiting for observation
                # (preemption.go:216). With the default synchronous
                # engine the store drains inside evict() below, so this
                # skip only engages when an async/remote applier (MK
                # orchestrated preemption, remote oracle) issued the
                # eviction and its observation is still in flight.
                continue
            self.preemption_expectations.expect_uids(twl.key, [twl.uid])
            twl.set_condition(WorkloadConditionType.PREEMPTED, True,
                              reason=target.reason, now=self.clock)
            self.evict(twl, "Preempted", bulk=bulk)
            self.metrics.preemptions_total += 1
            self._event("Preempted", twl.key,
                        cluster_queue=target.workload.cluster_queue,
                        detail=target.reason, defer_journal=bulk)

    def _requeue(self, entry) -> None:
        """scheduler.go:1016 (requeueAndUpdate)."""
        wl = entry.obj
        if wl.is_finished:
            return
        reason = entry.requeue_reason
        if (entry.status not in (EntryStatus.NOT_NOMINATED,
                                 EntryStatus.INADMISSIBLE)
                and reason == RequeueReason.GENERIC):
            reason = RequeueReason.FAILED_AFTER_NOMINATION
        if reason == RequeueReason.PREEMPTION_GATED:
            # scheduler.go:1046: surface the orchestrated-preemption
            # signal so a coordinator (MultiKueue) can open a gate.
            wl.set_condition(
                WorkloadConditionType.BLOCKED_ON_PREEMPTION_GATES, True,
                reason="PreemptionGated",
                message=entry.inadmissible_msg, now=self.clock)
            # The Requeued _event below persists the condition.
        self.queues.requeue_workload(entry.info, reason)
        self._track_unadmitted(wl, entry.info.cluster_queue, reason.value)
        self._event("Requeued", wl.key,
                    cluster_queue=entry.info.cluster_queue,
                    detail=f"{reason.value}: {entry.inadmissible_msg}")

    def _cohort_root_of(self, cohort_name: str) -> str:
        """Root cohort of a (possibly implicit) cohort, from the live
        registries — no snapshot needed."""
        seen = set()
        name = cohort_name
        while name not in seen:
            seen.add(name)
            co = self.cache.cohorts.get(name)
            if co is None or not co.parent:
                return name
            name = co.parent
        return name  # defensive: cycle (webhooks reject these)

    def _requeue_cohorts_bulk(self, cq_names: set) -> None:
        """One inadmissible-requeue pass over the union of the evicting
        CQs' cohort subtrees (deduped across a whole cycle's victims)."""
        if not cq_names:
            return
        all_names: set = set()
        for cq_name in cq_names:
            cq = self.cache.cluster_queues.get(cq_name)
            if cq is None:
                continue
            if not cq.cohort:
                all_names.add(cq_name)
                continue
            root = self._cohort_root_of(cq.cohort)
            all_names.update(
                name for name, c in self.cache.cluster_queues.items()
                if c.cohort and self._cohort_root_of(c.cohort) == root)
            all_names.add(cq_name)
        if all_names:
            self.queues.queue_inadmissible_workloads(all_names)

    def _requeue_cohort_inadmissible(self, cq_name: str) -> None:
        """Capacity freed: re-activate inadmissible workloads of the cohort
        (manager.go QueueAssociatedInadmissibleWorkloadsAfter). Computed
        from the live registries — building a full snapshot per eviction
        was the preemption-churn hot spot."""
        cq = self.cache.cluster_queues.get(cq_name)
        if cq is None:
            return
        if not cq.cohort:  # None or "" — no cohort membership
            self.queues.queue_inadmissible_workloads({cq_name})
            return
        root = self._cohort_root_of(cq.cohort)
        names = {name for name, c in self.cache.cluster_queues.items()
                 if c.cohort and self._cohort_root_of(c.cohort) == root}
        names.add(cq_name)
        self.queues.queue_inadmissible_workloads(names)

    def _event(self, kind: str, workload: str, cluster_queue: str = "",
               detail: str = "", defer_journal=None) -> None:
        ev = EngineEvent(self.clock, kind, workload, cluster_queue, detail)
        self.events.append(ev)
        # Every workload transition flows through here — persist the
        # post-transition state (the SSA status-patch analog). Bulk
        # cycles defer the write: one journal record per workload at
        # flush time instead of one per condition transition.
        if defer_journal is not None:
            defer_journal.journal_keys.append(workload)
        elif self.journal is not None and workload in self.workloads:
            _pt = _perf.begin()
            self.journal.apply("workload", self.workloads[workload],
                               ts=self.clock)
            _perf.end("apply.journal_append", _pt)
        _pt = _perf.begin()
        for fn in tuple(self.event_listeners):
            # Handler errors must not unwind the scheduling cycle
            # (client-go informers isolate handler panics the same way).
            try:
                fn(ev)
            except Exception as e:  # noqa: BLE001
                import warnings
                warnings.warn(f"event listener {fn!r} raised: {e!r}")
        _perf.end("apply.listener_fanout", _pt)
