"""WaitForPodsReady: gate admissions on previously admitted workloads
becoming ready, evict not-ready workloads after a timeout with exponential
requeue backoff, deactivate after too many requeues.

Reference: cache WaitForPodsReady tracking (pkg/cache/scheduler/
cache.go:199-246), the not-ready timeout eviction + requeuingBackoff
(core/workload_controller.go:1161-1214), and the scheduler's
waitForPodsReadyIfBlocked (scheduler.go:535).
"""

from __future__ import annotations

from dataclasses import dataclass

from kueue_tpu.api.types import Workload, WorkloadConditionType
from kueue_tpu.config.api import WaitForPodsReady


class PodsReadyManager:
    def __init__(self, engine, config: WaitForPodsReady):
        self.engine = engine
        self.config = config
        engine.pods_ready = self

    def mark_pods_ready(self, wl_key: str) -> None:
        """The job-side signal (PodsReady condition)."""
        wl = self.engine.workloads.get(wl_key)
        if wl is None or not wl.is_admitted:
            return
        adm = wl.condition(WorkloadConditionType.ADMITTED)
        wl.set_condition(WorkloadConditionType.PODS_READY, True,
                         reason="PodsReady", now=self.engine.clock)
        if adm is not None:
            self.engine.registry.counter(
                "ready_wait_time_seconds_total").inc(
                (), max(0.0, self.engine.clock - adm.last_transition_time))

    def all_admitted_ready(self) -> bool:
        """cache.PodsReadyForAllAdmittedWorkloads (cache.go:199)."""
        for key in self.engine.cache.workloads:
            wl = self.engine.workloads.get(key)
            if wl is None or not wl.is_admitted:
                continue
            if not wl.has_condition(WorkloadConditionType.PODS_READY):
                return False
        return True

    def _active(self) -> bool:
        """kube_features.go DisableWaitForPodsReady: an emergency
        off-switch over the config's enable flag."""
        from kueue_tpu.config import features
        return (self.config.enable
                and not features.enabled("DisableWaitForPodsReady"))

    def admission_blocked(self) -> bool:
        """scheduler.go:535: with blockAdmission, one not-ready admitted
        workload blocks further admissions."""
        return (self._active() and self.config.block_admission
                and not self.all_admitted_ready())

    def backoff_seconds(self, requeue_count: int) -> float:
        """Exponential requeue backoff
        (workload_controller.go requeuingBackoff)."""
        base = self.config.requeuing_backoff_base_seconds
        return min(float(base) * (2 ** max(0, requeue_count - 1)),
                   float(self.config.requeuing_backoff_max_seconds))

    def reconcile(self) -> None:
        """The not-ready timeout pass (workload_controller.go:1161)."""
        if not self._active():
            return
        now = self.engine.clock
        for key in list(self.engine.cache.workloads):
            wl = self.engine.workloads.get(key)
            if wl is None or not wl.is_admitted or wl.is_finished:
                continue
            if wl.has_condition(WorkloadConditionType.PODS_READY):
                continue
            adm = wl.condition(WorkloadConditionType.ADMITTED)
            if adm is None:
                continue
            if now - adm.last_transition_time <= self.config.timeout_seconds:
                continue
            limit = self.config.requeuing_backoff_limit_count
            if (limit is not None
                    and wl.status.requeue_count >= limit):
                # Deactivate after N requeues (:1214).
                wl.active = False
                self.engine.evict(wl, "RequeuingLimitExceeded",
                                  requeue=False)
                continue
            backoff = self.backoff_seconds(wl.status.requeue_count + 1)
            self.engine.evict(wl, "PodsReadyTimeout",
                              backoff_seconds=backoff)
