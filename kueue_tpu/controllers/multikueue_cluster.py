"""MultiKueue cluster connectivity: remote clients with exponential
reconnect, kubeconfig hot-reload, and origin-labeled orphan GC.

Reference: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go — the per-cluster client lifecycle (retryAfter
backoff :96-103, failedConnAttempts reset/bump :282-290, the Active
condition on the MultiKueueCluster object, runGC :608) — and
fswatch.go, which watches kubeconfig files so credential rotations
rebuild the client without a manager restart. The fsnotify watcher maps
to an mtime poll here (tick() is driven from the controller's reconcile
loop the way the watcher's events drive the reference's reconciler).

The transport is abstracted as ``connect(config) -> worker``: a
callable that builds a live worker handle from the kubeconfig's parsed
contents and raises on failure (bad endpoint, bad credential). Tests
and deployments provide it; the controller only manages the lifecycle.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Optional

# multikueuecluster.go:96 retryAfter: 0, then 2^(min(n, max)-1) * inc.
RETRY_MAX_STEPS = 7
DEFAULT_RETRY_INCREMENT = 1.0

# kueue.MultiKueueOriginLabel: marks remote objects created by this
# manager so runGC only collects its own orphans.
ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


def retry_after(failed_attempts: int,
                increment: float = DEFAULT_RETRY_INCREMENT) -> float:
    """multikueuecluster.go:98 (retryAfter)."""
    if failed_attempts == 0:
        return 0.0
    return float(1 << (min(failed_attempts, RETRY_MAX_STEPS) - 1)) \
        * increment


@dataclass
class ClusterActive:
    """The MultiKueueCluster Active condition surface."""

    status: bool = False
    reason: str = "Pending"
    message: str = ""


class RemoteClient:
    """One worker cluster's client lifecycle (remoteClient in
    multikueuecluster.go): connect from a kubeconfig file, reconnect
    with exponential backoff after failures, rebuild when the file
    changes."""

    def __init__(self, name: str, kubeconfig_path: str,
                 connect: Callable[[dict], object],
                 clock: Callable[[], float],
                 retry_increment: float = DEFAULT_RETRY_INCREMENT):
        self.name = name
        self.kubeconfig_path = kubeconfig_path
        self.connect = connect
        self.clock = clock
        self.retry_increment = retry_increment
        self.worker: Optional[object] = None
        self.failed_attempts = 0
        self.next_attempt_at = 0.0
        self.active = ClusterActive()
        self._mtime: Optional[int] = None

    def _stat_mtime(self) -> Optional[int]:
        try:
            return os.stat(self.kubeconfig_path).st_mtime_ns
        except OSError:
            return None

    def mark_lost(self, reason: str) -> None:
        """Watch-ended / transport-failure event (the reference's
        queueWatchEndedEvent): drop the client and schedule a
        backed-off reconnect (failedConnAttempts++, :289)."""
        self.worker = None
        self.failed_attempts += 1
        self.next_attempt_at = self.clock() + retry_after(
            self.failed_attempts, self.retry_increment)
        self.active = ClusterActive(False, "ClientConnectionLost", reason)

    def tick(self) -> str:
        """One lifecycle step. Returns the transition that happened:
        "" (none), "connected" (a fresh client is live),
        "reconfigured" (kubeconfig changed AND the rebuilt client
        connected in the same step — the old client must be torn down
        before the new one serves), or "disconnected" (kubeconfig
        changed and the rebuild failed — the old client is dead and
        must be torn down NOW; reconnects continue under backoff)."""
        now = self.clock()
        mtime = self._stat_mtime()
        reconfigured = False
        if mtime != self._mtime:
            # fswatch.go: the kubeconfig changed — rebuild immediately
            # (credential rotation must not wait out a backoff). While
            # DISCONNECTED the same rule cancels any accumulated
            # reconnect backoff: the operator just rotated the
            # credentials the backoff was waiting on.
            if self.worker is not None:
                self.worker = None
                self.active = ClusterActive(False, "KubeconfigChanged", "")
                reconfigured = True
            self.next_attempt_at = now
            self._mtime = mtime
        if self.worker is None and now >= self.next_attempt_at:
            try:
                with open(self.kubeconfig_path, encoding="utf-8") as f:
                    config = json.load(f)
                self.worker = self.connect(config)
                self._mtime = mtime
                self.failed_attempts = 0
                self.active = ClusterActive(True, "Active", "Connected")
                return "reconfigured" if reconfigured else "connected"
            except Exception as e:  # noqa: BLE001 — any connect failure
                self.failed_attempts += 1
                self.next_attempt_at = now + retry_after(
                    self.failed_attempts, self.retry_increment)
                self.active = ClusterActive(
                    False, "ClientConnectionFailed", str(e)[:200])
                if reconfigured:
                    return "disconnected"
        return ""
