"""MultiKueue cluster connectivity: remote clients with exponential
reconnect, kubeconfig hot-reload, and origin-labeled orphan GC.

Reference: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go — the per-cluster client lifecycle (retryAfter
backoff :96-103, failedConnAttempts reset/bump :282-290, the Active
condition on the MultiKueueCluster object, runGC :608) — and
fswatch.go, which watches kubeconfig files so credential rotations
rebuild the client without a manager restart. The fsnotify watcher maps
to an mtime poll here (tick() is driven from the controller's reconcile
loop the way the watcher's events drive the reference's reconciler).

The transport is abstracted as ``connect(config) -> worker``: a
callable that builds a live worker handle from the kubeconfig's parsed
contents and raises on failure (bad endpoint, bad credential). Tests
and deployments provide it; the controller only manages the lifecycle.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Optional

# multikueuecluster.go:96 retryAfter: 0, then 2^(min(n, max)-1) * inc.
RETRY_MAX_STEPS = 7
DEFAULT_RETRY_INCREMENT = 1.0

# kueue.MultiKueueOriginLabel: marks remote objects created by this
# manager so runGC only collects its own orphans.
ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


class _GateDisabled(RuntimeError):
    """ClusterProfile source used while MultiKueueClusterProfile is
    off."""


def retry_after(failed_attempts: int,
                increment: float = DEFAULT_RETRY_INCREMENT) -> float:
    """multikueuecluster.go:98 (retryAfter)."""
    if failed_attempts == 0:
        return 0.0
    return float(1 << (min(failed_attempts, RETRY_MAX_STEPS) - 1)) \
        * increment


@dataclass
class ClusterActive:
    """The MultiKueueCluster Active condition surface."""

    status: bool = False
    reason: str = "Pending"
    message: str = ""


@dataclass
class ClusterProfile:
    """cluster-inventory-api ClusterProfile, reduced to what the access
    provider consumes (multikueuecluster.go:716
    clusterProfileAccessProvider.BuildConfigFromCP)."""

    name: str
    config: dict = None
    generation: int = 0


class ClusterProfileRegistry:
    """The ClusterProfile object store + access provider: resolves a
    profile reference into a connection config. Registering a profile
    bumps its generation, the analog of the watch event that re-triggers
    the cluster reconciler (multikueuecluster.go:836)."""

    def __init__(self):
        self._profiles: dict[str, ClusterProfile] = {}
        self._gen = 0  # registry-wide, survives delete: a
        # delete + re-register rotation between ticks must still
        # present a NEW generation to the change detector.

    def register(self, profile: ClusterProfile) -> None:
        self._gen += 1
        profile.generation = self._gen
        self._profiles[profile.name] = profile

    def delete(self, name: str) -> None:
        self._profiles.pop(name, None)

    def get(self, name: str) -> Optional[ClusterProfile]:
        return self._profiles.get(name)

    def build_config(self, name: str) -> dict:
        """BuildConfigFromCP: raises on a missing profile (reconcile
        re-triggers when the ClusterProfile is created,
        multikueuecluster.go:836)."""
        profile = self._profiles.get(name)
        if profile is None or profile.config is None:
            raise KeyError(f"ClusterProfile {name!r} not found")
        return profile.config


class RemoteClient:
    """One worker cluster's client lifecycle (remoteClient in
    multikueuecluster.go): connect from a kubeconfig file OR a
    ClusterProfile reference (ClusterSource is exactly one of the two,
    multikueue_types.go ClusterSource), reconnect with exponential
    backoff after failures, rebuild when the source changes. The
    ClusterProfile source is gated by MultiKueueClusterProfile
    (multikueuecluster.go:859: gate off => Active=False with reason
    MultiKueueClusterProfileFeatureDisabled)."""

    def __init__(self, name: str, kubeconfig_path: str = None,
                 connect: Callable[[dict], object] = None,
                 clock: Callable[[], float] = None,
                 retry_increment: float = DEFAULT_RETRY_INCREMENT,
                 cluster_profile: str = None,
                 profiles: Optional[ClusterProfileRegistry] = None):
        if (kubeconfig_path is None) == (cluster_profile is None):
            raise ValueError("exactly one of kubeconfig_path and "
                             "cluster_profile must be set")
        self.name = name
        self.kubeconfig_path = kubeconfig_path
        self.cluster_profile = cluster_profile
        self.profiles = profiles
        self.connect = connect
        self.clock = clock
        self.retry_increment = retry_increment
        self.worker: Optional[object] = None
        self.failed_attempts = 0
        self.next_attempt_at = 0.0
        self.active = ClusterActive()
        self._mtime: Optional[int] = None

    def _stat_mtime(self):
        if self.kubeconfig_path is None:
            # ClusterProfile source: the profile's generation is the
            # change signal (a re-registered profile bumps it, the
            # watch-event analog); the gate state participates so a
            # flip re-triggers connection handling immediately.
            from kueue_tpu.config import features
            if not features.enabled("MultiKueueClusterProfile"):
                return "gate-disabled"
            profile = (self.profiles.get(self.cluster_profile)
                       if self.profiles is not None else None)
            return None if profile is None else profile.generation
        try:
            return os.stat(self.kubeconfig_path).st_mtime_ns
        except OSError:
            return None

    def _load_config(self) -> dict:
        if self.kubeconfig_path is not None:
            with open(self.kubeconfig_path, encoding="utf-8") as f:
                return json.load(f)
        from kueue_tpu.config import features
        if not features.enabled("MultiKueueClusterProfile"):
            raise _GateDisabled(
                "MultiKueueClusterProfile feature gate is disabled")
        if self.profiles is None:
            raise KeyError("no ClusterProfile registry attached")
        return self.profiles.build_config(self.cluster_profile)

    def mark_lost(self, reason: str) -> None:
        """Watch-ended / transport-failure event (the reference's
        queueWatchEndedEvent): drop the client and schedule a
        backed-off reconnect (failedConnAttempts++, :289)."""
        self.worker = None
        self.failed_attempts += 1
        self.next_attempt_at = self.clock() + retry_after(
            self.failed_attempts, self.retry_increment)
        self.active = ClusterActive(False, "ClientConnectionLost", reason)

    def tick(self) -> str:
        """One lifecycle step. Returns the transition that happened:
        "" (none), "connected" (a fresh client is live),
        "reconfigured" (kubeconfig changed AND the rebuilt client
        connected in the same step — the old client must be torn down
        before the new one serves), or "disconnected" (kubeconfig
        changed and the rebuild failed — the old client is dead and
        must be torn down NOW; reconnects continue under backoff)."""
        now = self.clock()
        mtime = self._stat_mtime()
        reconfigured = False
        if mtime != self._mtime:
            # fswatch.go: the kubeconfig changed — rebuild immediately
            # (credential rotation must not wait out a backoff). While
            # DISCONNECTED the same rule cancels any accumulated
            # reconnect backoff: the operator just rotated the
            # credentials the backoff was waiting on.
            if self.worker is not None:
                self.worker = None
                self.active = ClusterActive(False, "KubeconfigChanged", "")
                reconfigured = True
            self.next_attempt_at = now
            self._mtime = mtime
        if self.worker is None and now >= self.next_attempt_at:
            try:
                config = self._load_config()
                self.worker = self.connect(config)
                self._mtime = mtime
                self.failed_attempts = 0
                self.active = ClusterActive(True, "Active", "Connected")
                return "reconfigured" if reconfigured else "connected"
            except _GateDisabled as e:
                # multikueuecluster.go:859: no backoff churn — the gate
                # flip itself re-triggers via the source version.
                self.active = ClusterActive(
                    False, "MultiKueueClusterProfileFeatureDisabled",
                    str(e))
                if reconfigured:
                    return "disconnected"
            except Exception as e:  # noqa: BLE001 — any connect failure
                self.failed_attempts += 1
                self.next_attempt_at = now + retry_after(
                    self.failed_attempts, self.retry_increment)
                self.active = ClusterActive(
                    False, "ClientConnectionFailed", str(e)[:200])
                if reconfigured:
                    return "disconnected"
        return ""
