"""Fenced lease file: leader election with a monotonic epoch token.

The PR 1 lease (`utils/leaderelection.py`) proved single-active-
scheduler handoff but its lease carries no fencing token: a deposed
leader that wakes from a long stall cannot be told apart from the
current one by anything it writes. This lease adds the classic fencing
fix — a **monotonic epoch** bumped on every acquisition by a new
holder term. Writers stamp their epoch into what they write (the
``ha_digest`` journal records) and check it before committing
(`store.journal.Journal.fence`), so a stale leader's writes are refused
rather than interleaved.

Durability discipline mirrors ``store/journal.py``: the lease is a
small JSON file written atomically (tempfile + fsync + rename) and
every read-modify-write runs under an fcntl lock on a sidecar file —
the CAS the reference gets from the API server's resourceVersion.
Without it two standbys could both read an expired lease and both
"win" the same epoch.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass
from typing import Optional


@dataclass
class LeaseState:
    """coordination.k8s.io/v1 Lease plus the fencing epoch."""

    holder: str = ""
    epoch: int = 0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration_seconds: float = 15.0

    def expired(self, now: float) -> bool:
        return (not self.holder
                or now - self.renew_time > self.lease_duration_seconds)


class FencedLease:
    """The durable lock object. All mutations are epoch-monotonic:
    ``epoch`` never decreases, and acquisition of a free/expired lease
    bumps it — each leadership term owns exactly one epoch."""

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"

    def _locked(self):
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def _hold():
            with open(self._lock_path, "a+") as lock_fh:
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
        return _hold()

    def read(self) -> Optional[LeaseState]:
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None
        return LeaseState(**raw)

    def _write(self, lease: LeaseState) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.path) or ".")
        with os.fdopen(fd, "w") as f:
            json.dump(vars(lease), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- the three verbs, each a single critical section --

    def try_acquire(self, identity: str, now: float,
                    duration: float) -> Optional[LeaseState]:
        """Acquire when free/expired (epoch bumps), renew when already
        held by ``identity`` (epoch unchanged — same term). Returns the
        held LeaseState, or None when another live holder owns it."""
        with self._locked():
            current = self.read()
            if current is not None and current.holder == identity:
                current.renew_time = now
                self._write(current)
                return current
            if current is None or current.expired(now):
                state = LeaseState(
                    holder=identity,
                    epoch=(current.epoch if current else 0) + 1,
                    acquire_time=now, renew_time=now,
                    lease_duration_seconds=duration)
                self._write(state)
                return state
        return None

    def renew(self, identity: str, epoch: int,
              now: float) -> Optional[LeaseState]:
        """Renew only our own term: holder AND epoch must still match —
        a renewed lease under a different epoch means we were deposed
        and re-elected without noticing, which the fencing contract
        treats as loss."""
        with self._locked():
            current = self.read()
            if (current is not None and current.holder == identity
                    and current.epoch == epoch):
                current.renew_time = now
                self._write(current)
                return current
        return None

    def release(self, identity: str) -> None:
        """Graceful handoff (ReleaseOnCancel): clear the holder but KEEP
        the epoch — the next acquirer must still fence us out."""
        with self._locked():
            current = self.read()
            if current is not None and current.holder == identity:
                self._write(LeaseState(
                    epoch=current.epoch,
                    lease_duration_seconds=current
                    .lease_duration_seconds))

    def epoch_of(self) -> int:
        current = self.read()
        return current.epoch if current is not None else 0
