"""Failover digest verification: prove the journal reproduces the
leader's decisions before a follower accepts writes.

Two digests, both deterministic functions of journal content:

  * **decision chain** — the flight recorder's CRC chain
    (replay/trace.py decision_digest) over every non-idle cycle's
    canonical decision record. The leader carries it across cycles;
    a promoting follower seeds its own chain from the last checkpoint
    so the stream digest spans leadership terms.
  * **admitted-state digest** — an order-canonical CRC over the
    engine's current applied admissions (key + full Admission object).
    Computable live on the leader AND from a journal rebuild, which is
    what makes promotion *checkable*: replay to head must land on the
    exact state the dead leader checkpointed.

The leader journals one ``ha_digest`` record per non-idle cycle from a
pre-sync hook (Engine.pre_sync_hooks) — the record rides INSIDE the
cycle's fsync boundary, so a checkpoint can never describe admissions
the platter doesn't hold. ``ha_digest`` is declared in
store.journal.EPHEMERAL_KINDS: rebuild skips it by design (pure
verification rationale, no engine state), and graftlint R1 enforces
the registration.

Crash anatomy a promotion must handle: a SIGKILL mid-apply leaves the
journal with workload records AFTER the last checkpoint (the partially
applied cycle's durable admissions). Those are applied admissions —
the zero-loss contract forbids dropping them — so verification splits:
the checkpointed PREFIX must rebuild to digest identity, and the tail
is adopted as-is (the PR 2 crash-recovery semantics).
"""

from __future__ import annotations

import json
import zlib
from typing import Optional

HEAD_KEY = "head"  # single logical journal key for ha_digest records


def _canon_crc(obj) -> int:
    return zlib.crc32(json.dumps(obj, sort_keys=True,
                                 separators=(",", ":")).encode("utf-8"))


def admitted_state_digest(engine) -> str:
    """Order-canonical digest of the engine's applied admissions:
    sorted (key, Admission) pairs, serde-canonical JSON, CRC-32.
    Identical for a live leader and a journal rebuild of the same
    state — the promotion verification invariant."""
    from kueue_tpu.api.serde import to_jsonable

    rows = []
    for key in sorted(engine.workloads):
        wl = engine.workloads[key]
        if wl.is_finished or wl.status.admission is None:
            continue
        rows.append([key, to_jsonable(wl.status.admission)])
    return f"{_canon_crc(rows):08x}"


class DigestChain:
    """Leader-side checkpoint writer. Registered on
    ``engine.pre_sync_hooks`` so each non-idle cycle's checkpoint is
    appended AFTER the cycle's workload records and BEFORE the
    crash-safe fsync: one atomic durability unit per cycle."""

    def __init__(self, engine, epoch: int, seed_chain: int = 0,
                 seed_seq: int = -1):
        self.engine = engine
        self.epoch = epoch
        self.chain = seed_chain
        self.last_seq = seed_seq
        self.cycles = 0
        self._hook = self._on_pre_sync
        engine.pre_sync_hooks.append(self._hook)

    def _on_pre_sync(self, seq: int, result) -> None:
        from kueue_tpu.obs.span import correlation_id
        from kueue_tpu.replay.trace import canonical_decisions, \
            decision_digest

        decisions = canonical_decisions(result)
        self.chain = decision_digest(decisions, self.chain)
        self.last_seq = seq
        self.cycles += 1
        self.engine.journal.apply("ha_digest", {
            "name": HEAD_KEY,
            "seq": seq,
            "epoch": self.epoch,
            "chain": f"{self.chain:08x}",
            "state": admitted_state_digest(self.engine),
            "cid": correlation_id(seq, decisions),
        }, ts=self.engine.clock)

    @property
    def digest(self) -> str:
        return f"{self.chain:08x}"

    def detach(self) -> None:
        try:
            self.engine.pre_sync_hooks.remove(self._hook)
        except ValueError:
            pass


def last_checkpoint(records) -> tuple:
    """(index, record-or-None) of the final ha_digest record."""
    idx, found = -1, None
    for i, rec in enumerate(records):
        if rec.get("kind") == "ha_digest" and rec.get("op") == "apply":
            idx, found = i, rec
    return idx, found


def verify_promotion(records, rebuilt_engine,
                     new_epoch: Optional[int] = None,
                     base_records: Optional[list] = None,
                     base_meta=None) -> dict:
    """The promotion gate: given the journal's records (replayed to
    head) and the engine rebuilt from them, prove digest identity
    against the dead leader's last checkpoint.

    Returns a report dict; ``verified`` False means the journal does
    NOT reproduce the checkpointed state — the candidate must fence,
    not lead. ``chain_seed``/``seq_seed`` carry the decision chain
    forward into the new term's DigestChain.

    Checkpoint+suffix boot (store/checkpoint.py): ``records`` is then
    only the journal SUFFIX, ``base_records`` the sealed checkpoint's
    payload and ``base_meta`` its header. A sealed checkpoint embeds
    the same chain/state digests an ``ha_digest`` record carries, so
    when the suffix holds no ha_digest of its own the verification
    anchors on the sealed header — same protocol, older anchor."""
    report = {
        "verified": True,
        "checkpoint_seq": None,
        "checkpoint_epoch": 0,
        "chain_seed": 0,
        "seq_seed": -1,
        "partial_cycle": False,
        "source": "journal",
        "rebuilt_state": admitted_state_digest(rebuilt_engine),
        "checkpoint_state": None,
        "reason": "no checkpoint (fresh journal)",
    }
    base_records = base_records or []
    idx, ckpt = last_checkpoint(records)
    if ckpt is None and base_meta is not None:
        # No ha_digest in the suffix: anchor on the sealed checkpoint.
        report.update({
            "source": "sealed-checkpoint",
            "checkpoint_seq": base_meta.seq,
            "checkpoint_epoch": int(base_meta.epoch),
            "chain_seed": int(base_meta.chain or "0", 16),
            "seq_seed": int(base_meta.chain_seq),
            "checkpoint_state": base_meta.state,
        })
        if new_epoch is not None and base_meta.epoch >= new_epoch:
            report["verified"] = False
            report["reason"] = (
                f"fencing violation: sealed checkpoint epoch "
                f"{base_meta.epoch} >= new epoch {new_epoch}")
            return report
        tail_writes = [r for r in records
                       if r.get("kind") == "workload"]
        if not tail_writes:
            ok = report["rebuilt_state"] == base_meta.state
            report["verified"] = ok
            report["reason"] = (
                "digest identity at sealed checkpoint" if ok else
                f"state digest mismatch: rebuilt "
                f"{report['rebuilt_state']} != sealed checkpoint "
                f"{base_meta.state}")
            return report
        from kueue_tpu.store.journal import engine_from_records

        prefix_state = admitted_state_digest(
            engine_from_records(list(base_records)))
        ok = prefix_state == base_meta.state
        report["partial_cycle"] = True
        report["verified"] = ok
        report["reason"] = (
            f"sealed-checkpoint prefix digest identity + "
            f"{len(tail_writes)} adopted partial-cycle record(s)"
            if ok else
            f"sealed-checkpoint prefix state digest mismatch: "
            f"{prefix_state} != {base_meta.state}")
        return report
    if ckpt is None:
        return report
    obj = ckpt["obj"]
    report.update({
        "checkpoint_seq": obj.get("seq"),
        "checkpoint_epoch": int(obj.get("epoch", 0)),
        "chain_seed": int(obj.get("chain", "0"), 16),
        "seq_seed": int(obj.get("seq", -1)),
        "checkpoint_state": obj.get("state"),
    })
    if new_epoch is not None and report["checkpoint_epoch"] >= new_epoch:
        report["verified"] = False
        report["reason"] = (
            f"fencing violation: checkpoint epoch "
            f"{report['checkpoint_epoch']} >= new epoch {new_epoch}")
        return report
    tail_writes = [r for r in records[idx + 1:]
                   if r.get("kind") == "workload"]
    if not tail_writes:
        # Clean boundary (leader died between cycles): the rebuilt
        # state must BE the checkpointed state.
        ok = report["rebuilt_state"] == obj.get("state")
        report["verified"] = ok
        report["reason"] = ("digest identity at checkpoint" if ok else
                            f"state digest mismatch: rebuilt "
                            f"{report['rebuilt_state']} != checkpoint "
                            f"{obj.get('state')}")
        return report
    # Crash mid-cycle: workload records landed after the checkpoint.
    # Verify the checkpointed PREFIX reproduces byte-identically, then
    # adopt the tail (durable applied admissions — dropping them would
    # violate zero-loss).
    from kueue_tpu.store.journal import engine_from_records

    prefix_engine = engine_from_records(base_records + records[:idx + 1])
    prefix_state = admitted_state_digest(prefix_engine)
    ok = prefix_state == obj.get("state")
    report["partial_cycle"] = True
    report["verified"] = ok
    report["reason"] = (
        f"prefix digest identity + {len(tail_writes)} adopted "
        f"partial-cycle record(s)" if ok else
        f"prefix state digest mismatch: {prefix_state} != "
        f"{obj.get('state')}")
    return report
