"""Replica role state machine.

Roles and legal transitions (ARCHITECTURE.md "HA serving plane"):

    follower ──► candidate ──► leader
        ▲            │            │
        │            ▼            ▼
        └──────── follower      fenced   (terminal)
                                 ▲
    leader ──────────────────────┘  (lease stolen / digest mismatch)

  * follower   — tails the journal, serves reads/SSE, never writes
  * candidate  — won the lease; replaying the journal to head and
                 verifying the decision digest BEFORE accepting writes
  * leader     — runs admission cycles, renews the lease, journals
  * fenced     — terminal: the replica observed a newer epoch (or a
                 digest mismatch) and must never write again; it keeps
                 serving reads until restarted

Transitions are checked, not implicit: an illegal hop (e.g. follower →
leader without the candidate verification step) raises
RoleTransitionError — the state machine IS the protocol document.
"""

from __future__ import annotations

from typing import Callable, Optional

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"
FENCED = "fenced"

ROLES = (FOLLOWER, CANDIDATE, LEADER, FENCED)

# Promotion must pass through CANDIDATE (the replay-verification gate);
# FENCED is terminal; a candidate that loses the race or fails
# verification falls back to follower or fences.
_LEGAL = {
    (FOLLOWER, CANDIDATE),
    (CANDIDATE, LEADER),
    (CANDIDATE, FOLLOWER),
    (CANDIDATE, FENCED),
    (LEADER, FOLLOWER),
    (LEADER, FENCED),
}

# ha_role gauge encoding (stable across releases — dashboards key on it).
ROLE_CODES = {FOLLOWER: 0, LEADER: 1, CANDIDATE: 2, FENCED: 3}


class RoleTransitionError(Exception):
    """An illegal role hop: the caller skipped a protocol step."""


class RoleMachine:
    """Current role + transition log. ``listeners`` fire with
    (old, new, reason) after every successful transition."""

    def __init__(self, initial: str = FOLLOWER):
        if initial not in ROLES:
            raise ValueError(f"unknown role {initial!r}")
        self.role = initial
        self.listeners: list[Callable] = []
        # (old, new, reason) in order — the audit trail /debug/ha shows.
        self.transitions: list[tuple] = []

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    @property
    def is_fenced(self) -> bool:
        return self.role == FENCED

    def to(self, new: str, reason: str = "") -> None:
        if new not in ROLES:
            raise ValueError(f"unknown role {new!r}")
        old = self.role
        if old == new:
            return
        if (old, new) not in _LEGAL:
            raise RoleTransitionError(
                f"illegal role transition {old} -> {new}"
                f"{f' ({reason})' if reason else ''}")
        self.role = new
        self.transitions.append((old, new, reason))
        for fn in tuple(self.listeners):
            try:
                fn(old, new, reason)
            except Exception as e:  # noqa: BLE001 — observers must not
                import warnings      # unwind the control loop
                warnings.warn(f"role listener {fn!r} raised: {e!r}")

    def history(self, last: Optional[int] = None) -> list:
        rows = [{"from": o, "to": n, "reason": r}
                for o, n, r in self.transitions]
        return rows[-last:] if last else rows
