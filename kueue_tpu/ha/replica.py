"""HAReplica: the per-process orchestrator serve.py runs in HA mode.

One replica = one role at a time (roles.RoleMachine). The drive loop
calls ``step(now)`` every tick:

  * follower — tail the journal (read model + SSE synthesis), then try
    the lease; winning it starts the candidate promotion protocol.
  * candidate (transient, inside ``_promote``) — replay the journal to
    head, verify the last ``ha_digest`` checkpoint (digest.py), and
    only then attach a WRITABLE journal handle and go leader.
  * leader — renew the lease every ``renew_interval``; a failed renew
    (holder or epoch mismatch: we were deposed) fences the replica
    before the next journal write can land. Renewal runs on a
    background thread (``renew_in_background``) so a long admission
    cycle can't starve it past the lease — the drive-loop renewal in
    ``step`` remains as a backstop.
  * fenced — terminal. Keeps tailing for reads; never writes again.

The journal handle a leader holds carries a fence callable
(store.journal.Journal.fence): every append re-checks
``roles.is_leader`` inside the flock critical section, so a deposed
leader's in-flight cycle dies on JournalFenced instead of interleaving
stale writes with the new leader's.
"""

from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict
from typing import Callable, Optional

from kueue_tpu.ha.digest import DigestChain, admitted_state_digest, \
    verify_promotion
from kueue_tpu.ha.lease import FencedLease
from kueue_tpu.ha.roles import (
    CANDIDATE,
    FENCED,
    FOLLOWER,
    LEADER,
    ROLE_CODES,
    RoleMachine,
)
from kueue_tpu.ha.shedder import AdmissionShedder
from kueue_tpu.ha.tailer import JournalTailer


class HAReplica:
    def __init__(self, journal_path: str, lease_path: str, identity: str,
                 lease_duration: float = 15.0,
                 renew_interval: Optional[float] = None,
                 hub=None, shedder: Optional[AdmissionShedder] = None,
                 metrics=None, fsync: bool = True,
                 engine_kwargs: Optional[dict] = None,
                 on_promote: Optional[Callable] = None,
                 on_demote: Optional[Callable] = None,
                 renew_in_background: bool = True,
                 checkpoint_interval: int = 0,
                 checkpoint_keep: int = 2,
                 segment_rotate_bytes: Optional[int] = None,
                 segment_rotate_records: Optional[int] = None,
                 retain_segments: bool = True,
                 dedup_capacity: int = 4096,
                 min_free_bytes: int = 0):
        self.journal_path = journal_path
        # Disk budget (store/diskguard.py): the promoted leader's
        # journal refuses appends below this free-space floor and the
        # submit path sheds with 503 until the budget re-arms.
        self.min_free_bytes = int(min_free_bytes)
        # Bounded-time recovery knobs (store/checkpoint.py): a leader
        # with checkpoint_interval > 0 writes sealed checkpoints every
        # N non-idle cycles and rotates the journal into segments;
        # promotion then boots from checkpoint + suffix.
        self.checkpoint_interval = int(checkpoint_interval)
        self.checkpoint_keep = int(checkpoint_keep)
        self.segment_rotate_bytes = segment_rotate_bytes
        self.segment_rotate_records = segment_rotate_records
        self.retain_segments = retain_segments
        self.identity = identity
        self.lease = FencedLease(lease_path)
        self.lease_duration = float(lease_duration)
        self.renew_interval = float(
            renew_interval if renew_interval is not None
            else lease_duration / 3.0)
        self.roles = RoleMachine(FOLLOWER)
        self.hub = hub
        self.shedder = shedder
        self.metrics = metrics
        self.fsync = fsync
        self.engine_kwargs = dict(engine_kwargs or {})
        self.on_promote = on_promote
        self.on_demote = on_demote
        self.epoch = 0
        # Bounded submit dedup map: key -> submit time, for in-flight
        # idempotent-retry acks. Entries are evicted by the post-sync
        # cycle listener once the admission is durably journaled (from
        # then on engine.workloads + the journal answer retries), so
        # the map stays O(in-flight), not O(every name ever submitted).
        # ``dedup_capacity`` is the hard backstop on top of that:
        # insertion order (OrderedDict) evicts the OLDEST entry at the
        # bound, so a submit storm that outruns the cycle listener
        # cannot grow the map without limit. An evicted key whose
        # workload is also gone from engine.workloads re-acks as a
        # fresh 201, not a stale idempotent 200 — pinned by
        # tests/test_ha_replica.py.
        self.dedup_capacity = max(1, int(dedup_capacity))
        self._inflight_submits: OrderedDict = OrderedDict()
        # Federation fencing surface: key -> fence epoch at revocation.
        # A handoff replay carrying a route epoch <= the recorded one
        # is refused with 409 (the zombie double-admit guard); a NEWER
        # epoch means the dispatcher deliberately routed the key back
        # here and clears the tombstone.
        self._revoked: dict = {}
        self.route_epoch = 0
        self.engine = None              # live engine (leader only)
        self.digest_chain: Optional[DigestChain] = None
        self.promotion_report: Optional[dict] = None
        self.tailer = JournalTailer(journal_path, hub=hub,
                                    metrics=metrics,
                                    engine_kwargs=self.engine_kwargs)
        self.suspend_renewal = False    # fault hook: lease-stall@cycle:N
        self._last_renew = 0.0
        # Renewal thread (leaders only): an admission cycle larger than
        # the lease window must not depose a healthy leader. Tests that
        # drive step() with a synthetic clock pass False — a wall-clock
        # renewal would pin the lease un-expirable under synthetic time.
        self.renew_in_background = renew_in_background
        self._renew_stop: Optional[threading.Event] = None
        self._fence_lock = threading.Lock()
        self.roles.listeners.append(self._on_transition)
        # A follower must serve reads from tick zero (an empty journal
        # rebuilds to an empty engine, not a 503).
        self.tailer.rebuild()

    # -- role-keyed engine access (the HTTP layer resolves per request
    # because promotion SWAPS the engine object) --

    def engine_ref(self):
        """Current engine to serve reads from: the live engine when
        leading, the tailer's read model otherwise."""
        if self.roles.is_leader and self.engine is not None:
            return self.engine
        return self.tailer.engine

    # -- the drive loop --

    def step(self, now: float) -> str:
        """One HA tick. Returns the post-tick role."""
        role = self.roles.role
        if role == LEADER:
            self._leader_tick(now)
        elif role == FOLLOWER:
            self.tailer.poll()
            state = self.lease.try_acquire(self.identity, now,
                                           self.lease_duration)
            if state is not None:
                self._last_renew = now
                self._promote(state)
        else:  # fenced: read-only forever, but stay a useful follower
            self.tailer.poll()
        self._export(now)
        return self.roles.role

    def _leader_tick(self, now: float) -> None:
        if self.suspend_renewal:
            return  # fault injection: let the lease expire underneath us
        if now - self._last_renew < self.renew_interval:
            return
        state = self.lease.renew(self.identity, self.epoch, now)
        if state is None:
            # Holder or epoch moved on: we were deposed. Fence BEFORE
            # any further journal write (the journal fence backstops
            # writes already in flight).
            self._fence("lease renewal refused (deposed)")
            return
        self._last_renew = now

    def _renew_loop(self, stop: threading.Event) -> None:
        """Leader-lifetime renewal thread: keeps the lease alive even
        when one admission cycle runs longer than the lease window (the
        drive loop only reaches ``step`` between cycles). A refused
        renew fences exactly like the in-loop path."""
        while not stop.wait(self.renew_interval):
            if not self.roles.is_leader:
                return
            if self.suspend_renewal:
                continue  # fault injection: let the lease expire
            now = _time.time()
            if self.lease.renew(self.identity, self.epoch, now) is None:
                self._fence("lease renewal refused (deposed)")
                return
            self._last_renew = now

    # -- promotion: the replay-verified failover protocol --

    def _promote(self, lease_state) -> None:
        from kueue_tpu.store.checkpoint import recover_records
        from kueue_tpu.store.journal import Journal, engine_from_records
        from kueue_tpu.store.journal import _key_of as _journal_key_of

        self.roles.to(CANDIDATE,
                      f"lease acquired epoch={lease_state.epoch}")
        # Journal() repairs a torn tail (the dead leader's SIGKILL
        # mid-append) under the journal flock before we read. Recovery
        # is checkpoint base + suffix when a sealed checkpoint exists
        # (O(delta) promotion), full genesis replay otherwise — and
        # verify_promotion proves digest identity either way.
        reader = Journal(self.journal_path)
        base, suffix, ckpt_meta = recover_records(reader)
        if ckpt_meta is None:
            base, suffix = [], list(reader.replay())
        reader.close()
        engine = engine_from_records(base + suffix, **self.engine_kwargs)
        if ckpt_meta is not None:
            engine.clock = max(engine.clock, ckpt_meta.clock)
        report = verify_promotion(suffix, engine,
                                  new_epoch=lease_state.epoch,
                                  base_records=base,
                                  base_meta=ckpt_meta)
        self.promotion_report = report
        if not report["verified"]:
            self.lease.release(self.identity)
            self.roles.to(FENCED,
                          f"promotion verification failed: "
                          f"{report['reason']}")
            return
        self.epoch = lease_state.epoch
        journal = Journal(self.journal_path, fsync=self.fsync,
                          rotate_bytes=self.segment_rotate_bytes,
                          rotate_records=self.segment_rotate_records,
                          min_free_bytes=self.min_free_bytes,
                          metrics=self.metrics)
        journal.fence = self._write_allowed
        if base:
            journal.seed_generations(
                {(r["kind"], _journal_key_of(r)): int(r.get("gen", 0))
                 for r in base if r.get("gen")})
        engine.attach_journal(journal, record_existing=False)
        engine.ha = self
        self.digest_chain = DigestChain(
            engine, self.epoch,
            seed_chain=report["chain_seed"],
            seed_seq=report["seq_seed"])
        if self.checkpoint_interval > 0:
            from kueue_tpu.store.checkpoint import Checkpointer
            Checkpointer(engine, interval=self.checkpoint_interval,
                         keep=self.checkpoint_keep,
                         retain_segments=self.retain_segments)
        self._inflight_submits.clear()
        engine.cycle_listeners.append(self._evict_submit_dedup)
        self.engine = engine
        if self.hub is not None:
            self.hub.attach_engine(engine)
        self.roles.to(LEADER,
                      f"verified: {report['reason']}")
        if self.renew_in_background:
            self._renew_stop = threading.Event()
            threading.Thread(
                target=self._renew_loop, args=(self._renew_stop,),
                name=f"ha-renew-{self.identity}", daemon=True).start()
        if self.on_promote is not None:
            self.on_promote(engine, self)

    def _write_allowed(self) -> bool:
        """Journal fence predicate, evaluated inside the append flock."""
        return self.roles.is_leader

    def _fence(self, reason: str) -> None:
        # Idempotent and thread-safe: the renewal thread and the drive
        # loop (JournalFenced handler) can race to fence the same
        # deposed leader.
        with self._fence_lock:
            if self.roles.is_fenced:
                return
            if self._renew_stop is not None:
                self._renew_stop.set()
                self._renew_stop = None
            if self.hub is not None and self.engine is not None:
                self.hub.detach_engine()
            if self.digest_chain is not None:
                self.digest_chain.detach()
                self.digest_chain = None
            self.roles.to(FENCED, reason)
            if self.on_demote is not None:
                self.on_demote(self.engine, self, reason)
            self.engine = None
            self._inflight_submits.clear()

    def resign(self) -> None:
        """Graceful shutdown handoff: release the lease so a standby
        can take over without waiting out the expiry window."""
        if self.roles.is_leader:
            self.lease.release(self.identity)
            self._fence("resigned")

    # -- the write front door (HTTP POST /workloads lands here) --

    def submit(self, workload, now: float,
               route_epoch: Optional[int] = None) -> dict:
        """Leader check, then fencing, then dedup, then shed check,
        then Engine.submit. Shed requests never reach the engine — they
        must not become flight-recorder input frames (replay would
        diverge). ``route_epoch`` is the federation dispatcher's fence
        epoch for this cell (X-Route-Epoch): a handoff for a revoked
        key at a stale epoch is refused so a zombie cell rejoining the
        federation cannot double-admit."""
        if not self.roles.is_leader or self.engine is None:
            lease = self.lease.read()
            out = {"accepted": False, "code": 503,
                   "reason": f"not leader (role={self.roles.role})",
                   "leaderHint": lease.holder if lease else ""}
            if self.shedder is not None:
                # Same clamped backoff guidance as the 429 path, so
                # failover-window retries stay jittered + bounded.
                out["retryAfter"] = self.shedder.retry_after_hint()
            return out
        if route_epoch is not None:
            self.route_epoch = max(self.route_epoch, int(route_epoch))
            fenced_at = self._revoked.get(workload.key)
            if fenced_at is not None:
                if int(route_epoch) <= fenced_at:
                    return {"accepted": False, "code": 409,
                            "reason": f"fenced: revoked at epoch "
                                      f"{fenced_at}",
                            "workload": workload.name,
                            "fencedEpoch": fenced_at}
                del self._revoked[workload.key]
        if (workload.key in self._inflight_submits
                or workload.key in self.engine.workloads):
            # Idempotent retry: a client that lost its 201 to a leader
            # crash re-POSTs after promotion. The name is the dedup key
            # — re-submitting would reset an already-admitted workload
            # to pending. At-least-once retries + this ack are the
            # exactly-once admission story. Checked before the shedder:
            # a retry of accepted work must not burn bucket tokens.
            # The in-flight map fronts engine.workloads so dedup stays
            # correct even while a submission is between accept and
            # its first durable cycle.
            return {"accepted": True, "code": 200,
                    "workload": workload.name, "deduplicated": True}
        journal = getattr(self.engine, "journal", None)
        if journal is not None and journal.degraded:
            # Disk budget exhausted (store/diskguard.py): the journal
            # is read-only, so an accept here could never be made
            # durable. 503 (retryable elsewhere / later), checked
            # after dedup (acked work still answers 200) and before
            # the shedder (don't burn bucket tokens on a full disk).
            out = {"accepted": False, "code": 503,
                   "reason": "journal degraded: disk budget exhausted"}
            if self.shedder is not None:
                out["retryAfter"] = self.shedder.retry_after_hint()
            return out
        if self.shedder is not None:
            verdict = self.shedder.admit(now)
            if not verdict["accepted"]:
                return {"accepted": False, "code": 429,
                        "reason": "shed: admission rate limit",
                        "retryAfter": verdict["retryAfter"],
                        "factor": verdict["factor"]}
        self.engine.submit(workload)
        self._inflight_submits[workload.key] = now
        while len(self._inflight_submits) > self.dedup_capacity:
            # Oldest-entry eviction at the capacity bound: the oldest
            # in-flight entry is the most likely to already be durable
            # (answered by engine.workloads + the journal on retry).
            self._inflight_submits.popitem(last=False)
        return {"accepted": True, "code": 201,
                "workload": workload.name}

    def revoke(self, keys, epoch: int, now: float) -> dict:
        """Federation fencing: tombstone ``keys`` at ``epoch`` and
        delete any that this cell registered (journaled delete, usage
        released) — the cell side of zombie-rejoin reconciliation. The
        tombstone outlives the delete so a late handoff replay at a
        stale route epoch gets 409, not a fresh admission."""
        if not self.roles.is_leader or self.engine is None:
            return {"accepted": False, "code": 503,
                    "reason": f"not leader (role={self.roles.role})"}
        from kueue_tpu.cli.kueuectl import Kueuectl

        ctl = Kueuectl(self.engine)
        deleted = []
        for key in keys:
            self._revoked[key] = max(self._revoked.get(key, 0),
                                     int(epoch))
            self._inflight_submits.pop(key, None)
            if key in self.engine.workloads:
                ctl.delete_workload(key)
                deleted.append(key)
        if deleted and self.engine.journal is not None:
            self.engine.journal.sync()
        return {"accepted": True, "code": 200, "epoch": int(epoch),
                "revoked": len(keys), "deleted": deleted}

    def _evict_submit_dedup(self, seq: int, result) -> None:
        """Post-sync cycle listener (runs AFTER journal.sync, so this
        cycle's admissions are durable): drop dedup entries whose
        workload reached a durably-journaled admission or terminal
        state. Keeps the map O(in-flight)."""
        if result is None or not self._inflight_submits:
            return
        eng = self.engine
        if eng is None:
            return
        for key in list(self._inflight_submits):
            wl = eng.workloads.get(key)
            if wl is not None and (wl.is_finished
                                   or wl.status.admission is not None):
                del self._inflight_submits[key]

    # -- observability --

    def _on_transition(self, old: str, new: str, reason: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics.counter("ha_role_transitions_total").inc(
                    (old, new))
            except KeyError:
                pass

    def _export(self, now: float) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.gauge("ha_role").set(
                (), float(ROLE_CODES[self.roles.role]))
            self.metrics.gauge("ha_lease_epoch").set(
                (), float(self.epoch or self.lease.epoch_of()))
        except KeyError:
            pass

    def status(self) -> dict:
        lease = self.lease.read()
        out = {
            "identity": self.identity,
            "role": self.roles.role,
            "epoch": self.epoch or (lease.epoch if lease else 0),
            "leaseHolder": lease.holder if lease else "",
            "leaseRenewTime": lease.renew_time if lease else 0.0,
            "replayLag": self.tailer.replay_lag,
            "tailer": self.tailer.status(),
            "transitions": self.roles.history(last=16),
            "promotion": self.promotion_report,
        }
        if self.engine is not None:
            out["stateDigest"] = admitted_state_digest(self.engine)
            out["inflightSubmits"] = len(self._inflight_submits)
            out["dedupCapacity"] = self.dedup_capacity
            # Federation routing inputs: registered/admitted load is
            # the dispatcher's quota-headroom proxy; revoked/routeEpoch
            # surface the fencing state for kueuectl cells.
            out["workloads"] = len(self.engine.workloads)
            out["admittedWorkloads"] = sum(
                1 for w in self.engine.workloads.values()
                if w.status.admission is not None and not w.is_finished)
            out["revoked"] = len(self._revoked)
            out["routeEpoch"] = self.route_epoch
            if self.digest_chain is not None:
                out["decisionDigest"] = self.digest_chain.digest
                out["digestSeq"] = self.digest_chain.last_seq
            if self.engine.checkpointer is not None:
                out["checkpointer"] = self.engine.checkpointer.status()
        if self.hub is not None:
            out["sse"] = self.hub.stats()
            out["sseClients"] = self.hub.stats()["clients"]
        if self.shedder is not None:
            out["shedder"] = self.shedder.status()
        return out
