"""Follower-side journal tailing.

A follower never runs admission cycles; its view of the world is the
leader's journal, consumed incrementally. The tailer reads complete
lines past its last offset (a trailing partial line — the torn-tail
case — is left in place and re-read once the leader's next fsync
completes it), folds them into counters, forwards synthesized events
to the SSE fanout hub, and refreshes a cold-rebuilt read-model engine
that the HTTP layer serves GETs from.

Replay lag is the tailer's headline number: records observed in the
file but not yet folded into the read model. `kueuectl status` and the
``ha_replay_lag_records`` gauge both report it, and promotion latency
is dominated by draining it to zero.
"""

from __future__ import annotations

import json
from typing import Optional


class JournalTailer:
    """Incremental reader of a live journal file.

    ``poll()`` is cheap and safe to call every tick; the read-model
    rebuild (a full journal replay) is throttled to at most once per
    ``rebuild_every`` new records so a chatty leader doesn't make the
    follower spend its life rebuilding.
    """

    def __init__(self, path: str, hub=None, metrics=None,
                 rebuild_every: int = 32, engine_kwargs: Optional[dict] = None):
        self.path = path
        self.hub = hub
        self.metrics = metrics
        self.rebuild_every = max(1, int(rebuild_every))
        self.engine_kwargs = dict(engine_kwargs or {})
        self.engine = None          # the read model (None until 1st poll)
        self.records_seen = 0
        self.rebuilds = 0
        self.last_checkpoint: Optional[dict] = None  # last ha_digest obj
        self._offset = 0
        self._pending = 0           # records seen since last rebuild

    @property
    def replay_lag(self) -> int:
        """Records durable in the journal but not in the read model."""
        return self._pending

    def poll(self) -> int:
        """Consume newly completed journal lines. Returns how many new
        records were observed (0 when the file hasn't grown)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except FileNotFoundError:
            return 0
        if not chunk:
            self._gauge()
            return 0
        # Only complete lines: a torn tail stays unconsumed until the
        # leader's next write completes it (or repair truncates it).
        complete = chunk.rfind(b"\n") + 1
        if complete == 0:
            return 0
        new = 0
        for line in chunk[:complete].splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # corrupt interior line: repair's problem
            new += 1
            self._ingest(rec)
        self._offset += complete
        self.records_seen += new
        self._pending += new
        if self._pending >= self.rebuild_every or self.engine is None:
            self.rebuild()
        self._gauge()
        return new

    def _ingest(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "ha_digest":
            self.last_checkpoint = rec.get("obj")
            if self.hub is not None:
                self.hub.publish("ha_checkpoint",
                                 json.dumps(self.last_checkpoint))
        elif self.hub is not None:
            # Synthesized watch event: followers can't replay the
            # leader's EngineEvents, but the journal record itself is
            # the authoritative change feed.
            obj = rec.get("obj")
            key = (obj.get("metadata", {}).get("name", "")
                   if isinstance(obj, dict) else "")
            self.hub.publish("journal", json.dumps({
                "kind": kind, "op": rec.get("op"), "key": key,
                "ts": rec.get("ts"),
            }))

    def rebuild(self) -> None:
        """Refresh the read model: full cold replay, no journal attach
        (followers must never hold a writable journal handle)."""
        from kueue_tpu.store.journal import Journal, engine_from_records

        records = list(Journal(self.path).replay())
        self.engine = engine_from_records(records, **self.engine_kwargs)
        self.rebuilds += 1
        self._pending = 0

    def _gauge(self) -> None:
        if self.metrics is not None:
            try:
                self.metrics.gauge("ha_replay_lag_records").set(
                    (), float(self._pending))
            except KeyError:
                pass

    def status(self) -> dict:
        return {
            "recordsSeen": self.records_seen,
            "replayLag": self.replay_lag,
            "rebuilds": self.rebuilds,
            "lastCheckpoint": self.last_checkpoint,
        }
