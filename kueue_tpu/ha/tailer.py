"""Follower-side journal tailing.

A follower never runs admission cycles; its view of the world is the
leader's journal, consumed incrementally. The tailer reads complete
lines past its last offset (a trailing partial line — the torn-tail
case — is left in place and re-read once the leader's next fsync
completes it), folds them into counters, forwards synthesized events
to the SSE fanout hub, and refreshes a cold-rebuilt read-model engine
that the HTTP layer serves GETs from.

Segment rotation (store/journal.py): the tailer walks the sealed
segment chain in ordinal order and follows the active file across
rotations. A gap (retention deleted a segment the follower hadn't
consumed — it was asleep past the checkpoint horizon) or a lineage
change (compaction) forces a full resync through the checkpoint
recovery path, which is also what ``rebuild()`` uses: checkpoint base
+ journal suffix, O(delta) instead of O(history).

Rebuild throttling is jittered: after each throttled rebuild the next
one is pushed out by a FULL-JITTER exponential backoff
(uniform(0, min(cap, base·2^streak))), so N followers that all saw the
same failover burst don't rebuild — and hammer the shared journal
volume — in lockstep.

Replay lag is the tailer's headline number: records observed in the
file but not yet folded into the read model. `kueuectl status` and the
``ha_replay_lag_records`` gauge both report it, and promotion latency
is dominated by draining it to zero.
"""

from __future__ import annotations

import json
import random
import time
from typing import Optional


class JournalTailer:
    """Incremental reader of a live (possibly segmented) journal.

    ``poll()`` is cheap and safe to call every tick; the read-model
    rebuild (checkpoint + suffix replay) is throttled to at most once
    per ``rebuild_every`` new records, with full-jitter exponential
    backoff between consecutive throttled rebuilds.
    """

    def __init__(self, path: str, hub=None, metrics=None,
                 rebuild_every: int = 32,
                 engine_kwargs: Optional[dict] = None,
                 rebuild_backoff_base: float = 0.05,
                 rebuild_backoff_cap: float = 2.0,
                 rng: Optional[random.Random] = None,
                 clock=time.monotonic):
        self.path = path
        self.hub = hub
        self.metrics = metrics
        self.rebuild_every = max(1, int(rebuild_every))
        self.engine_kwargs = dict(engine_kwargs or {})
        self.engine = None          # the read model (None until 1st poll)
        self.records_seen = 0
        self.rebuilds = 0
        self.resyncs = 0
        self.last_checkpoint: Optional[dict] = None  # last ha_digest obj
        self._ordinal: Optional[int] = None  # file the offset refers to
        self._offset = 0
        self._lines = 0             # complete lines consumed of _ordinal
        self._lineage = 0
        self._pending = 0           # records seen since last rebuild
        # Staleness envelope inputs (kueue_tpu/readplane): the journal
        # position the read model was rebuilt at, when that happened on
        # this process's clock, and the correlation id of the last
        # admission cycle whose trace record passed through the tail.
        self.applied_position: Optional[dict] = None
        self.applied_at: Optional[float] = None
        self.last_cycle_cid: Optional[str] = None
        self.last_record_ts: Optional[float] = None
        # Full-jitter rebuild backoff (anti-thundering-herd): streak
        # counts consecutive throttled rebuilds; one quiet poll resets.
        self.rebuild_backoff_base = float(rebuild_backoff_base)
        self.rebuild_backoff_cap = float(rebuild_backoff_cap)
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._streak = 0
        self._cooldown_until = 0.0

    @property
    def replay_lag(self) -> int:
        """Records durable in the journal but not in the read model."""
        return self._pending

    def position(self) -> Optional[dict]:
        """The consumed tail position in ``Journal.position()``
        coordinates ({lineage, segment, offset} — offset in complete
        LINES of the file named by segment, meta line included), or
        None before the first poll."""
        if self._ordinal is None:
            return None
        return {"lineage": self._lineage, "segment": self._ordinal,
                "offset": self._lines}

    # -- segment chain helpers --

    def _segments(self) -> list:
        from kueue_tpu.store.journal import _file_meta, _sealed_segments

        lineage = self._journal_lineage()
        out = []
        for ordinal, seg in _sealed_segments(self.path):
            meta = _file_meta(seg)
            if int((meta or {}).get("lineage", 0)) == lineage:
                out.append((ordinal, seg))
        return out

    def _journal_lineage(self) -> int:
        from kueue_tpu.store.journal import _file_meta, _sealed_segments

        meta = _file_meta(self.path)
        if meta is not None:
            return int(meta.get("lineage", 0))
        segs = _sealed_segments(self.path)
        if segs:
            m = _file_meta(segs[-1][1])
            if m is not None:
                return int(m.get("lineage", 0))
        return 0

    def _active_ordinal(self, segs: list) -> int:
        from kueue_tpu.store.journal import _file_meta

        meta = _file_meta(self.path)
        if meta is not None and "seg" in meta:
            return int(meta["seg"])
        return (segs[-1][0] + 1) if segs else 0

    def poll(self) -> int:
        """Consume newly completed journal lines across the segment
        chain. Returns how many new records were observed."""
        segs = self._segments()
        sealed = dict(segs)
        active_ord = self._active_ordinal(segs)
        lineage = self._journal_lineage()
        if self._ordinal is None:
            self._ordinal = segs[0][0] if segs else active_ord
            self._lineage = lineage
        elif lineage != self._lineage:
            # Compaction rewrote history: positions are meaningless.
            self._resync(active_ord, lineage)
            return 0
        new = 0
        while True:
            if self._ordinal in sealed:
                n, _complete = self._consume(sealed[self._ordinal])
                new += n
                # Sealed files never grow: move on regardless.
                self._ordinal += 1
                self._offset = 0
                self._lines = 0
                continue
            if self._ordinal != active_ord:
                # Gap: retention deleted unread segments (we slept past
                # the checkpoint horizon) — positions are unrecoverable.
                self._resync(active_ord, lineage)
                return new
            n, _complete = self._consume(self.path)
            new += n
            break
        if new == 0:
            self._streak = 0
            if self._pending and self.engine is not None:
                # The tail went quiet with records still unfolded (a
                # dead leader stops the stream exactly here): fold now
                # — a quiet journal is the cheapest moment to rebuild,
                # and below-threshold lag would otherwise never clear,
                # pinning every replica answer behind the final writes.
                self.rebuild()
            self._gauge()
            return 0
        self.records_seen += new
        self._pending += new
        if self.engine is None:
            self.rebuild()
        elif self._pending >= self.rebuild_every:
            now = self._clock()
            if now >= self._cooldown_until:
                self.rebuild()
                self._streak += 1
                delay = self._rng.uniform(0.0, min(
                    self.rebuild_backoff_cap,
                    self.rebuild_backoff_base * (2.0 ** self._streak)))
                self._cooldown_until = now + delay
        self._gauge()
        return new

    def _consume(self, path: str) -> tuple:
        """Ingest complete lines of ``path`` past the current offset.
        Returns (records_ingested, consumed_to_eof)."""
        try:
            with open(path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except FileNotFoundError:
            return 0, True
        if not chunk:
            return 0, True
        # Only complete lines: a torn tail stays unconsumed until the
        # leader's next write completes it (or repair truncates it).
        complete = chunk.rfind(b"\n") + 1
        if complete == 0:
            return 0, False
        # Line-position bookkeeping mirrors Journal._active_lines: every
        # complete line counts (meta lines included), so position() is
        # directly comparable with the leader journal's position().
        self._lines += chunk[:complete].count(b"\n")
        new = 0
        for line in chunk[:complete].splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # corrupt interior line: repair's problem
            if rec.get("op") == "meta":
                continue
            new += 1
            self._ingest(rec)
        self._offset += complete
        return new, complete == len(chunk)

    def _resync(self, active_ord: int, lineage: int) -> None:
        """Full re-read through the checkpoint recovery path, then
        fast-forward the tail position to the journal's current end."""
        self.resyncs += 1
        self.rebuild()
        self._lineage = lineage
        self._ordinal = active_ord
        try:
            with open(self.path, "rb") as f:
                data = f.read()
            self._offset = data.rfind(b"\n") + 1
            self._lines = data[:self._offset].count(b"\n")
        except FileNotFoundError:
            self._offset = 0
            self._lines = 0
        self._gauge()

    def _ingest(self, rec: dict) -> None:
        kind = rec.get("kind")
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            self.last_record_ts = float(ts)
        if kind == "cycle_trace":
            obj = rec.get("obj")
            if isinstance(obj, dict) and obj.get("name"):
                self.last_cycle_cid = str(obj["name"])
        if kind == "ha_digest":
            self.last_checkpoint = rec.get("obj")
            if self.hub is not None:
                self.hub.publish("ha_checkpoint",
                                 json.dumps(self.last_checkpoint))
        elif self.hub is not None:
            # Synthesized watch event: followers can't replay the
            # leader's EngineEvents, but the journal record itself is
            # the authoritative change feed.
            obj = rec.get("obj")
            key = (obj.get("metadata", {}).get("name", "")
                   if isinstance(obj, dict) else "")
            self.hub.publish("journal", json.dumps({
                "kind": kind, "op": rec.get("op"), "key": key,
                "ts": rec.get("ts"),
            }))

    def rebuild(self) -> None:
        """Refresh the read model: checkpoint base + journal suffix
        (genesis replay when no checkpoint exists), no journal attach
        (followers must never hold a writable journal handle)."""
        from kueue_tpu.store.checkpoint import recover_records
        from kueue_tpu.store.journal import Journal, engine_from_records

        journal = Journal(self.path)
        base, suffix, meta = recover_records(journal)
        records = (base + suffix) if meta is not None \
            else list(journal.replay())
        self.engine = engine_from_records(records, **self.engine_kwargs)
        if meta is not None:
            self.engine.clock = max(self.engine.clock, meta.clock)
        # The rebuild folded everything durable at this instant: stamp
        # the position it answered from (readplane staleness envelope,
        # and `kueuectl explain` honesty about rebuilt engines).
        self.applied_position = journal.position()
        self.applied_at = self._clock()
        self.engine.rebuild_position = self.applied_position
        self.engine.rebuild_wall = time.time()
        journal.close()
        self.rebuilds += 1
        self._pending = 0

    def _gauge(self) -> None:
        if self.metrics is not None:
            try:
                self.metrics.gauge("ha_replay_lag_records").set(
                    (), float(self._pending))
            except KeyError:
                pass

    def status(self) -> dict:
        return {
            "recordsSeen": self.records_seen,
            "replayLag": self.replay_lag,
            "rebuilds": self.rebuilds,
            "resyncs": self.resyncs,
            "lastCheckpoint": self.last_checkpoint,
            "position": self.position(),
            "appliedPosition": self.applied_position,
            "lastCycleCid": self.last_cycle_cid,
        }
