"""Explicit degradation ladder: one ordered sequence of things to turn
off under overload, instead of N independent components each guessing.

The metastable-failure literature is clear on the shape of the fix:
when offered load exceeds capacity, shed the *cheapest, least
essential* work first, in a FIXED order, and recover in the reverse
order — ad-hoc per-component reactions produce feedback loops (tracing
stays on while submissions are shed; fanout floods while the journal
is read-only) that keep a system wedged after the trigger clears.

Rungs, cumulative (each includes everything above it):

    0 normal   everything on
    1 trace    span-tree capture off (obs/tracer.py ``capture``)
    2 fanout   SSE detail chatter suppressed (visibility/fanout.py
               ``detail`` / DETAIL_KINDS)
    3 submit   new submissions squeezed below the shedder's own
               floors (AdmissionShedder.degraded_factor: 0.05, or
               0.0 while the journal is disk-degraded — nothing may
               be admitted that cannot be journaled)
    4 device   the device decision path demoted at the oracle
               breaker (supervisor.demote) — host-path-only cycles

Escalation is immediate (the cycle that observes the trigger moves the
rung); relaxation is one rung per ``relax_cycles`` consecutive clean
cycles, so a flapping trigger ratchets the ladder up and walks it down
slowly — hysteresis against oscillation.

Triggers, evaluated every cycle from components already attached to
the engine (all read-only except the documented levers):

    SLO worst() WARN              → at least rung 1
    SLO worst() WARN, burn ≥ 2    → at least rung 2
    SLO worst() BREACH            → at least rung 3
    journal disk-degraded         → at least rung 3 (factor 0.0)
    watchdog demoted (OPEN/probe) → rung 4

The ladder itself is a cycle listener — deterministic in cycle
sequence given the trigger inputs, visible as the
``overload_ladder_rung`` gauge, ``overload_ladder_transitions_total``
counter, and the ``ladder`` block on /debug/slo.
"""

from __future__ import annotations

from typing import Optional

RUNGS = ("normal", "trace", "fanout", "submit", "device")
R_NORMAL, R_TRACE, R_FANOUT, R_SUBMIT, R_DEVICE = range(5)

STATUS_WARN, STATUS_BREACH = 1, 2


class DegradationLadder:
    """Owns the rung and applies its cumulative effects each cycle."""

    def __init__(self, engine, shedder=None, hub=None,
                 relax_cycles: int = 32, metrics=None):
        self.engine = engine
        self.shedder = shedder
        self.hub = hub
        self.relax_cycles = max(1, int(relax_cycles))
        self.metrics = metrics if metrics is not None else getattr(
            engine, "registry", None)
        self.rung = R_NORMAL
        self.transitions = 0
        self.escalations = 0
        self.relaxations = 0
        self.last_reason = ""
        self._clean_cycles = 0
        self._post = self._on_cycle
        engine.cycle_listeners.append(self._post)
        engine.ladder = self
        self._export()

    # -- trigger evaluation --

    def _target(self) -> tuple:
        """(target rung, reason, disk_degraded) from current signals.
        The max of all triggers wins — rungs are cumulative, so the
        worst signal decides how far down the ladder we are."""
        target, reason = R_NORMAL, "clear"
        slo = getattr(self.engine, "slo", None)
        if slo is not None:
            try:
                status, burn = slo.worst()
            except Exception:  # noqa: BLE001 — ladder must not unwind
                status, burn = 0, 0.0   # the cycle listener chain
            if status >= STATUS_BREACH:
                target, reason = R_SUBMIT, "slo breach"
            elif status >= STATUS_WARN:
                if burn >= 2.0:
                    target, reason = R_FANOUT, f"slo warn burn={burn:.2f}"
                else:
                    target, reason = R_TRACE, "slo warn"
        journal = getattr(self.engine, "journal", None)
        disk = bool(journal is not None
                    and getattr(journal, "degraded", False))
        if disk and target < R_SUBMIT:
            target, reason = R_SUBMIT, "journal disk-degraded"
        watchdog = getattr(self.engine, "watchdog", None)
        if watchdog is not None and getattr(watchdog, "demoted", False):
            target, reason = R_DEVICE, (
                f"watchdog {watchdog.state}: "
                f"{watchdog.last_transition_reason}")
        return target, reason, disk

    # -- the cycle listener --

    def _on_cycle(self, seq: int, result) -> None:
        target, reason, disk = self._target()
        if target > self.rung:
            # Escalate immediately: the trigger cycle is already late.
            self._move(target, reason)
            self._clean_cycles = 0
        elif target < self.rung:
            self._clean_cycles += 1
            if self._clean_cycles >= self.relax_cycles:
                # One rung at a time — re-enable the most recently
                # shed work first and let the next window confirm.
                self._move(self.rung - 1, "relaxed: clean window")
                self._clean_cycles = 0
        else:
            self._clean_cycles = 0
        self._apply(seq, disk)

    # -- effects --

    def _apply(self, seq: int, disk: bool) -> None:
        """Idempotent application of the current rung's cumulative
        effects; called every cycle so late-attached components pick
        the posture up on their first cycle."""
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None:
            tracer.capture = self.rung < R_TRACE
        hub = self.hub if self.hub is not None else getattr(
            self.engine, "fanout", None)
        if hub is not None:
            hub.detail = self.rung < R_FANOUT
        shedder = self.shedder if self.shedder is not None else getattr(
            self.engine, "shedder", None)
        if shedder is not None:
            if self.rung >= R_SUBMIT:
                # Disk-degraded means admissions cannot be journaled:
                # shed everything, not merely almost-everything.
                shedder.degraded_factor = 0.0 if disk else 0.05
            else:
                shedder.degraded_factor = None
        if self.rung >= R_DEVICE:
            sup = getattr(getattr(self.engine, "oracle", None),
                          "supervisor", None)
            if sup is not None:
                try:
                    # Keeps the breaker's probe window pushed out for
                    # as long as the ladder holds the bottom rung.
                    sup.demote(seq, "ladder: device rung")
                except Exception:  # noqa: BLE001 — advisory only
                    pass

    def _move(self, to: int, reason: str) -> None:
        to = max(R_NORMAL, min(R_DEVICE, to))
        if to == self.rung:
            return
        if to > self.rung:
            self.escalations += 1
        else:
            self.relaxations += 1
        self.transitions += 1
        if self.metrics is not None:
            try:
                self.metrics.counter(
                    "overload_ladder_transitions_total").inc(
                    (RUNGS[self.rung], RUNGS[to]))
            except KeyError:
                pass
        self.rung = to
        self.last_reason = reason
        self._export()

    # -- observability --

    def _export(self) -> None:
        if self.metrics is not None:
            try:
                self.metrics.gauge("overload_ladder_rung").set(
                    (), float(self.rung))
            except KeyError:
                pass

    def status(self) -> dict:
        return {
            "rung": self.rung,
            "rungName": RUNGS[self.rung],
            "rungs": list(RUNGS),
            "lastReason": self.last_reason,
            "cleanCycles": self._clean_cycles,
            "relaxCycles": self.relax_cycles,
            "transitions": self.transitions,
            "escalations": self.escalations,
            "relaxations": self.relaxations,
        }

    def detach(self) -> None:
        try:
            self.engine.cycle_listeners.remove(self._post)
        except ValueError:
            pass
        if getattr(self.engine, "ladder", None) is self:
            self.engine.ladder = None


def attach_ladder(engine, **kwargs) -> DegradationLadder:
    """Attach a ladder to a live engine (idempotent)."""
    existing = getattr(engine, "ladder", None)
    if existing is not None:
        return existing
    return DegradationLadder(engine, **kwargs)
