"""HA serving plane: journal-backed leader/follower failover.

The durability primitives (crash-safe journal, flight recorder, fault
injection, SSE) all terminate in one `serve` replica; this package is
the scale-out story (ROADMAP open item 5). Arax's shape (PAPERS.md):
many client-facing frontends, one accelerator-backed decision cycle —
replicas coordinate through a journal-adjacent lease file, exactly one
leader runs admission cycles, followers tail the journal and absorb
read/SSE traffic, and promotion is replay-verified before the new
leader accepts a single write.

Modules:

  lease    fenced lease file (monotonic epoch = the fencing token)
  roles    the replica role state machine (follower/candidate/leader/
           fenced) with explicit legal transitions
  digest   decision-digest chain + admitted-state digest, journaled as
           ``ha_digest`` records inside the cycle's fsync boundary
  tailer   follower-side incremental journal tailing (replay lag,
           synthesized SSE events)
  shedder  token-bucket admission-rate control wired to SLO burn rates
  replica  HAReplica: the orchestrator serve.py runs in --ha mode
"""

from kueue_tpu.ha.digest import DigestChain, admitted_state_digest
from kueue_tpu.ha.lease import FencedLease, LeaseState
from kueue_tpu.ha.replica import HAReplica
from kueue_tpu.ha.roles import (
    CANDIDATE,
    FENCED,
    FOLLOWER,
    LEADER,
    RoleMachine,
    RoleTransitionError,
)
from kueue_tpu.ha.shedder import AdmissionShedder, TokenBucket
from kueue_tpu.ha.tailer import JournalTailer

__all__ = [
    "AdmissionShedder",
    "CANDIDATE",
    "DigestChain",
    "FENCED",
    "FOLLOWER",
    "FencedLease",
    "HAReplica",
    "JournalTailer",
    "LEADER",
    "LeaseState",
    "RoleMachine",
    "RoleTransitionError",
    "TokenBucket",
    "admitted_state_digest",
]
