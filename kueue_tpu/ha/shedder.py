"""Admission-rate control: a token-bucket front door on the submit
path, with SLO burn rates driving the shed threshold.

The bucket guards the SERVING layer, not the engine: Engine.submit is
wrapped by the flight recorder as a replayable input frame, so a gate
inside the engine would make recorded traces diverge on replay (the
replayer has no shedder attached). Shed submissions are refused before
they ever become inputs — a shed request leaves a counter and a trace
event, never a journal record.

Coupling to obs/slo.py: the effective refill rate is
``rate * factor`` where factor degrades as the worst SLO burns:

    status OK      → 1.00           (full configured rate)
    status WARN    → 1 / (1+burn)   floored at 0.25
    status BREACH  → ¼ · 1/(1+burn) floored at 0.05

so a breached SLO with a 4× burn rate sheds ~95% of new submissions —
back-pressure proportional to how fast the error budget is burning.
"""

from __future__ import annotations

from typing import Optional

STATUS_OK, STATUS_WARN, STATUS_BREACH = 0, 1, 2

# Hard ceiling on any Retry-After the serving plane hands out, in
# seconds (shared by the shedder 429/503 paths and the APF 429 path —
# one clamp, not two code paths). 30 s bounds the hint during failover
# windows: lease expiry + replay-verified promotion completes well
# inside it, so a clamped retry lands after the new leader is serving.
RETRY_AFTER_MAX = 30.0


def clamped_retry_after(base: float, jitter: float = 0.5, rng=None,
                        cap: float = RETRY_AFTER_MAX) -> float:
    """One jittered, clamped Retry-After value from a base delay.

    Every shed client computing the same deterministic delay would
    re-arrive in one synchronized wave (thundering herd after a
    failover) — each refusal gets ``base * uniform(1-j, 1+j)``
    instead: same mean, decorrelated, and never above ``cap``.
    The single code path behind AdmissionShedder.retry_after_hint
    (429 shed / 503 failover) and the APF 429s in
    visibility/http_server.py."""
    import random

    j = max(0.0, min(1.0, float(jitter)))
    r = rng if rng is not None else random
    retry = round(max(0.0, base) * r.uniform(1.0 - j, 1.0 + j), 3)
    return min(retry, cap)


class TokenBucket:
    """Plain token bucket; refill is scaled by an external factor so
    the shedder can squeeze it without mutating configuration."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        self.tokens = self.burst
        self._last: Optional[float] = None

    def take(self, now: float, n: float = 1.0,
             factor: float = 1.0) -> bool:
        if self._last is None:
            self._last = now
        elapsed = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.burst,
                          self.tokens + elapsed * self.rate * factor)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class AdmissionShedder:
    """Decides accept/shed for one submission attempt. Stateless apart
    from the bucket — safe to consult from HTTP handler threads (the
    GIL serializes the float updates; drift under contention only
    mis-sizes the bucket by a token, never corrupts it)."""

    # Backward-compat alias for the module-level clamp (tests and
    # callers configured against the class attribute keep working).
    RETRY_AFTER_MAX = RETRY_AFTER_MAX

    def __init__(self, rate: float = 200.0, burst: Optional[float] = None,
                 slo=None, metrics=None, hub=None,
                 retry_jitter: float = 0.5, rng=None,
                 retry_after_max: Optional[float] = None):
        import random
        self.bucket = TokenBucket(rate, burst)
        self.slo = slo
        self.metrics = metrics
        self.hub = hub
        self.accepted = 0
        self.shed = 0
        self.factor = 1.0
        # Degradation-ladder override (ha/ladder.py): when set, the
        # effective factor is capped at this value regardless of what
        # the SLO coupling computes — the "new submissions" rung
        # squeezing the front door below its own floors (0.0 = shed
        # everything, the disk-degraded posture).
        self.degraded_factor: Optional[float] = None
        # Retry-After jitter: every shed client computing the same
        # deterministic retry delay would re-arrive in one synchronized
        # wave (thundering herd after a failover). Each 429 gets
        # base * uniform(1-j, 1+j) instead — same mean, decorrelated.
        self.retry_jitter = max(0.0, min(1.0, float(retry_jitter)))
        self.retry_after_max = float(
            retry_after_max if retry_after_max is not None
            else self.RETRY_AFTER_MAX)
        self._rng = rng if rng is not None else random.Random()

    def _factor(self) -> float:
        computed = self._slo_factor()
        if self.degraded_factor is not None:
            return min(computed, max(0.0, self.degraded_factor))
        return computed

    def _slo_factor(self) -> float:
        if self.slo is None:
            return 1.0
        try:
            status, burn = self.slo.worst()
        except Exception:  # noqa: BLE001 — SLO eval must not block intake
            return 1.0
        if status >= STATUS_BREACH:
            return max(0.05, 0.25 / (1.0 + burn))
        if status >= STATUS_WARN:
            return max(0.25, 1.0 / (1.0 + burn))
        return 1.0

    def admit(self, now: float, reason: str = "submit") -> dict:
        """Returns {"accepted": bool, "factor": float, "retryAfter": s}."""
        self.factor = self._factor()
        ok = self.bucket.take(now, 1.0, self.factor)
        if self.metrics is not None:
            try:
                self.metrics.gauge("admission_shed_factor").set(
                    (), self.factor)
                if not ok:
                    self.metrics.counter("admission_shed_total").inc(
                        (reason,))
            except KeyError:
                pass
        if ok:
            self.accepted += 1
        else:
            self.shed += 1
            if self.hub is not None:
                import json
                self.hub.publish("admission_shed", json.dumps({
                    "reason": reason, "factor": round(self.factor, 4)}))
        retry = self.retry_after_hint() if not ok else 0.0
        return {"accepted": ok, "factor": self.factor,
                "retryAfter": retry}

    def retry_after_hint(self) -> float:
        """One jittered, clamped Retry-After value for this shedder's
        current posture: base 1/(rate*factor), through the shared
        ``clamped_retry_after`` helper (also used verbatim by the 503
        failover path in ha/replica.py and the APF 429 path in
        visibility/http_server.py)."""
        base = 1.0 / max(1e-6, self.bucket.rate * self.factor)
        return clamped_retry_after(base, jitter=self.retry_jitter,
                                   rng=self._rng,
                                   cap=self.retry_after_max)

    def status(self) -> dict:
        return {"accepted": self.accepted, "shed": self.shed,
                "factor": round(self.factor, 4),
                "degradedFactor": self.degraded_factor,
                "rate": self.bucket.rate, "burst": self.bucket.burst,
                "tokens": round(self.bucket.tokens, 3),
                "retryAfterMax": self.retry_after_max}
