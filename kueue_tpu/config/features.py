"""Feature gates.

Reference: pkg/features/kube_features.go:35-492 (~70 gates). We carry the
gates that affect decision semantics or enable subsystems; unknown gates
are accepted (forward compatibility) but default to False."""

from __future__ import annotations

# gate -> default-enabled
_DEFAULTS: dict[str, bool] = {
    # decision semantics
    "FlavorFungibility": True,
    "PartialAdmission": True,
    "PrioritySortingWithinCohort": True,
    "FairSharing": False,
    "AdmissionFairSharing": False,
    "QuotaCheckStrategy": False,
    "SchedulerTimestampPreemptionBuffer": False,
    "FairSharingPreemptWithinNominal": False,
    "FairSharingPrioritizeNonBorrowing": False,
    # decision semantics (continued)
    "ReclaimablePods": True,
    "SchedulingEquivalenceHashing": True,
    "HierarchicalCohorts": True,
    "SparkApplicationIntegration": True,
    # TAS
    "TopologyAwareScheduling": True,
    "TASBalancedPlacement": False,
    "TASFailedNodeReplacement": True,
    "TASReplaceNodeOnPodTermination": False,
    "TASReplaceNodeOnNodeTaints": False,
    "TASReplaceNodeNotReadyOverFixedTime": False,
    "TASFailedNodeReplacementFailFast": False,
    "TASRecomputeAssignmentWithinSchedulingCycle": False,
    "TASMultiLayerTopology": True,
    # kube_features.go:541 (beta since 0.15, default on): unconstrained
    # placements use the LeastFreeCapacity ordering; off = BestFit
    # everywhere (the KEP#2724 profile matrix).
    "TASProfileMixed": True,
    "SkipReassignmentForPodOwnedWorkloads": True,
    # kube_features.go:688 (beta since 0.19, default on): external
    # admission gates via the admission-gated-by annotation; the
    # per-integration webhooks validate the annotation's format.
    "AdmissionGatedBy": True,
    # subsystems
    "MultiKueue": True,
    "MultiKueueOrchestratedPreemption": False,
    "MultiKueueManagerQuotaAutomation": False,
    "MultiKueueIncrementalDispatcherConfig": True,
    # kube_features.go:253 MultiKueueClusterProfile (alpha, default off):
    # MultiKueueCluster may name a ClusterProfile instead of a
    # kubeconfig as its connection source.
    "MultiKueueClusterProfile": False,
    "ElasticJobsViaWorkloadSlices": False,
    "ElasticJobsViaWorkloadSlicesWithTAS": True,
    "ConcurrentAdmission": False,
    "WaitForPodsReady": False,
    "DisableWaitForPodsReady": False,
    "ObjectRetentionPolicies": False,
    "PriorityBoost": False,
    "FailureRecoveryPolicy": True,
    "KueueDRAIntegration": True,
    "KueueDRAIntegrationExtendedResource": True,
    "LocalQueueDefaulting": True,
    # defaulting / webhooks
    "ManagedJobsNamespaceSelectorAlwaysRespected": True,
    # observability
    "UnadmittedWorkloadsObservability": True,
    "LocalQueueMetrics": True,
    "MetricsForCohorts": True,
    "CustomMetricLabels": True,
    "VisibilityOnDemand": True,
    # the TPU oracle fast path
    "BatchedOracle": True,
    # TAS placement solved by the device kernel (ops/tas.tas_place);
    # off = sequential host path only.
    "DeviceTAS": True,
}

_overrides: dict[str, bool] = {}


def enabled(name: str) -> bool:
    if name in _overrides:
        return _overrides[name]
    return _DEFAULTS.get(name, False)


def set_feature(name: str, value: bool) -> None:
    _overrides[name] = value


def apply(gates: dict[str, bool]) -> None:
    _overrides.update(gates)


def reset() -> None:
    _overrides.clear()


def all_gates() -> dict[str, bool]:
    out = dict(_DEFAULTS)
    out.update(_overrides)
    return out
