"""Configuration API: the manager's startup config file schema.

Reference: apis/config/v1beta2/configuration_types.go:35 (Configuration)
+ pkg/config (load/validate/defaults). Standalone: dataclasses loaded from
JSON/YAML with defaulting and validation."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class WaitForPodsReady:
    """configuration_types.go (WaitForPodsReady)."""

    enable: bool = False
    timeout_seconds: int = 300
    block_admission: bool = False
    requeuing_backoff_base_seconds: int = 60
    requeuing_backoff_limit_count: Optional[int] = None
    requeuing_backoff_max_seconds: int = 3600
    # FIFO anchor for PodsReady-evicted workloads: "Eviction" (default)
    # or "Creation" (configuration_types.go RequeuingStrategy.Timestamp).
    requeuing_timestamp: str = "Eviction"


@dataclass
class FairSharingConfig:
    enable: bool = False
    preemption_strategies: tuple[str, ...] = (
        "LessThanOrEqualToFinalShare", "LessThanInitialShare")


@dataclass
class AdmissionFairSharingConfig:
    usage_half_life_seconds: int = 600
    usage_sampling_interval_seconds: int = 60
    resource_weights: dict[str, float] = field(default_factory=dict)


@dataclass
class ResourceTransformationSpec:
    """configuration_types.go:560 (ResourceTransformation)."""

    input: str = ""
    strategy: str = "Retain"  # Retain | Replace
    outputs: dict[str, float] = field(default_factory=dict)
    multiply_by: str = ""


@dataclass
class ResourcesConfig:
    """configuration_types.go:540 (Resources): resources excluded from
    quota accounting and input->output transformations."""

    exclude_resource_prefixes: tuple[str, ...] = ()
    transformations: tuple[ResourceTransformationSpec, ...] = ()


@dataclass
class MultiKueueConfigSpec:
    gc_interval_seconds: int = 60
    origin: str = "multikueue"
    worker_lost_timeout_seconds: int = 900
    dispatcher_name: str = "AllAtOnce"


@dataclass
class Configuration:
    """configuration_types.go:35."""

    namespace: str = "kueue-system"
    manage_jobs_without_queue_name: bool = False
    integrations: tuple[str, ...] = ("batch/job",)
    wait_for_pods_ready: WaitForPodsReady = field(
        default_factory=WaitForPodsReady)
    fair_sharing: FairSharingConfig = field(
        default_factory=FairSharingConfig)
    admission_fair_sharing: Optional[AdmissionFairSharingConfig] = None
    multikueue: MultiKueueConfigSpec = field(
        default_factory=MultiKueueConfigSpec)
    feature_gates: dict[str, bool] = field(default_factory=dict)
    resources: ResourcesConfig = field(default_factory=ResourcesConfig)
    # objectRetentionPolicies.workloads (configuration_types.go:648),
    # durations in seconds; None = keep forever.
    retention_after_finished_seconds: Optional[float] = None
    retention_after_deactivated_seconds: Optional[float] = None
    # metrics.customLabels (configuration_types.go:187): extra metric
    # labels sourced from object metadata.
    metrics_custom_labels: list = field(default_factory=list)
    # oracle: the batched TPU decision path configuration
    oracle_enabled: bool = True
    oracle_max_depth: int = 4
    # pprofBindAddress analog (configuration_types.go:140): a directory
    # to drop JAX profiler traces into (xprof-viewable); None = off.
    profile_dir: Optional[str] = None

    def info_options(self):
        """Build workload_info.InfoOptions from the resources section."""
        from kueue_tpu.workload_info import InfoOptions, ResourceTransformation

        return InfoOptions.from_transform_list(
            [ResourceTransformation(input=t.input, outputs=dict(t.outputs),
                                    strategy=t.strategy,
                                    multiply_by=t.multiply_by)
             for t in self.resources.transformations],
            excluded=self.resources.exclude_resource_prefixes)

    def validate(self) -> list[str]:
        """pkg/config/validation.go."""
        errs = []
        if self.wait_for_pods_ready.timeout_seconds <= 0:
            errs.append("waitForPodsReady.timeout must be > 0")
        if self.wait_for_pods_ready.requeuing_backoff_base_seconds < 1:
            errs.append("waitForPodsReady.requeuingBackoffBaseSeconds >= 1")
        for s in self.fair_sharing.preemption_strategies:
            if s not in ("LessThanOrEqualToFinalShare",
                         "LessThanInitialShare"):
                errs.append(f"unknown preemption strategy {s}")
        # pkg/config/validation.go:455 validateResourceTransformations.
        seen_inputs = set()
        for t in self.resources.transformations:
            if not t.input:
                errs.append("resource transformation needs an input")
            if t.input in seen_inputs:
                errs.append(f"duplicate transformation input {t.input}")
            seen_inputs.add(t.input)
            if t.strategy not in ("Retain", "Replace"):
                errs.append(f"unknown transformation strategy {t.strategy}")
        if self.oracle_max_depth < 1:
            errs.append("oracleMaxDepth must be >= 1")
        return errs


def load(path: str) -> Configuration:
    """pkg/config/config.go (Load): read, default, validate."""
    with open(path) as f:
        text = f.read()
    try:
        raw = json.loads(text)
    except json.JSONDecodeError:
        import yaml  # baked in with flax/orbax deps

        raw = yaml.safe_load(text)
    cfg = from_dict(raw or {})
    errs = cfg.validate()
    if errs:
        raise ValueError("invalid configuration: " + "; ".join(errs))
    return cfg


def _duration_seconds(value) -> Optional[float]:
    """Accepts a number of seconds or a Go-style duration string
    ("300s", "5m", "1h30m")."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    import re

    text = str(value)
    parts = re.findall(r"([\d.]+)(ms|h|m|s)", text)
    # An unparseable duration must NOT silently become 0 (instant
    # deletion); treat it as unset, the safe direction.
    if not parts or "".join(q + u for q, u in parts) != text:
        return None
    return sum(float(qty) * {"h": 3600.0, "m": 60.0, "s": 1.0,
                             "ms": 0.001}[unit]
               for qty, unit in parts)


def from_dict(raw: dict) -> Configuration:
    cfg = Configuration()
    cfg.namespace = raw.get("namespace", cfg.namespace)
    cfg.manage_jobs_without_queue_name = raw.get(
        "manageJobsWithoutQueueName", cfg.manage_jobs_without_queue_name)
    cfg.integrations = tuple(
        raw.get("integrations", {}).get("frameworks", cfg.integrations)
        if isinstance(raw.get("integrations"), dict)
        else raw.get("integrations", cfg.integrations))
    w = raw.get("waitForPodsReady") or {}
    cfg.wait_for_pods_ready = WaitForPodsReady(
        enable=w.get("enable", False),
        timeout_seconds=w.get("timeout", 300),
        block_admission=w.get("blockAdmission", False),
        requeuing_backoff_base_seconds=(w.get("requeuingStrategy") or {})
        .get("backoffBaseSeconds", 60),
        requeuing_backoff_limit_count=(w.get("requeuingStrategy") or {})
        .get("backoffLimitCount"),
        requeuing_backoff_max_seconds=(w.get("requeuingStrategy") or {})
        .get("backoffMaxSeconds", 3600),
    )
    fs = raw.get("fairSharing") or {}
    cfg.fair_sharing = FairSharingConfig(
        enable=fs.get("enable", False),
        preemption_strategies=tuple(fs.get(
            "preemptionStrategies",
            FairSharingConfig().preemption_strategies)))
    from kueue_tpu.metrics.registry import CustomLabelEntry

    cfg.metrics_custom_labels = [
        CustomLabelEntry(
            name=e.get("name", ""),
            source_label_key=e.get("sourceLabelKey", ""),
            source_annotation_key=e.get("sourceAnnotationKey", ""))
        for e in (raw.get("metrics") or {}).get("customLabels", ())]
    ret = ((raw.get("objectRetentionPolicies") or {})
           .get("workloads") or {})
    cfg.retention_after_finished_seconds = _duration_seconds(
        ret.get("afterFinished"))
    cfg.retention_after_deactivated_seconds = _duration_seconds(
        ret.get("afterDeactivatedByKueue"))
    res = raw.get("resources") or {}
    cfg.resources = ResourcesConfig(
        exclude_resource_prefixes=tuple(
            res.get("excludeResourcePrefixes", ())),
        transformations=tuple(
            ResourceTransformationSpec(
                input=t.get("input", ""),
                strategy=t.get("strategy", "Retain"),
                outputs={k: float(v)
                         for k, v in (t.get("outputs") or {}).items()},
                multiply_by=t.get("multiplyBy", ""))
            for t in res.get("transformations", ())))
    cfg.feature_gates = dict(raw.get("featureGates", {}))
    cfg.oracle_enabled = raw.get("oracle", {}).get("enable", True)
    cfg.oracle_max_depth = raw.get("oracle", {}).get("maxDepth", 4)
    cfg.profile_dir = raw.get("profileDir")
    return cfg
