"""Flight recorder: deterministic record/replay + fault injection for
the admission pipeline.

The reference's correctness story rests on "the Go semantics as the
oracle" — but golden worlds are hand-built. This subsystem turns any
live serving run into a regression test:

  * ``trace``     — the versioned, checksummed trace framing (one JSON
                    frame per line, CRC-chained so truncation or
                    tampering anywhere invalidates the tail);
  * ``recorder``  — FlightRecorder captures an engine's inputs (object
                    creations, submissions, clock ticks) and each
                    cycle's decision stream + phase timings;
  * ``replayer``  — re-executes a trace through the real engine (host
                    path, device path, or differential both) and
                    asserts the decision stream is byte-identical,
                    with per-cycle phase attribution;
  * ``faults``    — injects SIGKILL-mid-cycle, torn-journal-tail,
                    oracle-crash and delayed-verdict faults under
                    replay or live smoke (serve.py --fault).
"""

from kueue_tpu.replay.faults import FaultPlan, arm_faults
from kueue_tpu.replay.recorder import FlightRecorder
from kueue_tpu.replay.replayer import ReplayReport, replay_trace
from kueue_tpu.replay.trace import (
    TraceCorruption,
    TraceReader,
    TraceWriter,
    canonical_decisions,
    decision_digest,
)

__all__ = [
    "FaultPlan",
    "FlightRecorder",
    "ReplayReport",
    "TraceCorruption",
    "TraceReader",
    "TraceWriter",
    "arm_faults",
    "canonical_decisions",
    "decision_digest",
    "replay_trace",
]
