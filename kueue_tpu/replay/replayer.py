"""Deterministic replayer: re-execute a trace through the real engine.

Modes:

  host    — a plain engine (sequential decision core);
  device  — engine with the oracle attached (batched device path,
            hybrid cycles included);
  both    — differential: host AND device engines consume the trace
            side by side; every cycle's decision record must match the
            recording AND each other.

The determinism contract: applying the trace's input frames at their
recorded clocks to a fresh engine and running exactly the recorded
number of schedule_once() calls yields a byte-identical decision stream
(canonical per-cycle records, chained CRC digest). Any divergence is
reported with the first differing cycle and a decision-level diff.

Per-cycle phase timings are captured on both sides; the report's
attribution table (recorded vs replayed, per phase: total/mean/share)
is the tool that finally pins where a serving cycle's time goes — e.g.
the ~70% verdict-apply share the round-5 verdict flags.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.replay.recorder import apply_input
from kueue_tpu.replay.trace import (
    TraceReader,
    canonical_decisions,
    decision_digest,
)


@dataclass
class CycleMismatch:
    seq: int
    kind: str  # "decisions" | "extra-idle" | "missing-idle"
    detail: str = ""


@dataclass
class ReplayReport:
    trace: str
    mode: str
    cycles: int = 0
    idle_cycles: int = 0
    inputs: int = 0
    admitted: int = 0
    preempting: int = 0
    truncated: bool = False
    recorded_digest: str = ""
    replayed_digest: str = ""
    mismatches: list = field(default_factory=list)
    # phase -> seconds summed over cycles, recorded vs replayed (and
    # "device" when mode == "both").
    phases_recorded: dict = field(default_factory=dict)
    phases_replayed: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return (not self.mismatches
                and self.recorded_digest == self.replayed_digest)

    def attribution(self, which: str = "replayed") -> dict:
        """Per-phase attribution: {phase: {total_s, mean_ms, share}}."""
        phases = (self.phases_recorded if which == "recorded"
                  else self.phases_replayed)
        total = sum(phases.values()) or 1.0
        n = max(self.cycles, 1)
        return {p: {"total_s": round(t, 6),
                    "mean_ms": round(t / n * 1e3, 3),
                    "share": round(t / total, 4)}
                for p, t in sorted(phases.items(),
                                   key=lambda kv: -kv[1])}

    def render(self) -> str:
        lines = [
            f"trace    {self.trace}",
            f"mode     {self.mode}",
            f"cycles   {self.cycles} ({self.idle_cycles} idle), "
            f"{self.inputs} inputs, {self.admitted} admitted, "
            f"{self.preempting} preempting",
            f"digest   recorded={self.recorded_digest or '-'} "
            f"replayed={self.replayed_digest or '-'}"
            + (" [TRUNCATED TAIL]" if self.truncated else ""),
            f"verdict  {'BYTE-IDENTICAL' if self.ok else 'DIVERGED'}",
        ]
        for which in ("recorded", "replayed"):
            attr = self.attribution(which)
            if not attr:
                continue
            lines.append(f"phases ({which}):")
            for p, a in attr.items():
                lines.append(f"  {p:<10} {a['mean_ms']:>9.3f} ms/cycle  "
                             f"{a['share'] * 100:5.1f}%")
        for m in self.mismatches[:5]:
            lines.append(f"MISMATCH cycle {m.seq} [{m.kind}]: "
                         f"{m.detail[:400]}")
        if len(self.mismatches) > 5:
            lines.append(f"... {len(self.mismatches) - 5} more mismatches")
        return "\n".join(lines)


def _diff_decisions(want: list, got: list) -> str:
    w = json.dumps(want, sort_keys=True)
    g = json.dumps(got, sort_keys=True)
    if w == g:
        return ""
    # First differing character region, for a readable probe.
    i = next((k for k in range(min(len(w), len(g)))
              if w[k] != g[k]), min(len(w), len(g)))
    lo = max(0, i - 60)
    return (f"recorded[{lo}:]={w[lo:i + 120]!r} "
            f"replayed[{lo}:]={g[lo:i + 120]!r}")


def _fresh_engine(device: bool, engine_factory=None):
    if engine_factory is not None:
        eng = engine_factory()
    else:
        from kueue_tpu.controllers.engine import Engine
        eng = Engine()
    if device:
        eng.attach_oracle()
    return eng


def replay_trace(path: str, mode: str = "host",
                 engine_factory=None, faults=None,
                 stop_after_cycles: Optional[int] = None) -> ReplayReport:
    """Replay ``path`` and verify the decision stream. ``engine_factory``
    builds the fresh engine(s) (default: plain Engine()); ``faults`` is
    a FaultPlan armed on the (primary) replay engine — replay doubles as
    the fault-injection harness, exercising crash paths against a known
    decision stream."""
    if mode not in ("host", "device", "both"):
        raise ValueError(f"unknown replay mode {mode!r}")
    report = ReplayReport(trace=path, mode=mode)
    engines = {}
    engines["primary"] = _fresh_engine(mode == "device", engine_factory)
    if mode == "both":
        engines["device"] = _fresh_engine(True, engine_factory)
    if faults is not None:
        from kueue_tpu.replay.faults import arm_faults
        arm_faults(engines["primary"], faults)

    reader = TraceReader(path)
    digest = 0
    for frame in reader:
        kind = frame["f"]
        if kind == "input":
            for eng in engines.values():
                apply_input(eng, frame)
            report.inputs += 1
            continue
        if kind == "idle":
            for _ in range(frame["n"]):
                for name, eng in engines.items():
                    eng.clock = frame["clock"]
                    got_idle = canonical_decisions(eng.schedule_once())
                    # A recorded idle can replay as an entry-less result
                    # on the other path (skipped heads materialize as
                    # entries host-side); only actual DECISIONS diverge.
                    if got_idle:
                        report.mismatches.append(CycleMismatch(
                            eng.cycle_seq - 1, "extra-decisions",
                            f"{name}: recorded idle, replay produced "
                            f"{json.dumps(got_idle)[:300]}"))
                report.idle_cycles += 1
            continue
        if kind != "cycle":
            continue
        seq = frame["seq"]
        got = {}
        for name, eng in engines.items():
            eng.clock = frame["clock"]
            result = eng.schedule_once()
            got[name] = canonical_decisions(result)
            for p, dur in eng.last_cycle_phases.items():
                key = p if name == "primary" else f"{name}:{p}"
                report.phases_replayed[key] = \
                    report.phases_replayed.get(key, 0.0) + dur
        want = frame["decisions"]
        diff = _diff_decisions(want, got["primary"])
        if diff:
            report.mismatches.append(
                CycleMismatch(seq, "decisions", diff))
        if mode == "both":
            ddiff = _diff_decisions(got["primary"], got["device"])
            if ddiff:
                report.mismatches.append(CycleMismatch(
                    seq, "host-vs-device", ddiff))
        digest = decision_digest(got["primary"], digest)
        report.cycles += 1
        report.admitted += len(want[0]) if want else 0
        report.preempting += len(want[1]) if want else 0
        for p, dur in frame.get("phases", {}).items():
            report.phases_recorded[p] = \
                report.phases_recorded.get(p, 0.0) + dur
        if stop_after_cycles is not None \
                and report.cycles >= stop_after_cycles:
            break
    report.truncated = reader.truncated
    report.recorded_digest = reader.digest
    report.replayed_digest = f"{digest:08x}"
    if reader.truncated and not reader.digest:
        # No end frame and no cycle reached: nothing to compare against.
        report.recorded_digest = report.replayed_digest
    return report
