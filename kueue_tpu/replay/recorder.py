"""FlightRecorder: capture a live engine's inputs and decision stream.

Attachment wraps the engine's public mutators as instance attributes
(the class methods stay untouched) and registers a cycle listener on the
engine's capture points (Engine.cycle_listeners). Every top-level input
call writes an ``input`` frame BEFORE delegating — the frame carries the
pre-call clock, so replay reproduces out-of-band clock manipulation
(tests that do ``eng.clock += x`` directly) exactly. Nested calls made
by the engine itself (preemption evictions inside a cycle, retention
sweeps inside tick) are consequences of recorded inputs, not inputs —
a reentrancy guard keeps them out of the trace, or replay would apply
them twice.

Idle cycles are coalesced into one ``idle`` frame per run of consecutive
Nones (a serve loop idles thousands of times between submissions; the
replayer still executes every one of them, because an idle cycle runs
the second-pass queue and its count is part of the determinism
contract).
"""

from __future__ import annotations

import functools
from typing import Optional

from kueue_tpu.api.serde import from_jsonable, to_jsonable
from kueue_tpu.replay.trace import TraceWriter, canonical_decisions

# Engine methods that constitute the input surface. Arguments round-trip
# through the api.serde codec (dataclasses get __t__ tags; primitives
# pass through).
RECORDED_METHODS = (
    "create_cohort",
    "create_resource_flavor",
    "create_cluster_queue",
    "create_local_queue",
    "create_topology",
    "create_node",
    "create_workload_priority_class",
    "create_limit_range",
    "create_runtime_class",
    "set_namespace_labels",
    "observe_pod",
    "observe_pod_deleted",
    "delete_node",
    "mark_node_unhealthy",
    "submit",
    "restore_workload",
    "reconcile_workload",
    "finish",
    "hold_workload",
    "clear_hold",
    "tick",
)

# Methods whose first argument is a live engine-owned Workload: recorded
# by key, resolved against engine.workloads on replay (serializing a
# copy would make replay act on a detached object).
BY_KEY_METHODS = ("evict",)


class FlightRecorder:
    def __init__(self, engine, path: str, label: str = "",
                 bootstrap: bool = False, fsync: bool = True):
        self.engine = engine
        self.writer = TraceWriter(path, label=label, fsync=fsync)
        self._depth = 0  # reentrancy guard: record top-level calls only
        self._idle = 0
        self._idle_clock = 0.0
        self._wrapped: list[str] = []
        self._listener = self._on_cycle
        if bootstrap:
            self._bootstrap()
        elif engine.cache.cluster_queues or engine.workloads:
            import warnings
            warnings.warn(
                "FlightRecorder attached to a populated engine without "
                "bootstrap=True: the trace will not carry the existing "
                "world and cannot replay faithfully", stacklevel=2)
        self._wrap_all()
        engine.cycle_listeners.append(self._listener)

    # -- capture --

    def _bootstrap(self) -> None:
        """Emit the engine's CURRENT state as input frames, so a trace
        can start from a journal-rebuilt world (kueuectl record,
        serve --record): the replayer reconstructs the same world from
        the trace alone."""
        eng = self.engine
        clock = eng.clock
        for kind, objs in (
                ("create_cohort", eng.cache.cohorts.values()),
                ("create_resource_flavor",
                 eng.cache.resource_flavors.values()),
                ("create_cluster_queue", eng.cache.cluster_queues.values()),
                ("create_local_queue", eng.queues.local_queues.values()),
                ("create_topology", eng.cache.topologies.values()),
                ("create_node", eng.cache.nodes.values())):
            for obj in objs:
                self.writer.input(clock, kind, [to_jsonable(obj)], {})
        for name, value in eng.workload_priority_classes.items():
            self.writer.input(clock, "create_workload_priority_class",
                              [name, value], {})
        for ns, labels in eng.namespace_labels.items():
            self.writer.input(clock, "set_namespace_labels",
                              [ns, dict(labels)], {})
        for wl in eng.workloads.values():
            self.writer.input(clock, "restore_workload",
                              [to_jsonable(wl)], {})

    def _wrap_all(self) -> None:
        for name in RECORDED_METHODS + BY_KEY_METHODS:
            orig = getattr(self.engine, name)
            setattr(self.engine, name,
                    self._make_wrapper(name, orig,
                                       by_key=name in BY_KEY_METHODS))
            self._wrapped.append(name)
        # schedule_once is NOT an input (the replayer drives cycles from
        # cycle frames), but everything the cycle itself calls —
        # preemption evictions in the apply loop above all — must count
        # as nested, or replay would apply those evictions twice: once
        # from a spurious input frame and once from re-running the cycle.
        orig_cycle = self.engine.schedule_once

        @functools.wraps(orig_cycle)
        def cycle_guard():
            self._depth += 1
            try:
                return orig_cycle()
            finally:
                self._depth -= 1
        self.engine.schedule_once = cycle_guard
        self._wrapped.append("schedule_once")

    def _make_wrapper(self, name: str, orig, by_key: bool):
        @functools.wraps(orig)
        def wrapper(*args, **kwargs):
            if self._depth == 0:
                self._flush_idle()
                if by_key:
                    enc = [args[0].key] + [to_jsonable(a)
                                           for a in args[1:]]
                else:
                    enc = [to_jsonable(a) for a in args]
                self.writer.input(
                    self.engine.clock, name, enc,
                    {k: to_jsonable(v) for k, v in kwargs.items()})
            self._depth += 1
            try:
                return orig(*args, **kwargs)
            finally:
                self._depth -= 1
        return wrapper

    def _on_cycle(self, seq: int, result) -> None:
        eng = self.engine
        if result is None:
            if self._idle == 0:
                self._idle_clock = eng.clock
            self._idle += 1
            return
        self._flush_idle()
        verdict = None
        if eng.oracle is not None:
            verdict = getattr(eng.oracle, "last_verdict_digest", None)
        decisions = canonical_decisions(result)
        # The cid is a pure function of (seq, decisions) — computed here
        # independently of any attached tracer, so the frame joins
        # against journal cycle_trace records and retained span trees
        # whether or not tracing was on during the recording.
        from kueue_tpu.obs.span import correlation_id
        self.writer.cycle(
            seq, eng.clock, eng.last_cycle_mode or "sequential",
            decisions, dict(eng.last_cycle_phases),
            verdict_digest=verdict,
            cid=correlation_id(seq, decisions))

    def _flush_idle(self) -> None:
        if self._idle:
            self.writer.idle(self._idle, self._idle_clock)
            self._idle = 0

    # -- lifecycle --

    @property
    def digest(self) -> str:
        return self.writer.digest

    def close(self) -> None:
        """Detach from the engine and seal the trace (end frame)."""
        try:
            self.engine.cycle_listeners.remove(self._listener)
        except ValueError:
            pass
        for name in self._wrapped:
            # The wrapper shadows the class method as an instance
            # attribute; deleting it restores the original binding.
            self.engine.__dict__.pop(name, None)
        self._wrapped = []
        self._flush_idle()
        self.writer.close()


def decode_args(frame: dict) -> tuple:
    """Replay-side decoding for an input frame (shared with replayer)."""
    args = [from_jsonable(a) for a in frame.get("args", [])]
    kwargs = {k: from_jsonable(v)
              for k, v in frame.get("kwargs", {}).items()}
    return args, kwargs


def apply_input(engine, frame: dict) -> None:
    """Apply one input frame to an engine, restoring the recorded clock
    first (the determinism contract: identical clocks at every call)."""
    engine.clock = frame["clock"]
    method = frame["method"]
    args, kwargs = decode_args(frame)
    if method in BY_KEY_METHODS:
        wl = engine.workloads.get(args[0])
        if wl is None:
            raise KeyError(
                f"replay: {method} targets unknown workload {args[0]!r}")
        args[0] = wl
    getattr(engine, method)(*args, **kwargs)
