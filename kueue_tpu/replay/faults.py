"""Fault injection for the admission pipeline, under replay or live
smoke (serve.py --fault / KUEUE_TPU_FAULT).

Spec grammar (comma-separated faults):

  sigkill@cycle:N          SIGKILL this process as cycle N begins
  sigkill@admission:N      SIGKILL mid-apply, at the Nth admission —
                           the journal's torn-tail + crash-recovery
                           path under a real half-applied cycle
  torn-tail@cycle:N        append a partial (newline-less, invalid)
                           record to the journal, fsync it, SIGKILL —
                           the exact artifact of a crash mid-append
  oracle-crash@cycle:N     the oracle executor raises transport errors
                           for the whole of cycle N (sidecar crash);
                           the bridge must fall back sequentially and
                           re-attach on the next cycle
  delay-verdict@cycle:N:MS the oracle's verdicts arrive MS late on
                           cycle N (slow sidecar) — decisions must be
                           unaffected, only phase timings move
  lease-stall@cycle:N      stop renewing the HA lease from cycle N on
                           (a wedged-but-alive leader): a standby must
                           steal the lease at expiry and the stale
                           leader's next journal write must die on
                           JournalFenced, not interleave

The recovery contract these faults exist to prove: reboot via
store.journal.rebuild_engine and drain, and the admitted set equals an
uninterrupted run's — zero lost, zero duplicate admissions.
"""

from __future__ import annotations

import os
import signal
from dataclasses import dataclass, field


@dataclass
class Fault:
    kind: str        # sigkill | torn-tail | oracle-crash | delay-verdict
    at: str          # cycle | admission
    n: int           # trigger point (cycle seq or admission ordinal)
    arg: float = 0.0  # delay-verdict: milliseconds


@dataclass
class FaultPlan:
    faults: list = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for part in filter(None, (s.strip() for s in spec.split(","))):
            try:
                kind, rest = part.split("@", 1)
                bits = rest.split(":")
                at, n = bits[0], int(bits[1])
                arg = float(bits[2]) if len(bits) > 2 else 0.0
            except (ValueError, IndexError):
                raise ValueError(
                    f"bad fault spec {part!r} "
                    "(want kind@cycle:N or kind@admission:N)") from None
            if kind not in ("sigkill", "torn-tail", "oracle-crash",
                            "delay-verdict", "lease-stall"):
                raise ValueError(f"unknown fault kind {kind!r}")
            if at not in ("cycle", "admission"):
                raise ValueError(f"unknown fault point {at!r}")
            if at == "admission" and kind != "sigkill":
                raise ValueError(
                    f"{kind} only triggers at cycle boundaries")
            plan.faults.append(Fault(kind, at, n, arg))
        return plan


def _die() -> None:
    # SIGKILL, not sys.exit: no atexit, no finally blocks, no flush —
    # the same crash the fault matrix is meant to prove recovery from.
    os.kill(os.getpid(), signal.SIGKILL)


def _tear_journal_tail(journal) -> None:
    """Plant the artifact of a crash mid-append: a flushed, newline-less
    JSON fragment at the end of the journal file."""
    with open(journal.path, "ab") as fh:
        fh.write(b'{"op":"apply","kind":"workload","ts":9')
        fh.flush()
        os.fsync(fh.fileno())


class _ExecutorFaultProxy:
    """Wraps the oracle bridge's executor: raises transport errors while
    ``crashed`` is set, sleeps ``delay_ms`` before returning otherwise."""

    def __init__(self, inner):
        self.inner = inner
        self.crashed = False
        self.delay_ms = 0.0
        self.injected_errors = 0
        self.delayed_calls = 0

    def _gate(self):
        from kueue_tpu.oracle.service import RemoteOracleError
        if self.crashed:
            self.injected_errors += 1
            raise RemoteOracleError("injected oracle crash")
        if self.delay_ms > 0:
            import time
            time.sleep(self.delay_ms / 1e3)
            self.delayed_calls += 1

    def cycle_step(self, tensors, statics):
        self._gate()
        return self.inner.cycle_step(tensors, statics)

    def classical_targets(self, tensors, statics, derived=None):
        self._gate()
        return self.inner.classical_targets(tensors, statics,
                                            derived=derived)

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()


class FaultInjector:
    """Armed on an engine: hooks the cycle boundary (pre_cycle_hooks)
    and the admission apply path (_admit)."""

    def __init__(self, engine, plan: FaultPlan):
        self.engine = engine
        self.plan = plan
        self.admissions = 0
        self.fired: list[str] = []
        self.proxy = None
        self._kill_at_admission = min(
            (f.n for f in plan.faults
             if f.kind == "sigkill" and f.at == "admission"),
            default=None)
        engine.pre_cycle_hooks.append(self._pre_cycle)
        engine.cycle_listeners.append(self._post_cycle)
        if self._kill_at_admission is not None:
            orig = engine._admit

            def admit_and_maybe_die(entry, bulk=None):
                orig(entry, bulk=bulk)
                self.admissions += 1
                if self.admissions == self._kill_at_admission:
                    _die()
            engine._admit = admit_and_maybe_die
        if any(f.kind in ("oracle-crash", "delay-verdict")
               for f in plan.faults):
            self._ensure_proxy()

    def _ensure_proxy(self):
        bridge = self.engine.oracle
        if bridge is None:
            raise RuntimeError(
                "oracle faults need an attached oracle "
                "(engine.attach_oracle() first)")
        if not isinstance(bridge.executor, _ExecutorFaultProxy):
            bridge.executor = _ExecutorFaultProxy(bridge.executor)
        self.proxy = bridge.executor

    def _pre_cycle(self, seq: int, engine) -> None:
        for f in self.plan.faults:
            if f.at != "cycle" or f.n != seq:
                continue
            if f.kind == "sigkill":
                self.fired.append(f"sigkill@cycle:{seq}")
                _die()
            elif f.kind == "torn-tail":
                if engine.journal is None:
                    raise RuntimeError("torn-tail fault needs a journal")
                _tear_journal_tail(engine.journal)
                self.fired.append(f"torn-tail@cycle:{seq}")
                _die()
            elif f.kind == "oracle-crash":
                self.proxy.crashed = True
                self.fired.append(f"oracle-crash@cycle:{seq}")
            elif f.kind == "delay-verdict":
                self.proxy.delay_ms = f.arg
                self.fired.append(f"delay-verdict@cycle:{seq}")
            elif f.kind == "lease-stall":
                if engine.ha is None:
                    raise RuntimeError(
                        "lease-stall fault needs an HA replica "
                        "(engine.ha unset — not running in HA mode)")
                engine.ha.suspend_renewal = True
                self.fired.append(f"lease-stall@cycle:{seq}")

    def _post_cycle(self, seq: int, result) -> None:
        # Transient faults clear at the cycle's end: the sidecar
        # "restarts" and the next cycle reconnects.
        if self.proxy is not None:
            self.proxy.crashed = False
            self.proxy.delay_ms = 0.0


def arm_faults(engine, plan) -> FaultInjector:
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    return FaultInjector(engine, plan)
