"""Fault injection for the admission pipeline, under replay or live
smoke (serve.py --fault / KUEUE_TPU_FAULT).

Spec grammar (comma-separated faults):

  sigkill@cycle:N          SIGKILL this process as cycle N begins
  sigkill@admission:N      SIGKILL mid-apply, at the Nth admission —
                           the journal's torn-tail + crash-recovery
                           path under a real half-applied cycle; the
                           ordinal counts per-entry (_admit) and bulk
                           (device-cycle columnar) admissions alike
  torn-tail@cycle:N        append a partial (newline-less, invalid)
                           record to the journal, fsync it, SIGKILL —
                           the exact artifact of a crash mid-append
  oracle-crash@cycle:N     the oracle executor raises transport errors
                           for the whole of cycle N (sidecar crash);
                           the bridge must fall back sequentially and
                           re-attach on the next cycle
  delay-verdict@cycle:N:MS the oracle's verdicts arrive MS late on
                           cycle N (slow sidecar) — decisions must be
                           unaffected, only phase timings move
  lease-stall@cycle:N      stop renewing the HA lease from cycle N on
                           (a wedged-but-alive leader): a standby must
                           steal the lease at expiry and the stale
                           leader's next journal write must die on
                           JournalFenced, not interleave
  enospc@cycle:N           every checkpoint write during cycle N fails
                           with ENOSPC (store.checkpoint.WRITE_FAULT) —
                           the previous checkpoint must stay the
                           newest valid one, the engine keeps running
  torn-checkpoint@cycle:N  truncate the newest sealed checkpoint file
                           to ~60% as cycle N begins — recovery must
                           reject it on the payload CRC and fall back
                           to the previous checkpoint + longer suffix
  sigkill@compaction:N     SIGKILL inside the Nth journal maintenance
                           event (segment rotation or compaction), at
                           the nastiest point: after the rename,
                           before cleanup/reopen
  clock-skew@cycle:N:MS    jump the engine clock forward MS ms at
                           cycle N (NTP step / VM freeze-thaw): every
                           decision downstream of the skewed stamps
                           must still replay identically from the
                           journal
  oracle-crash-storm@cycle:N:M
                           the executor raises transport errors for M
                           CONSECUTIVE cycles starting at N — long
                           enough to trip the supervisor's circuit
                           breaker (oracle/supervisor.py), which must
                           demote to the host path and re-promote
                           after the storm, digest-identical
  hang@cycle:N:MS          wedge the engine thread for MS ms as cycle
                           N begins (a GC stall / wedged device call):
                           the cycle watchdog's sampler thread
                           (obs/watchdog.py) must notice the in-flight
                           cycle mid-hang, capture stacks, and feed
                           its breaker. Attach the watchdog BEFORE
                           arming faults — its pre-cycle hook must
                           stamp the cycle start before the sleep.
  arrival-storm@cycle:N:M  submit M synthetic workloads as cycle N
                           begins (an open-loop burst landing straight
                           on the engine, past any front door):
                           admission stays exact — every storm
                           workload is journaled, zero lost/duplicate
  slow-consumer-flood@cycle:N:M
                           subscribe M never-draining SSE clients to
                           the fanout hub at cycle N: the hub's
                           slow-consumer policy must evict them
                           without stalling the cycle loop or any
                           live client
  disk-pressure-ramp@cycle:N:M
                           simulated free space collapses to zero for
                           M cycles starting at N (diskguard
                           FREE_BYTES_PROBE): the disk budget must
                           degrade read-only, scheduling park, and
                           the budget re-arm when the window passes —
                           no restart, nothing lost

The recovery contract these faults exist to prove: reboot via
store.journal.rebuild_engine and drain, and the admitted set equals an
uninterrupted run's — zero lost, zero duplicate admissions.

``ChaosSchedule`` expands one integer seed into a deterministic
multi-stage fault plan over those kinds (tools/chaos_smoke.py runs a
batch of seeds and asserts the recovery contract after every stage).

``FederationChaosSchedule`` is the multi-cell analog
(tools/federation_smoke.py): one seed expands into a deterministic
chain of federation faults (FEDERATION_KINDS) — a whole cell
SIGKILLed mid-admission, the dispatcher crashed between route-intent
fsync and handoff, a network partition, and the zombie cell's rejoin —
and the contract becomes GLOBAL: the union of per-cell admitted sets
equals the submitted set, pairwise disjoint (zero lost, zero
duplicate admissions across the federation).
"""

from __future__ import annotations

import os
import random
import signal
from dataclasses import dataclass, field

KINDS = ("sigkill", "torn-tail", "oracle-crash", "delay-verdict",
         "lease-stall", "enospc", "torn-checkpoint", "clock-skew",
         "oracle-crash-storm", "hang", "arrival-storm",
         "slow-consumer-flood", "disk-pressure-ramp")
POINTS = ("cycle", "admission", "compaction")


@dataclass
class Fault:
    kind: str        # one of KINDS
    at: str          # cycle | admission | compaction
    n: int           # trigger point (cycle seq / admission ordinal /
                     # maintenance-event ordinal)
    arg: float = 0.0  # delay-verdict + clock-skew: ms; storm: cycles


@dataclass
class FaultPlan:
    faults: list = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for part in filter(None, (s.strip() for s in spec.split(","))):
            try:
                kind, rest = part.split("@", 1)
                bits = rest.split(":")
                at, n = bits[0], int(bits[1])
                arg = float(bits[2]) if len(bits) > 2 else 0.0
            except (ValueError, IndexError):
                raise ValueError(
                    f"bad fault spec {part!r} "
                    "(want kind@cycle:N or kind@admission:N)") from None
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            if at not in POINTS:
                raise ValueError(f"unknown fault point {at!r}")
            if at != "cycle" and kind != "sigkill":
                raise ValueError(
                    f"{kind} only triggers at cycle boundaries")
            if kind == "clock-skew" and len(bits) < 3:
                raise ValueError(
                    "clock-skew needs a skew: clock-skew@cycle:N:MS")
            if kind == "oracle-crash-storm" and (
                    len(bits) < 3 or arg < 1 or arg != int(arg)):
                raise ValueError(
                    "oracle-crash-storm needs a whole cycle count "
                    ">= 1: oracle-crash-storm@cycle:N:M")
            if kind == "delay-verdict" and arg < 0:
                raise ValueError("delay-verdict delay must be >= 0 ms")
            if kind == "hang" and (len(bits) < 3 or arg <= 0):
                raise ValueError(
                    "hang needs a duration: hang@cycle:N:MS")
            if kind in ("arrival-storm", "slow-consumer-flood",
                        "disk-pressure-ramp") and (
                    len(bits) < 3 or arg < 1 or arg != int(arg)):
                raise ValueError(
                    f"{kind} needs a whole count >= 1: "
                    f"{kind}@cycle:N:M")
            plan.faults.append(Fault(kind, at, n, arg))
        return plan

    @property
    def lethal(self) -> bool:
        """True when some fault SIGKILLs the process (the plan's worker
        is expected to die rather than drain to completion)."""
        return any(f.kind in ("sigkill", "torn-tail")
                   for f in self.faults)

    @property
    def needs_oracle(self) -> bool:
        return any(f.kind in ("oracle-crash", "delay-verdict",
                              "oracle-crash-storm")
                   for f in self.faults)


def _die() -> None:
    # SIGKILL, not sys.exit: no atexit, no finally blocks, no flush —
    # the same crash the fault matrix is meant to prove recovery from.
    os.kill(os.getpid(), signal.SIGKILL)


def _tear_journal_tail(journal) -> None:
    """Plant the artifact of a crash mid-append: a flushed, newline-less
    JSON fragment at the end of the journal file."""
    with open(journal.path, "ab") as fh:
        fh.write(b'{"op":"apply","kind":"workload","ts":9')
        fh.flush()
        os.fsync(fh.fileno())


def _enospc(fh) -> None:
    """store.checkpoint.WRITE_FAULT payload: the disk is full."""
    import errno
    raise OSError(errno.ENOSPC, "injected: no space left on device")


class _ExecutorFaultProxy:
    """Wraps the oracle bridge's executor: raises transport errors while
    ``crashed`` is set, sleeps ``delay_ms`` before returning otherwise."""

    def __init__(self, inner, sleep=None):
        self.inner = inner
        self.crashed = False
        self.delay_ms = 0.0
        self.injected_errors = 0
        self.delayed_calls = 0
        if sleep is None:
            import time
            sleep = time.sleep
        self._sleep = sleep

    def _gate(self):
        from kueue_tpu.oracle.service import RemoteOracleError
        if self.crashed:
            self.injected_errors += 1
            raise RemoteOracleError("injected oracle crash")
        if self.delay_ms > 0:
            self._sleep(self.delay_ms / 1e3)
            self.delayed_calls += 1

    def cycle_step(self, tensors, statics):
        self._gate()
        return self.inner.cycle_step(tensors, statics)

    def classical_targets(self, tensors, statics, derived=None):
        self._gate()
        return self.inner.classical_targets(tensors, statics,
                                            derived=derived)

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()


class FaultInjector:
    """Armed on an engine: hooks the cycle boundary (pre_cycle_hooks)
    and the admission apply path (_admit)."""

    def __init__(self, engine, plan: FaultPlan, sleep=None):
        self.engine = engine
        self.plan = plan
        # Injected wait primitive: wall-clock sleep by default; the
        # simulator passes its virtual clock's sleep so a `hang` fault
        # advances compressed time instead of burning it
        # (kueue_tpu/sim/clock.py).
        if sleep is None:
            import time as _time
            sleep = _time.sleep
        self._sleep = sleep
        self.admissions = 0
        self.maintenance_events = 0
        self.fired: list[str] = []
        self.proxy = None
        self._enospc_until = None
        self._disk_ramp_until = None
        self._flood_clients: list = []
        # Storm coverage: [start, end) cycle ranges the executor stays
        # crashed through (vs the single-cycle oracle-crash, which the
        # post-cycle "sidecar restart" clears).
        self._storms = [(f.n, f.n + int(f.arg)) for f in plan.faults
                        if f.kind == "oracle-crash-storm"]
        self._kill_at_admission = min(
            (f.n for f in plan.faults
             if f.kind == "sigkill" and f.at == "admission"),
            default=None)
        self._kill_at_maintenance = min(
            (f.n for f in plan.faults
             if f.kind == "sigkill" and f.at == "compaction"),
            default=None)
        engine.pre_cycle_hooks.append(self._pre_cycle)
        engine.cycle_listeners.append(self._post_cycle)
        if self._kill_at_admission is not None:
            orig = engine._admit

            def admit_and_maybe_die(entry, bulk=None):
                orig(entry, bulk=bulk)
                self.admissions += 1
                if self.admissions == self._kill_at_admission:
                    _die()
            engine._admit = admit_and_maybe_die

            # The bulk assume path (oracle bridge device cycles) admits
            # its fast shape without per-entry _admit calls, so the
            # ordinal must count those too — sigkill@admission:N means
            # the same thing on every decision path. A batch that
            # crosses the ordinal applies exactly the prefix that
            # reaches it and dies mid-apply: in-memory state mutated,
            # the batch's journal records still buffered in the bulk
            # ctx (flush_bulk_admit never runs) — the widest torn
            # window the recovery contract covers. Slow entries inside
            # the prefix still count (and can kill) through the _admit
            # wrap above; the returned pairs are fast-path only, so the
            # two counters never double-count an admission.
            orig_bulk = engine.bulk_assume_batch

            def bulk_and_maybe_die(entries, bulk):
                entries = list(entries)
                budget = self._kill_at_admission - self.admissions
                if 0 < budget <= len(entries):
                    orig_bulk(entries[:budget], bulk)
                    self.admissions = self._kill_at_admission
                    self.fired.append(
                        f"sigkill@admission:{self._kill_at_admission}")
                    _die()
                pairs = orig_bulk(entries, bulk)
                self.admissions += len(pairs)
                return pairs
            engine.bulk_assume_batch = bulk_and_maybe_die
        if self._kill_at_maintenance is not None:
            from kueue_tpu.store import journal as _journal_mod

            def die_in_maintenance(event: str) -> None:
                self.maintenance_events += 1
                if self.maintenance_events == self._kill_at_maintenance:
                    self.fired.append(
                        f"sigkill@compaction:{self.maintenance_events}"
                        f" ({event})")
                    _die()
            _journal_mod.MAINTENANCE_CRASH_HOOK = die_in_maintenance
        if plan.needs_oracle:
            self._ensure_proxy()

    def _ensure_proxy(self):
        bridge = self.engine.oracle
        if bridge is None:
            raise RuntimeError(
                "oracle faults need an attached oracle "
                "(engine.attach_oracle() first)")
        if not isinstance(bridge.executor, _ExecutorFaultProxy):
            bridge.executor = _ExecutorFaultProxy(bridge.executor,
                                                  sleep=self._sleep)
        self.proxy = bridge.executor

    def _storm_covers(self, seq: int) -> bool:
        return any(start <= seq < end for start, end in self._storms)

    def _arrival_storm(self, engine, seq: int, count: int) -> None:
        """Inject ``count`` synthetic workloads straight into the
        engine (the open-loop burst, bypassing any serving front
        door). Deterministic: names carry the cycle seq, the target is
        the lexicographically first local queue."""
        from kueue_tpu.api.types import PodSet, Workload

        lqs = sorted(engine.queues.local_queues)
        if not lqs:
            raise RuntimeError(
                "arrival-storm needs at least one local queue")
        lq = engine.queues.local_queues[lqs[0]]
        for i in range(count):
            engine.submit(Workload(
                name=f"storm-{seq}-{i}", namespace=lq.namespace,
                queue_name=lq.name,
                pod_sets=(PodSet("main", 1, {"cpu": 100}),)))

    def _tear_newest_checkpoint(self, engine) -> None:
        ck = getattr(engine, "checkpointer", None)
        if ck is None:
            raise RuntimeError(
                "torn-checkpoint fault needs an attached Checkpointer")
        files = ck.store._indexed()
        if not files:
            return  # nothing sealed yet; the fault is a no-op
        path = files[-1][1]
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(1, int(size * 0.6)))

    def _pre_cycle(self, seq: int, engine) -> None:
        if self._enospc_until is not None and seq >= self._enospc_until:
            # Cleared at the NEXT cycle's start, not post-cycle: the
            # Checkpointer writes from a cycle listener that may run
            # after ours, and the fault must cover it.
            from kueue_tpu.store import checkpoint as _ckpt
            _ckpt.WRITE_FAULT = None
            self._enospc_until = None
        if (self._disk_ramp_until is not None
                and seq >= self._disk_ramp_until):
            from kueue_tpu.store import diskguard as _dg
            _dg.FREE_BYTES_PROBE = None
            self._disk_ramp_until = None
        for f in self.plan.faults:
            if f.at != "cycle" or f.n != seq:
                continue
            if f.kind == "sigkill":
                self.fired.append(f"sigkill@cycle:{seq}")
                _die()
            elif f.kind == "torn-tail":
                if engine.journal is None:
                    raise RuntimeError("torn-tail fault needs a journal")
                _tear_journal_tail(engine.journal)
                self.fired.append(f"torn-tail@cycle:{seq}")
                _die()
            elif f.kind == "oracle-crash":
                self.proxy.crashed = True
                self.fired.append(f"oracle-crash@cycle:{seq}")
            elif f.kind == "oracle-crash-storm":
                self.proxy.crashed = True
                self.fired.append(
                    f"oracle-crash-storm@cycle:{seq}:{int(f.arg)}")
            elif f.kind == "delay-verdict":
                self.proxy.delay_ms = f.arg
                self.fired.append(f"delay-verdict@cycle:{seq}")
            elif f.kind == "enospc":
                from kueue_tpu.store import checkpoint as _ckpt
                _ckpt.WRITE_FAULT = _enospc
                self._enospc_until = seq + 1
                self.fired.append(f"enospc@cycle:{seq}")
            elif f.kind == "torn-checkpoint":
                self._tear_newest_checkpoint(engine)
                self.fired.append(f"torn-checkpoint@cycle:{seq}")
            elif f.kind == "clock-skew":
                engine.clock += f.arg / 1e3
                self.fired.append(
                    f"clock-skew@cycle:{seq}:{f.arg:g}")
            elif f.kind == "lease-stall":
                if engine.ha is None:
                    raise RuntimeError(
                        "lease-stall fault needs an HA replica "
                        "(engine.ha unset — not running in HA mode)")
                engine.ha.suspend_renewal = True
                self.fired.append(f"lease-stall@cycle:{seq}")
            elif f.kind == "hang":
                self.fired.append(f"hang@cycle:{seq}:{f.arg:g}")
                # The engine thread wedges here, mid-cycle from the
                # watchdog's point of view (its pre-cycle hook already
                # stamped the start when it was attached first). Under
                # a virtual clock the sleep is an instant advance and
                # the watchdog's daemon poll events observe the hang
                # inside this very call.
                self._sleep(f.arg / 1e3)
            elif f.kind == "arrival-storm":
                self._arrival_storm(engine, seq, int(f.arg))
                self.fired.append(
                    f"arrival-storm@cycle:{seq}:{int(f.arg)}")
            elif f.kind == "slow-consumer-flood":
                hub = getattr(engine, "fanout", None)
                if hub is None:
                    raise RuntimeError(
                        "slow-consumer-flood needs a fanout hub "
                        "(engine.fanout unset)")
                # Subscribed, never drained: their queues fill, drops
                # accrue, and the hub's eviction policy must fire.
                self._flood_clients.extend(
                    hub.subscribe() for _ in range(int(f.arg)))
                self.fired.append(
                    f"slow-consumer-flood@cycle:{seq}:{int(f.arg)}")
            elif f.kind == "disk-pressure-ramp":
                from kueue_tpu.store import diskguard as _dg
                _dg.FREE_BYTES_PROBE = lambda path: 0
                self._disk_ramp_until = seq + int(f.arg)
                self.fired.append(
                    f"disk-pressure-ramp@cycle:{seq}:{int(f.arg)}")

    def _post_cycle(self, seq: int, result) -> None:
        # Transient faults clear at the cycle's end: the sidecar
        # "restarts" and the next cycle reconnects. A storm holds the
        # crash through its whole [start, end) range.
        if self.proxy is not None:
            self.proxy.crashed = self._storm_covers(seq + 1)
            self.proxy.delay_ms = 0.0


def arm_faults(engine, plan, sleep=None) -> FaultInjector:
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    return FaultInjector(engine, plan, sleep=sleep)


@dataclass
class ChaosStage:
    """One worker process's life: a fault spec, how many drain cycles
    it gets, and whether the plan is expected to SIGKILL it."""
    spec: str
    cycles: int
    lethal: bool
    needs_oracle: bool


class ChaosSchedule:
    """Expand one integer seed into a deterministic multi-stage,
    multi-fault plan (tools/chaos_smoke.py's input).

    Stage = one worker process: it reboots from the journal
    (checkpoint base + suffix when one exists), drains under its fault
    plan, and either dies (lethal stage — the next stage is the crash
    recovery) or drains clean. Every stage before the last is lethal so
    each seed exercises a chain of crash/recover transitions; the final
    stage always runs fault-free to completion so the terminal state is
    comparable with the control arm. Cycle numbers restart per process
    (Engine.cycle_seq starts at 0 after every reboot), so each stage's
    triggers are drawn independently in [1, cycles).

    Same seed → byte-identical stages; replay/ is outside graftlint's
    determinism zones precisely so seeded PRNG expansion like this is
    legal here.
    """

    LETHAL = ("sigkill@cycle:{n}",
              "sigkill@admission:{adm}",
              "torn-tail@cycle:{n}",
              "sigkill@compaction:{maint}")
    # BENIGN faults must be INPUT-NEUTRAL: the terminal state is
    # compared byte-for-byte against a fault-free control arm, so a
    # benign fault may delay or reroute decisions but never add or
    # remove inputs. disk-pressure-ramp qualifies (scheduling parks,
    # then resumes — same admitted set, later). arrival-storm does NOT
    # (it injects workloads the control arm never saw); hang and
    # slow-consumer-flood need a watchdog/fanout hub the chaos workers
    # don't attach — all three are exercised by tools/overload_smoke.py
    # and tests/test_overload.py instead.
    BENIGN = ("oracle-crash@cycle:{n}",
              "oracle-crash-storm@cycle:{n}:{m}",
              "enospc@cycle:{n}",
              "torn-checkpoint@cycle:{n}",
              "clock-skew@cycle:{n}:{ms}",
              "disk-pressure-ramp@cycle:{n}:{m}")

    def __init__(self, seed: int, stages: int = 3,
                 cycles_per_stage: int = 24, oracle: bool = True):
        self.seed = int(seed)
        self.n_stages = max(2, int(stages))
        self.cycles_per_stage = max(8, int(cycles_per_stage))
        self.oracle = oracle

    def stages(self) -> list:
        rng = random.Random(self.seed)
        benign = [t for t in self.BENIGN
                  if self.oracle or not t.startswith("oracle")]
        out = []
        for i in range(self.n_stages):
            last = i == self.n_stages - 1
            faults = []
            if not last:
                lethal_at = rng.randrange(
                    self.cycles_per_stage // 2, self.cycles_per_stage)
                for tmpl in rng.sample(benign, rng.randrange(0, 3)):
                    # Benign faults land strictly before the lethal one
                    # so they demonstrably fire.
                    faults.append(tmpl.format(
                        n=rng.randrange(1, max(2, lethal_at)),
                        m=rng.randrange(2, 6),
                        ms=rng.choice([250, 1000, 5000])))
                faults.append(rng.choice(self.LETHAL).format(
                    n=lethal_at, adm=rng.randrange(2, 9),
                    maint=rng.randrange(1, 4)))
            spec = ",".join(faults)
            plan = FaultPlan.parse(spec)
            out.append(ChaosStage(
                spec=spec, cycles=self.cycles_per_stage,
                lethal=plan.lethal or any(
                    f.at in ("admission", "compaction")
                    for f in plan.faults),
                needs_oracle=plan.needs_oracle))
        return out


# -- multi-cell federation faults (kueue_tpu/federation) --

FEDERATION_KINDS = ("cell-sigkill", "dispatcher-crash", "partition",
                    "zombie-rejoin")


@dataclass
class FederationEvent:
    """One fault in a federation chaos chain.

    kind      one of FEDERATION_KINDS
    cell      victim cell name ("" = the dispatcher itself)
    at        trigger ordinal — submissions completed for cell-sigkill
              and partition, dispatcher HANDOFFS attempted for
              dispatcher-crash (the HANDOFF_CRASH_HOOK coordinate:
              after the route intent is durable, before the send)
    arg       partition: width of the outage window in dispatcher
              ticks; zombie-rejoin carries 0
    """
    kind: str
    cell: str
    at: int
    arg: int = 0


class PartitionedTransport:
    """Network-partition proxy around a federation cell transport:
    while ``partitioned`` is set every call raises CellTransportError
    — the cell process is healthy, the dispatcher just cannot reach
    it. Distinct from cell-sigkill: here the cell's own journal keeps
    advancing, so reconnection must NOT be treated as a rejoin that
    lost state."""

    def __init__(self, inner):
        self.inner = inner
        self.partitioned = False
        self.dropped = 0

    def _gate(self) -> None:
        if self.partitioned:
            from kueue_tpu.federation.cells import CellTransportError
            self.dropped += 1
            raise CellTransportError("injected network partition")

    @property
    def events_url(self) -> str:
        return self.inner.events_url

    def submit(self, wl_jsonable, route_epoch=None):
        self._gate()
        return self.inner.submit(wl_jsonable, route_epoch=route_epoch)

    def health(self):
        self._gate()
        return self.inner.health()

    def workloads(self):
        self._gate()
        return self.inner.workloads()

    def revoke(self, keys, epoch):
        self._gate()
        return self.inner.revoke(keys, epoch)


class FederationChaosSchedule:
    """Expand one integer seed into a deterministic federation fault
    chain over ``cells`` (tools/federation_smoke.py's input).

    Every chain is multi-fault by construction: one cell is SIGKILLed
    mid-admission stream (the whole-cell failure the drain path
    exists for) and ALWAYS rejoins later as a zombie (the fencing
    path); the dispatcher crashes once between route-intent fsync and
    handoff (the exactly-once recovery path); and about half the
    seeds additionally partition a DIFFERENT cell for a bounded
    window. Same seed → identical event list, independent of
    PYTHONHASHSEED (cells are sorted before any draw).
    """

    def __init__(self, seed: int, cells, workloads: int = 24):
        self.seed = int(seed)
        self.cells = sorted(cells)
        self.workloads = max(8, int(workloads))
        if len(self.cells) < 2:
            raise ValueError("federation chaos needs >= 2 cells")

    def events(self) -> list:
        rng = random.Random(self.seed)
        n = self.workloads
        victim = rng.choice(self.cells)
        out = [
            # Mid-stream: enough admissions before it to seed state on
            # the victim, enough after to force re-routing under load.
            FederationEvent("cell-sigkill", victim,
                            rng.randrange(n // 4, n // 2)),
            FederationEvent("dispatcher-crash", "",
                            rng.randrange(2, n // 2)),
        ]
        if rng.random() < 0.5:
            survivors = [c for c in self.cells if c != victim]
            out.append(FederationEvent(
                "partition", rng.choice(survivors),
                rng.randrange(n // 2, 3 * n // 4),
                arg=rng.randrange(4, 10)))
        out.append(FederationEvent(
            "zombie-rejoin", victim, rng.randrange(3 * n // 4, n)))
        return out
