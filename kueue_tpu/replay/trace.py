"""Trace framing: versioned, checksummed JSONL.

One JSON object ("frame") per line. Frame kinds:

  header  {"f":"header","version":1,"schema":<api schema>,"label":...}
  input   {"f":"input","clock":c,"method":m,"args":[...],"kwargs":{..}}
  idle    {"f":"idle","n":k,"clock":c}        k coalesced idle cycles
  cycle   {"f":"cycle","seq":s,"clock":c,"mode":m,
           "decisions":[...],"digest":"%08x","phases":{...}}
  end     {"f":"end","frames":N,"digest":"%08x"}

Integrity: every frame carries ``crc`` = CRC-32 of its canonical JSON
(sans crc) chained from the previous frame's crc — flipping a byte or
dropping a line invalidates every later frame, so a reader can prove a
trace prefix is exactly what the recorder wrote. A torn final line
(crash mid-write) is tolerated and reported as ``truncated``; corruption
anywhere else raises TraceCorruption. The running ``digest`` chains the
per-cycle decision digests: two traces with equal digests carry
byte-identical decision streams (what ``make replay-smoke`` diffs).

Decision canonicalization is order-insensitive WITHIN a cycle (sorted by
workload key): the host path commits entries in nomination order while
the device path applies verdict slots in launch order, but the cycle's
semantic outcome — who got admitted with which flavors/counts/topology,
who got preempted — is path-invariant (the same contract
tests/golden_ref/schedule_harness.py asserts). Cycle ORDER remains
significant: the stream digest chains cycles in sequence.
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Iterator, Optional

TRACE_VERSION = 1


class TraceCorruption(Exception):
    """The trace fails its frame CRC chain (tamper or mid-file
    corruption — distinct from a tolerated torn tail)."""


def _canon_bytes(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def frame_crc(frame: dict, prev_crc: int) -> int:
    body = {k: v for k, v in frame.items() if k != "crc"}
    return zlib.crc32(_canon_bytes(body), prev_crc)


def canonical_decisions(result) -> list:
    """The cycle's semantic decision record, path-invariant (see module
    docstring): [admitted, preempting], or [] when the cycle decided
    nothing. ``result`` is a CycleResult or None (idle).

    Only admissions and initiated preemptions are canonical — exactly
    the observables the golden host/device parity harness asserts
    (tests/golden_ref/schedule_harness.py observe()). Skipped/parked
    heads are NOT: the host path materializes them as entries while a
    device cycle reports only decided slots, and a cycle that decides
    nothing surfaces as an entry-less result on one path and an idle
    None on the other — representation, not decisions.

    Memoized per result object: at cycle end the flight recorder, the
    tracer and any digest-chaining listener each canonicalize the same
    (by then immutable) CycleResult — one walk serves them all."""
    if result is None:
        return []
    cached = getattr(result, "_canonical_decisions", None)
    if cached is not None:
        return cached
    from kueue_tpu.scheduler.cycle import EntryStatus

    def topo(psa) -> Optional[list]:
        ta = getattr(psa, "topology_assignment", None)
        if ta is None:
            return None
        return [list(ta.levels),
                sorted([list(d.values), d.count] for d in ta.domains)]

    admitted = []
    preempting = []
    for e in list(result.entries) + list(result.inadmissible):
        if e.status == EntryStatus.ASSUMED:
            adm = e.obj.status.admission
            admitted.append([
                e.info.key, adm.cluster_queue,
                [[psa.name, sorted(psa.flavors.items()),
                  sorted(psa.resource_usage.items()), psa.count,
                  topo(psa)]
                 for psa in adm.pod_set_assignments]])
        elif e.status == EntryStatus.PREEMPTING:
            preempting.append([
                e.info.key,
                sorted(t.workload.key for t in e.preemption_targets)])
    decisions = ([] if not admitted and not preempting
                 else [sorted(admitted), sorted(preempting)])
    result._canonical_decisions = decisions
    return decisions


def decision_digest(decisions: list, prev: int = 0) -> int:
    return zlib.crc32(_canon_bytes(decisions), prev)


class TraceWriter:
    """Append frames with CRC chaining; flush per frame, fsync on cycle
    frames (the trace must survive the SIGKILL faults it exists to
    diagnose)."""

    def __init__(self, path: str, label: str = "", fsync: bool = True):
        from kueue_tpu.api.conversion import SCHEMA_VERSION

        self.path = path
        self.fsync = fsync
        self._fh = open(path, "w", encoding="utf-8")
        self._crc = 0
        self._digest = 0
        self.frames = 0
        self.cycles = 0
        self._write({"f": "header", "version": TRACE_VERSION,
                     "schema": SCHEMA_VERSION, "label": label})

    def _write(self, frame: dict, sync: bool = False) -> None:
        self._crc = frame_crc(frame, self._crc)
        frame["crc"] = self._crc
        self._fh.write(json.dumps(frame, sort_keys=True,
                                  separators=(",", ":")) + "\n")
        self._fh.flush()
        if sync and self.fsync:
            import os
            os.fsync(self._fh.fileno())
        self.frames += 1

    def input(self, clock: float, method: str, args: list,
              kwargs: dict) -> None:
        frame: dict = {"f": "input", "clock": clock, "method": method,
                       "args": args}
        if kwargs:
            frame["kwargs"] = kwargs
        self._write(frame)

    def idle(self, n: int, clock: float) -> None:
        if n > 0:
            self._write({"f": "idle", "n": n, "clock": clock})

    def cycle(self, seq: int, clock: float, mode: str, decisions: list,
              phases: dict, verdict_digest: Optional[int] = None,
              cid: Optional[str] = None) -> None:
        self._digest = decision_digest(decisions, self._digest)
        frame = {"f": "cycle", "seq": seq, "clock": clock, "mode": mode,
                 "decisions": decisions,
                 "digest": f"{self._digest:08x}",
                 "phases": {k: round(v, 6) for k, v in phases.items()}}
        if verdict_digest is not None:
            frame["verdict"] = f"{verdict_digest:08x}"
        if cid is not None:
            # Correlation id joining this frame to the journal's
            # cycle_trace record and the tracer's span tree. Carried
            # OUTSIDE the decision digest (which hashes decisions only):
            # traced and untraced recordings stay digest-identical.
            frame["cid"] = cid
        self._write(frame, sync=True)
        self.cycles += 1

    @property
    def digest(self) -> str:
        return f"{self._digest:08x}"

    def close(self) -> None:
        if self._fh.closed:
            return
        self._write({"f": "end", "frames": self.frames,
                     "digest": self.digest}, sync=True)
        self._fh.close()


class TraceReader:
    """Validate the CRC chain while iterating frames. ``truncated`` is
    set when the trace lacks its end frame (crash mid-record); a frame
    that fails its CRC raises TraceCorruption."""

    def __init__(self, path: str):
        self.path = path
        self.header: Optional[dict] = None
        self.truncated = False
        self.digest = ""
        self.frames = 0

    def __iter__(self) -> Iterator[dict]:
        crc = 0
        saw_end = False
        with open(self.path, encoding="utf-8") as fh:
            lines = fh.read().split("\n")
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                frame = json.loads(line)
            except json.JSONDecodeError:
                # Only a torn FINAL line is a crash artifact; a bad
                # line with valid frames after it is corruption.
                if any(rest.strip() for rest in lines[i + 1:]):
                    raise TraceCorruption(
                        f"{self.path}:{i + 1}: unparseable frame "
                        "followed by more frames") from None
                self.truncated = True
                break
            want = frame.get("crc")
            crc = frame_crc(frame, crc)
            if crc != want:
                raise TraceCorruption(
                    f"{self.path}:{i + 1}: frame CRC mismatch "
                    f"(got {want}, chain says {crc}) — trace was "
                    "modified or records were dropped")
            self.frames += 1
            kind = frame.get("f")
            if kind == "header":
                if frame.get("version") != TRACE_VERSION:
                    raise TraceCorruption(
                        f"unsupported trace version "
                        f"{frame.get('version')}")
                self.header = frame
                continue
            if kind == "end":
                self.digest = frame.get("digest", "")
                saw_end = True
                continue
            if kind == "cycle":
                self.digest = frame.get("digest", self.digest)
            yield frame
        if self.header is None:
            raise TraceCorruption(f"{self.path}: missing header frame")
        if not saw_end:
            self.truncated = True
