"""Workload accounting helpers — the equivalent of the reference's
pkg/workload (workload.Info, usage computation, eviction/admission helpers).

A ``WorkloadInfo`` wraps an api.Workload with its resolved ClusterQueue and
per-PodSet total requests plus (once assigned/admitted) the per-resource
flavor assignment, from which quota usage is derived.
Reference: pkg/workload/workload.go:215 (Info), resources.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import (
    Admission,
    FlavorResource,
    PodSetAssignmentStatus,
    Workload,
)


@dataclass(frozen=True)
class ResourceTransformation:
    """One input-resource mapping applied to effective requests.

    Reference: apis/config/v1beta1/configuration_types.go:560
    (ResourceTransformation) — strategy Retain keeps the input resource
    alongside the outputs, Replace drops it; ``outputs`` maps output
    resource name -> factor multiplied into the input quantity;
    ``multiply_by`` optionally scales the input by another resource's
    quantity first (counter-based DRA resources)."""

    input: str
    outputs: dict[str, float] = field(default_factory=dict)
    strategy: str = "Retain"
    multiply_by: str = ""


@dataclass(frozen=True)
class InfoOptions:
    """Reference: pkg/workload/workload.go:123 (InfoOptions) — the knobs
    that shape effective requests at Info construction time."""

    excluded_resource_prefixes: tuple[str, ...] = ()
    transformations: dict[str, ResourceTransformation] = field(
        default_factory=dict)

    @classmethod
    def from_transform_list(cls, transforms, excluded=()) -> "InfoOptions":
        return cls(excluded_resource_prefixes=tuple(excluded),
                   transformations={t.input: t for t in transforms})


def apply_resource_transformations(
        requests: dict[str, int],
        transforms: dict[str, ResourceTransformation]) -> dict[str, int]:
    """pkg/workload/workload.go:516 applyResourceTransformations."""
    if not transforms or not any(r in transforms for r in requests):
        return requests
    out: dict[str, int] = {}
    for res, qty in requests.items():
        mapping = transforms.get(res)
        if mapping is None:
            out[res] = out.get(res, 0) + qty
            continue
        eff = qty
        if mapping.multiply_by and mapping.multiply_by in requests:
            # The multiplied quantity is what Retain keeps, matching the
            # reference (workload.go:530-546 mutates inputQuantity before
            # both the outputs loop and the Retain branch).
            eff = qty * requests[mapping.multiply_by]
        for out_name, factor in mapping.outputs.items():
            out[out_name] = out.get(out_name, 0) + int(eff * factor)
        if mapping.strategy == "Retain":
            out[res] = out.get(res, 0) + eff
    return out


def drop_excluded_resources(requests: dict[str, int],
                            prefixes: tuple[str, ...]) -> dict[str, int]:
    """pkg/workload/workload.go (dropExcludedResources)."""
    if not prefixes:
        return requests
    return {r: q for r, q in requests.items()
            if not any(r.startswith(p) for p in prefixes)}


@dataclass
class PodSetResources:
    """Total (count-scaled) requests of one PodSet with flavor assignment.

    Reference: pkg/workload/workload.go (PodSetResources).
    """

    name: str
    count: int
    requests: dict[str, int] = field(default_factory=dict)  # total, not per-pod
    flavors: dict[str, str] = field(default_factory=dict)  # resource -> flavor

    def scaled_to(self, count: int) -> "PodSetResources":
        if self.count == count or self.count == 0:
            return PodSetResources(self.name, count, dict(self.requests),
                                   dict(self.flavors))
        scaled = {r: (q // self.count) * count for r, q in self.requests.items()}
        return PodSetResources(self.name, count, scaled, dict(self.flavors))

    def single_pod_requests(self) -> dict[str, int]:
        if self.count == 0:
            return {}
        return {r: q // self.count for r, q in self.requests.items()}


@dataclass(frozen=True)
class Ordering:
    """pkg/workload/workload.go (Ordering): which timestamp drives FIFO
    for workloads evicted by the WaitForPodsReady timeout —
    config.EvictionTimestamp (default) or config.CreationTimestamp."""

    pods_ready_requeuing_timestamp: str = "Eviction"


DEFAULT_ORDERING = Ordering()
_EPSILON = 1e-3  # the reference's time.Millisecond nudge


def queue_order_timestamp(wl: Workload,
                          ordering: Ordering = DEFAULT_ORDERING) -> float:
    """workload.go:1087 (Ordering.GetQueueOrderTimestamp): FIFO uses the
    eviction timestamp for PodsReady-timeout and admission-check
    evictions, and — when priority sorting is disabled — nudges
    InCohortReclaimWhileBorrowing preemptees just past their preemptor."""
    from kueue_tpu.api.types import WorkloadConditionType as WCT
    from kueue_tpu.config import features

    evicted = wl.condition(WCT.EVICTED)
    if evicted is not None and evicted.status:
        if (ordering.pods_ready_requeuing_timestamp == "Eviction"
                and evicted.reason == "PodsReadyTimeout"):
            return evicted.last_transition_time
        if evicted.reason == "AdmissionCheck":
            return evicted.last_transition_time
    if not features.enabled("PrioritySortingWithinCohort"):
        preempted = wl.condition(WCT.PREEMPTED)
        if (preempted is not None and preempted.status
                and preempted.reason == "InCohortReclaimWhileBorrowing"):
            return preempted.last_transition_time + _EPSILON
    return wl.creation_time


@dataclass
class WorkloadInfo:
    """Reference: pkg/workload/workload.go:215 (Info)."""

    obj: Workload
    cluster_queue: str = ""
    total_requests: list[PodSetResources] = field(default_factory=list)
    # Flavor-assignment resume state (reference: AssignmentClusterQueueState).
    last_assignment_flavor_idx: Optional[list[dict[str, int]]] = None
    last_assignment_generation: int = -1
    # AdmissionFairSharing: LocalQueue's historical usage, if AFS is on.
    local_queue_fs_usage: Optional[float] = None

    @classmethod
    def from_workload(cls, wl: Workload, cluster_queue: str = "",
                      options: Optional[InfoOptions] = None) -> "WorkloadInfo":
        info = cls(obj=wl, cluster_queue=cluster_queue)
        # Zero-quantity requests are KEPT: a zero request for a resource
        # the ClusterQueue covers still receives a flavor assignment
        # (flavorassigner_test.go "zero resource request defined in
        # clusterQueue should get flavor assigned"); zero requests for
        # uncovered resources are skipped at assignment time instead.
        # Effective requests: drop excluded prefixes, then resource
        # transformations (workload.go:623-626 totalRequestsFromPodSets).
        info.total_requests = []
        for ps in wl.pod_sets:
            per_pod = ps.requests
            if options is not None:
                per_pod = drop_excluded_resources(
                    per_pod, options.excluded_resource_prefixes)
                per_pod = apply_resource_transformations(
                    per_pod, options.transformations)
            info.total_requests.append(PodSetResources(
                name=ps.name,
                count=ps.count,
                requests={r: q * ps.count for r, q in per_pod.items()},
            ))
        if wl.status.admission is not None:
            info.apply_admission(wl.status.admission)
        # Reclaimable pods free their share of the quota while the rest of
        # the workload keeps running (workload_types.go:874, applied after
        # admission so the reduction survives count scaling). Gated:
        # kube_features.go ReclaimablePods.
        from kueue_tpu.config import features
        if features.enabled("ReclaimablePods"):
            for psr in info.total_requests:
                reclaimed = wl.status.reclaimable_pods.get(psr.name, 0)
                if reclaimed > 0:
                    scaled = psr.scaled_to(max(psr.count - reclaimed, 0))
                    psr.count = scaled.count
                    psr.requests = scaled.requests
        return info

    @property
    def key(self) -> str:
        return self.obj.key

    def apply_admission(self, admission: Admission) -> None:
        """Sync flavors (and possibly reduced counts) from an Admission."""
        self.cluster_queue = admission.cluster_queue
        by_name = {psa.name: psa for psa in admission.pod_set_assignments}
        for psr in self.total_requests:
            psa = by_name.get(psr.name)
            if psa is None:
                continue
            if psa.count and psa.count != psr.count:
                scaled = psr.scaled_to(psa.count)
                psr.count = scaled.count
                psr.requests = scaled.requests
            psr.flavors = dict(psa.flavors)

    def usage(self) -> dict[FlavorResource, int]:
        """FlavorResource quantities this workload counts against quota.

        Reference: workload.Info.Usage / FlavorResourceUsage.
        """
        out: dict[FlavorResource, int] = {}
        for psr in self.total_requests:
            for res, qty in psr.requests.items():
                if qty == 0:
                    continue
                flavor = psr.flavors.get(res)
                if flavor is None:
                    continue
                fr = FlavorResource(flavor, res)
                out[fr] = out.get(fr, 0) + qty
        return out

    def tas_domains(self, tas_flavor_names) -> list:
        """TAS usage tuples (flavor, values, single_pod_requests, count)
        from the admission's topology assignments
        (workload.Info TASUsage)."""
        adm = self.obj.status.admission
        if adm is None:
            return []
        out = []
        by_name = {psr.name: psr for psr in self.total_requests}
        for psa in adm.pod_set_assignments:
            ta = psa.topology_assignment
            if ta is None:
                continue
            flavor = next((f for f in psa.flavors.values()
                           if f in tas_flavor_names), None)
            if flavor is None:
                continue
            psr = by_name.get(psa.name)
            single = psr.single_pod_requests() if psr else {}
            for dom in ta.domains:
                out.append((flavor, tuple(dom.values), single, dom.count))
        return out

    def uses_any(self, frs: set[FlavorResource]) -> bool:
        """Reference: classical.WorkloadUsesResources
        (candidate_generator.go:54)."""
        for psr in self.total_requests:
            for res, flavor in psr.flavors.items():
                if FlavorResource(flavor, res) in frs:
                    return True
        return False


def adjust_resources(wl: Workload, limit_ranges=None,
                     runtime_class_overheads=None) -> None:
    """The reference's pre-queue request adjustment
    (pkg/workload/resources.go:141 AdjustResources): for every PodSet
    carrying a pod template, resolve RuntimeClass overhead, merge
    LimitRange container defaults, promote limits to missing requests,
    and recompute the PodSet's per-pod ``requests`` with the pod-requests
    aggregation. PodSets without a template are left verbatim."""
    from kueue_tpu.utils import limitrange as lr
    from kueue_tpu.utils import podtemplate as pt

    summary = None
    if limit_ranges:
        in_ns = [r for r in limit_ranges if r.namespace == wl.namespace]
        if in_ns:
            summary = lr.summarize(in_ns)
    for ps in wl.pod_sets:
        template = ps.template
        if template is None:
            continue
        # Pod overhead from RuntimeClass (resources.go:59
        # handlePodOverhead): only when not already set on the template.
        if (template.runtime_class_name and not template.overhead
                and runtime_class_overheads):
            template.overhead = dict(runtime_class_overheads.get(
                template.runtime_class_name, {}))
        if summary is not None:
            lr.apply_defaults(template, summary)
        pt.use_limits_as_missing_requests(template)
        ps.requests = pt.pod_requests(template)


def namespace_selector_mismatch(selector, labels) -> bool:
    """The CQ namespace-selector match predicate, shared by the
    nomination check (scheduler/cycle.py) and the device bridge's
    per-head demotion so the two can never diverge."""
    if selector is None:
        return False
    labels = labels or {}
    return any(labels.get(k) != v for k, v in selector.items())


def validate_admissibility(wl: Workload, limit_ranges=None,
                           namespace_labels=None) -> Optional[str]:
    """pkg/workload/resources.go:233 ValidateAdmissibility:
    requests<=limits, LimitRange bounds. Returns the first failure
    message, or None when admissible. The namespace-selector check runs
    at nomination time (namespace_selector_mismatch)."""
    from kueue_tpu.utils import limitrange as lr
    from kueue_tpu.utils import podtemplate as pt

    summary = None
    if limit_ranges:
        in_ns = [r for r in limit_ranges if r.namespace == wl.namespace]
        if in_ns:
            summary = lr.summarize(in_ns)
    for ps in wl.pod_sets:
        if ps.template is None:
            continue
        errs = pt.validate_requests_under_limits(ps.template)
        if errs:
            return "resources validation failed: " + "; ".join(errs)
        if summary is not None:
            errs = lr.validate_template(ps.template, summary)
            if errs:
                return ("resources didn't satisfy LimitRange constraints: "
                        + "; ".join(errs))
    return None


def admission_from_assignment(cluster_queue: str, pod_sets) -> Admission:
    """Build an api Admission from scheduler PodSetAssignments."""
    return Admission(
        cluster_queue=cluster_queue,
        pod_set_assignments=tuple(
            PodSetAssignmentStatus(
                name=psa.name,
                flavors={res: getattr(fa, "name", fa)
                         for res, fa in psa.flavors.items()},
                resource_usage=dict(psa.requests),
                count=psa.count,
                topology_assignment=psa.topology_assignment,
            )
            for psa in pod_sets
        ),
    )
