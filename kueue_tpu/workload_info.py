"""Workload accounting helpers — the equivalent of the reference's
pkg/workload (workload.Info, usage computation, eviction/admission helpers).

A ``WorkloadInfo`` wraps an api.Workload with its resolved ClusterQueue and
per-PodSet total requests plus (once assigned/admitted) the per-resource
flavor assignment, from which quota usage is derived.
Reference: pkg/workload/workload.go:215 (Info), resources.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import (
    Admission,
    FlavorResource,
    PodSetAssignmentStatus,
    Workload,
)


@dataclass
class PodSetResources:
    """Total (count-scaled) requests of one PodSet with flavor assignment.

    Reference: pkg/workload/workload.go (PodSetResources).
    """

    name: str
    count: int
    requests: dict[str, int] = field(default_factory=dict)  # total, not per-pod
    flavors: dict[str, str] = field(default_factory=dict)  # resource -> flavor

    def scaled_to(self, count: int) -> "PodSetResources":
        if self.count == count or self.count == 0:
            return PodSetResources(self.name, count, dict(self.requests),
                                   dict(self.flavors))
        scaled = {r: (q // self.count) * count for r, q in self.requests.items()}
        return PodSetResources(self.name, count, scaled, dict(self.flavors))

    def single_pod_requests(self) -> dict[str, int]:
        if self.count == 0:
            return {}
        return {r: q // self.count for r, q in self.requests.items()}


@dataclass
class WorkloadInfo:
    """Reference: pkg/workload/workload.go:215 (Info)."""

    obj: Workload
    cluster_queue: str = ""
    total_requests: list[PodSetResources] = field(default_factory=list)
    # Flavor-assignment resume state (reference: AssignmentClusterQueueState).
    last_assignment_flavor_idx: Optional[list[dict[str, int]]] = None
    last_assignment_generation: int = -1
    # AdmissionFairSharing: LocalQueue's historical usage, if AFS is on.
    local_queue_fs_usage: Optional[float] = None

    @classmethod
    def from_workload(cls, wl: Workload, cluster_queue: str = "") -> "WorkloadInfo":
        info = cls(obj=wl, cluster_queue=cluster_queue)
        # Zero-quantity requests carry no scheduling information and are
        # dropped (pod specs don't list zero resources; reference skips
        # them in usage accounting, flavorassigner.go:229-234).
        info.total_requests = [
            PodSetResources(
                name=ps.name,
                count=ps.count,
                requests={r: q * ps.count for r, q in ps.requests.items()
                          if q != 0},
            )
            for ps in wl.pod_sets
        ]
        if wl.status.admission is not None:
            info.apply_admission(wl.status.admission)
        # Reclaimable pods free their share of the quota while the rest of
        # the workload keeps running (workload_types.go:874, applied after
        # admission so the reduction survives count scaling).
        for psr in info.total_requests:
            reclaimed = wl.status.reclaimable_pods.get(psr.name, 0)
            if reclaimed > 0:
                scaled = psr.scaled_to(max(psr.count - reclaimed, 0))
                psr.count = scaled.count
                psr.requests = scaled.requests
        return info

    @property
    def key(self) -> str:
        return self.obj.key

    def apply_admission(self, admission: Admission) -> None:
        """Sync flavors (and possibly reduced counts) from an Admission."""
        self.cluster_queue = admission.cluster_queue
        by_name = {psa.name: psa for psa in admission.pod_set_assignments}
        for psr in self.total_requests:
            psa = by_name.get(psr.name)
            if psa is None:
                continue
            if psa.count and psa.count != psr.count:
                scaled = psr.scaled_to(psa.count)
                psr.count = scaled.count
                psr.requests = scaled.requests
            psr.flavors = dict(psa.flavors)

    def usage(self) -> dict[FlavorResource, int]:
        """FlavorResource quantities this workload counts against quota.

        Reference: workload.Info.Usage / FlavorResourceUsage.
        """
        out: dict[FlavorResource, int] = {}
        for psr in self.total_requests:
            for res, qty in psr.requests.items():
                if qty == 0:
                    continue
                flavor = psr.flavors.get(res)
                if flavor is None:
                    continue
                fr = FlavorResource(flavor, res)
                out[fr] = out.get(fr, 0) + qty
        return out

    def tas_domains(self, tas_flavor_names) -> list:
        """TAS usage tuples (flavor, values, single_pod_requests, count)
        from the admission's topology assignments
        (workload.Info TASUsage)."""
        adm = self.obj.status.admission
        if adm is None:
            return []
        out = []
        by_name = {psr.name: psr for psr in self.total_requests}
        for psa in adm.pod_set_assignments:
            ta = psa.topology_assignment
            if ta is None:
                continue
            flavor = next((f for f in psa.flavors.values()
                           if f in tas_flavor_names), None)
            if flavor is None:
                continue
            psr = by_name.get(psa.name)
            single = psr.single_pod_requests() if psr else {}
            for dom in ta.domains:
                out.append((flavor, tuple(dom.values), single, dom.count))
        return out

    def uses_any(self, frs: set[FlavorResource]) -> bool:
        """Reference: classical.WorkloadUsesResources
        (candidate_generator.go:54)."""
        for psr in self.total_requests:
            for res, flavor in psr.flavors.items():
                if FlavorResource(flavor, res) in frs:
                    return True
        return False


def admission_from_assignment(cluster_queue: str, pod_sets) -> Admission:
    """Build an api Admission from scheduler PodSetAssignments."""
    return Admission(
        cluster_queue=cluster_queue,
        pod_set_assignments=tuple(
            PodSetAssignmentStatus(
                name=psa.name,
                flavors={res: getattr(fa, "name", fa)
                         for res, fa in psa.flavors.items()},
                resource_usage=dict(psa.requests),
                count=psa.count,
                topology_assignment=psa.topology_assignment,
            )
            for psa in pod_sets
        ),
    )
