"""kueuectl-equivalent CLI for the standalone engine.

Reference: cmd/kueuectl (app/cmd.go:79): create {cq,lq,rf}, list
{clusterqueues,localqueues,workloads,resourceflavors}, stop/resume
{workload,clusterqueue,localqueue}, delete, version.

The CLI operates on an Engine instance (in-process) or on a state file; an
RPC transport can front the same command surface.
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    StopPolicy,
)
from kueue_tpu.webhooks.validators import (
    validate_cluster_queue,
    validate_resource_flavor,
)

VERSION = "kueue-tpu v0.1 (round 1)"


class Kueuectl:
    def __init__(self, engine):
        self.engine = engine

    def _journal_delete(self, kind: str, key: str) -> None:
        if self.engine.journal is not None:
            self.engine.journal.delete(kind, key, ts=self.engine.clock)

    def _journal_apply(self, kind: str, obj) -> None:
        if self.engine.journal is not None:
            self.engine.journal.apply(kind, obj, ts=self.engine.clock)

    # -- create --

    def create_cluster_queue(self, name: str, cohort: Optional[str] = None,
                             nominal_quota: Optional[dict] = None,
                             borrowing_limit: Optional[dict] = None,
                             lending_limit: Optional[dict] = None,
                             queueing_strategy: str = "BestEffortFIFO"
                             ) -> ClusterQueue:
        """kueuectl create cq."""
        nominal_quota = nominal_quota or {}
        flavors: dict[str, dict[str, ResourceQuota]] = {}
        for key, val in nominal_quota.items():
            flavor, res = key.split(":", 1)
            flavors.setdefault(flavor, {})[res] = ResourceQuota(
                nominal=val,
                borrowing_limit=(borrowing_limit or {}).get(key),
                lending_limit=(lending_limit or {}).get(key))
        covered = tuple(sorted({res for f in flavors.values()
                                for res in f}))
        # Pad every flavor to cover all resources of the group.
        for f in flavors.values():
            for res in covered:
                f.setdefault(res, ResourceQuota(0))
        cq = ClusterQueue(
            name=name, cohort=cohort,
            resource_groups=(ResourceGroup(
                covered,
                tuple(FlavorQuotas(fn, fr)
                      for fn, fr in flavors.items())),) if flavors else (),
        )
        errs = validate_cluster_queue(cq) if flavors else []
        if errs:
            raise ValueError("; ".join(errs))
        self.engine.create_cluster_queue(cq)
        return cq

    def create_local_queue(self, name: str, cluster_queue: str,
                           namespace: str = "default") -> LocalQueue:
        lq = LocalQueue(name, namespace, cluster_queue)
        self.engine.create_local_queue(lq)
        return lq

    def create_resource_flavor(self, name: str,
                               node_labels: Optional[dict] = None
                               ) -> ResourceFlavor:
        rf = ResourceFlavor(name, node_labels=node_labels or {})
        errs = validate_resource_flavor(rf)
        if errs:
            raise ValueError("; ".join(errs))
        self.engine.create_resource_flavor(rf)
        return rf

    # -- list --

    def list_cluster_queues(self) -> list[dict]:
        out = []
        for name, cq in sorted(self.engine.cache.cluster_queues.items()):
            pcq = self.engine.queues.cluster_queues.get(name)
            out.append({
                "name": name,
                "cohort": cq.cohort or "",
                "pending": pcq.pending() if pcq else 0,
                "admitted": self.engine.cache.admitted_count(name),
                "active": cq.stop_policy == StopPolicy.NONE,
            })
        return out

    def list_local_queues(self, namespace: Optional[str] = None
                          ) -> list[dict]:
        out = []
        for key, lq in sorted(self.engine.queues.local_queues.items()):
            if namespace and lq.namespace != namespace:
                continue
            out.append({"name": lq.name, "namespace": lq.namespace,
                        "clusterQueue": lq.cluster_queue})
        return out

    def list_workloads(self, namespace: Optional[str] = None) -> list[dict]:
        out = []
        for key, wl in sorted(self.engine.workloads.items()):
            if namespace and wl.namespace != namespace:
                continue
            status = "Pending"
            if wl.is_finished:
                status = "Finished"
            elif wl.is_admitted:
                status = "Admitted"
            elif wl.has_quota_reservation:
                status = "QuotaReserved"
            elif wl.is_evicted:
                status = "Evicted"
            out.append({
                "name": wl.name, "namespace": wl.namespace,
                "queue": wl.queue_name, "priority": wl.effective_priority,
                "status": status, "active": wl.active,
            })
        return out

    def list_resource_flavors(self) -> list[dict]:
        return [{"name": rf.name, "nodeLabels": dict(rf.node_labels)}
                for rf in sorted(
                    self.engine.cache.resource_flavors.values(),
                    key=lambda r: r.name)]

    # -- stop / resume --

    def stop_workload(self, key: str) -> None:
        wl = self.engine.workloads.get(key)
        if wl is None:
            raise KeyError(key)
        wl.active = False
        if wl.has_quota_reservation:
            self.engine.evict(wl, "WorkloadStopped", requeue=False)
        self.engine.queues.delete_workload(wl)

    def resume_workload(self, key: str) -> None:
        wl = self.engine.workloads.get(key)
        if wl is None:
            raise KeyError(key)
        wl.active = True
        self.engine.queues.add_or_update_workload(wl)

    def stop_cluster_queue(self, name: str,
                           drain: bool = False) -> None:
        cq = self.engine.cache.cluster_queues.get(name)
        if cq is None:
            raise KeyError(name)
        cq.stop_policy = (StopPolicy.HOLD_AND_DRAIN if drain
                          else StopPolicy.HOLD)
        if drain:
            for key, info in list(self.engine.cache.workloads.items()):
                if info.cluster_queue == name:
                    wl = self.engine.workloads.get(key)
                    if wl is not None:
                        self.engine.evict(wl, "ClusterQueueStopped")
        self._journal_apply("cluster_queue", cq)

    def resume_cluster_queue(self, name: str) -> None:
        cq = self.engine.cache.cluster_queues.get(name)
        if cq is None:
            raise KeyError(name)
        cq.stop_policy = StopPolicy.NONE
        self.engine.queues.queue_inadmissible_workloads({name})
        self._journal_apply("cluster_queue", cq)

    def stop_local_queue(self, key: str, drain: bool = False) -> None:
        """kueuectl stop localqueue (stop/stop_localqueue.go). The held
        stop policy keeps the LQ's workloads out of the pending heaps
        (queues.add_or_update_workload gate)."""
        lq = self.engine.queues.local_queues.get(key)
        if lq is None:
            raise KeyError(key)
        lq.stop_policy = (StopPolicy.HOLD_AND_DRAIN if drain
                          else StopPolicy.HOLD)
        for wkey, wl in list(self.engine.workloads.items()):
            if f"{wl.namespace}/{wl.queue_name}" != key or wl.is_finished:
                continue
            if drain and wl.has_quota_reservation:
                self.engine.evict(wl, "LocalQueueStopped")
            elif not wl.has_quota_reservation:
                # Hold: pending workloads leave the queue until resume.
                self.engine.queues.delete_workload(wl)
        self._journal_apply("local_queue", lq)

    def resume_local_queue(self, key: str) -> None:
        lq = self.engine.queues.local_queues.get(key)
        if lq is None:
            raise KeyError(key)
        lq.stop_policy = StopPolicy.NONE
        # Re-queue the LQ's parked pending workloads (they were gated or
        # removed while stopped).
        for wl in self.engine.workloads.values():
            if f"{wl.namespace}/{wl.queue_name}" == key and wl.active \
                    and not wl.is_finished \
                    and not wl.has_quota_reservation:
                self.engine.queues.add_or_update_workload(wl)
        self.engine.queues.queue_inadmissible_workloads()
        self._journal_apply("local_queue", lq)

    # -- pods (list/list_pods.go: pods of a queued job) --

    def list_pods(self, workload_key: Optional[str] = None,
                  namespace: Optional[str] = None) -> list[dict]:
        """Pod-level rows derived from admissions: one row per admitted
        pod (pod set x count) with its flavor-derived node selector."""
        rows = []
        flavors = self.engine.cache.resource_flavors
        for key, wl in sorted(self.engine.workloads.items()):
            if workload_key and key != workload_key:
                continue
            if namespace and wl.namespace != namespace:
                continue
            if wl.status.admission is None:
                continue
            for psa in wl.status.admission.pod_set_assignments:
                selector = {}
                for fname in psa.flavors.values():
                    rf = flavors.get(fname)
                    if rf is not None:
                        selector.update(rf.node_labels)
                for i in range(psa.count):
                    rows.append({
                        "name": f"{wl.name}-{psa.name}-{i}",
                        "namespace": wl.namespace,
                        "workload": key,
                        "podSet": psa.name,
                        "nodeSelector": selector,
                        "phase": ("Running" if wl.is_admitted
                                  else "Pending"),
                    })
        return rows

    # -- describe (passthrough describe analog) --

    def describe_workload(self, key: str) -> dict:
        wl = self.engine.workloads.get(key)
        if wl is None:
            raise KeyError(key)
        from kueue_tpu.workload_info import WorkloadInfo

        info = WorkloadInfo.from_workload(
            wl, wl.status.admission.cluster_queue
            if wl.status.admission else "",
            options=self.engine.info_options)
        return {
            "name": wl.name, "namespace": wl.namespace,
            "queue": wl.queue_name, "priority": wl.effective_priority,
            "active": wl.active,
            "conditions": [
                {"type": c.type, "status": c.status, "reason": c.reason,
                 "message": c.message}
                for c in wl.status.conditions.values()],
            "admission": None if wl.status.admission is None else {
                "clusterQueue": wl.status.admission.cluster_queue,
                "podSetAssignments": [
                    {"name": psa.name, "count": psa.count,
                     "flavors": dict(psa.flavors)}
                    for psa in wl.status.admission.pod_set_assignments],
            },
            "requeueCount": wl.status.requeue_count,
            "usage": {f"{fr.flavor}/{fr.resource}": q
                      for fr, q in info.usage().items()},
            "admissionChecks": dict(wl.status.admission_check_states),
        }

    def describe_cluster_queue(self, name: str) -> dict:
        from kueue_tpu.controllers.status import StatusController

        cq = self.engine.cache.cluster_queues.get(name)
        if cq is None:
            raise KeyError(name)
        sc = self.engine.status_controller or StatusController(
            self.engine, attach=False)
        st = sc.cq_status(name)
        return {
            "name": name, "cohort": cq.cohort or "",
            "queueingStrategy": cq.queueing_strategy.value,
            "flavors": [
                {"name": fq.name,
                 "quotas": {res: {"nominal": q.nominal,
                                  "borrowingLimit": q.borrowing_limit,
                                  "lendingLimit": q.lending_limit}
                            for res, q in fq.resources.items()}}
                for rg in cq.resource_groups for fq in rg.flavors],
            "status": vars(st) if st is not None else None,
        }

    def describe_local_queue(self, key: str) -> dict:
        from kueue_tpu.controllers.status import StatusController

        lq = self.engine.queues.local_queues.get(key)
        if lq is None:
            raise KeyError(key)
        sc = self.engine.status_controller or StatusController(
            self.engine, attach=False)
        st = sc.lq_status(key)
        return {"name": lq.name, "namespace": lq.namespace,
                "clusterQueue": lq.cluster_queue,
                "status": vars(st) if st is not None else None}

    # -- delete --

    def delete_workload(self, key: str) -> None:
        wl = self.engine.workloads.pop(key, None)
        if wl is not None:
            self.engine.cache.delete_workload(key)
            self.engine.queues.delete_workload(wl)
            self._journal_delete("workload", key)

    def delete_cluster_queue(self, name: str) -> None:
        """delete/delete_clusterqueue.go: the queue (and its pending
        heap) go away; workload objects stay registered, unqueued."""
        self.engine.cache.cluster_queues.pop(name, None)
        self.engine.queues.cluster_queues.pop(name, None)
        self._journal_delete("cluster_queue", name)

    def delete_local_queue(self, key: str) -> None:
        self.engine.queues.delete_local_queue(key)
        self._journal_delete("local_queue", key)

    def delete_resource_flavor(self, name: str) -> None:
        self.engine.cache.resource_flavors.pop(name, None)
        self._journal_delete("resource_flavor", name)

    # -- passthrough (app/passthrough: get on any kueue kind) --

    def get(self, kind: str, name: Optional[str] = None,
            namespace: Optional[str] = None):
        table = {
            "clusterqueues": self.list_cluster_queues,
            "localqueues": lambda: self.list_local_queues(namespace),
            "workloads": lambda: self.list_workloads(namespace),
            "resourceflavors": self.list_resource_flavors,
            "pods": lambda: self.list_pods(namespace=namespace),
        }
        if kind not in table:
            raise KeyError(f"unknown kind {kind}")
        rows = table[kind]()
        if name is not None:
            rows = [r for r in rows if r.get("name") == name]
        return rows

    # -- explain (obs/: why is my workload pending?) --

    def explain(self, key: str, probe: bool = True) -> dict:
        from kueue_tpu.obs import explain_workload
        return explain_workload(self.engine, key, probe=probe)

    def version(self) -> str:
        return VERSION


def _endpoint_url(endpoint: str, path: str) -> str:
    """Accept both host:port and full http://host:port endpoints."""
    base = endpoint.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    return base + path


def _parse_quota_pairs(pairs: list[str]) -> dict:
    """--nominal-quota flavor:resource=value [...]"""
    out = {}
    for pair in pairs or []:
        key, val = pair.split("=", 1)
        out[key] = int(val)
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kueuectl")
    sub = p.add_subparsers(dest="command")
    sub.add_parser("version")

    lst = sub.add_parser("list")
    lst.add_argument("kind", choices=["clusterqueues", "localqueues",
                                      "workloads", "resourceflavors",
                                      "pods"])
    lst.add_argument("--namespace")
    lst.add_argument("--for", dest="for_workload",
                     help="workload key for pods listing")

    get = sub.add_parser("get")  # passthrough
    get.add_argument("kind")
    get.add_argument("name", nargs="?")
    get.add_argument("--namespace")

    desc = sub.add_parser("describe")
    desc.add_argument("kind", choices=["workload", "clusterqueue",
                                       "localqueue"])
    desc.add_argument("name")
    desc.add_argument("--namespace", default="default")

    create = sub.add_parser("create")
    create.add_argument("kind", choices=["clusterqueue", "localqueue",
                                         "resourceflavor"])
    create.add_argument("name")
    create.add_argument("--cohort")
    create.add_argument("--clusterqueue")
    create.add_argument("--namespace", default="default")
    create.add_argument("--nominal-quota", nargs="*", default=[],
                        help="flavor:resource=value pairs")
    create.add_argument("--queueing-strategy", default="BestEffortFIFO")
    create.add_argument("--node-label", nargs="*", default=[],
                        help="key=value pairs")
    create.add_argument("--dry-run", choices=["none", "client"],
                        default="none")

    for verb in ("stop", "resume"):
        cmd = sub.add_parser(verb)
        cmd.add_argument("kind", choices=["workload", "clusterqueue",
                                          "localqueue"])
        cmd.add_argument("name")
        cmd.add_argument("--namespace", default="default")
        if verb == "stop":
            cmd.add_argument("--drain", action="store_true")

    dele = sub.add_parser("delete")
    dele.add_argument("kind", choices=["workload", "clusterqueue",
                                       "localqueue", "resourceflavor"])
    dele.add_argument("name")
    dele.add_argument("--namespace", default="default")
    dele.add_argument("--dry-run", choices=["none", "client"],
                      default="none")

    rec = sub.add_parser(
        "record",
        help="flight-record the engine: bootstrap the current "
             "(journal-rebuilt) world into a trace, then run scheduling "
             "cycles until quiescent (or --cycles)")
    rec.add_argument("out", help="trace path to write")
    rec.add_argument("--cycles", type=int, default=0,
                     help="cycle budget (0 = run until quiescent)")
    rec.add_argument("--label", default="")

    rep = sub.add_parser(
        "replay",
        help="deterministically re-execute a flight-recorder trace and "
             "verify the decision stream (exit non-zero on divergence)")
    rep.add_argument("trace")
    rep.add_argument("--mode", choices=["host", "device", "both"],
                     default="host",
                     help="host = sequential core; device = oracle "
                          "attached; both = differential host-vs-device")
    rep.add_argument("--faults",
                     help="fault spec armed on the replay engine, e.g. "
                          "oracle-crash@cycle:2 (see replay/faults.py)")
    rep.add_argument("--stop-after", type=int, dest="stop_after",
                     help="replay only the first N cycles")

    exp = sub.add_parser(
        "explain",
        help="why is my workload pending: last traced decision "
             "(per-flavor rejection reasons, preemption rationale, "
             "correlation id) plus a live what-if probe")
    exp.add_argument("name")
    exp.add_argument("--namespace", default="default")
    exp.add_argument("--json", action="store_true", dest="as_json",
                     help="emit the structured report instead of text")
    exp.add_argument("--no-probe", action="store_true",
                     help="skip the live one-shot nomination probe")

    # Parsed in main() before engine construction; registered here so
    # `kueuectl --help` lists it.
    lint = sub.add_parser(
        "lint",
        help="run the graftlint static analyzer (tools/graftlint) over "
             "the package; extra args pass through (--explain RULE, "
             "--json FILE, --sarif FILE, --rule F1,S1, --sanitize, "
             "paths)")
    lint.add_argument("lint_args", nargs=argparse.REMAINDER)

    # Parsed in main() before engine construction, like lint: the
    # simulator builds its own engines from the world seeds.
    sim = sub.add_parser(
        "sim",
        help="time-compressed world simulator (kueue_tpu/sim): "
             "regenerate a world from its seed triple, replay it on "
             "the virtual clock, check invariants, shrink failures")
    sims = sim.add_subparsers(dest="sim_command")
    srun = sims.add_parser(
        "run",
        help="replay one world; exit 3 when --check finds an "
             "invariant violation")
    srun.add_argument("--world-seed", type=int, default=0)
    srun.add_argument("--traffic-seed", type=int, default=0)
    srun.add_argument("--fault-seed", type=int, default=0)
    srun.add_argument("--horizon", type=float, default=None,
                      help="virtual horizon seconds (default: drawn "
                           "from the world seed)")
    srun.add_argument("--cycle", type=float, default=None,
                      help="scheduling cadence in virtual seconds")
    srun.add_argument("--device", action="store_true",
                      help="include the host-vs-device differential "
                           "(needs JAX)")
    srun.add_argument("--check", action="store_true",
                      help="run the invariant oracle instead of a "
                           "bare replay")
    srun.add_argument("--repro",
                      help="reproducer JSON written by the shrinker; "
                           "overrides the seed/dim flags")
    srun.add_argument("--json", action="store_true", dest="as_json",
                      help="emit the structured result")
    sshr = sims.add_parser(
        "shrink",
        help="shrink a failing triple to a minimal reproducer")
    sshr.add_argument("--world-seed", type=int, required=True)
    sshr.add_argument("--traffic-seed", type=int, default=0)
    sshr.add_argument("--fault-seed", type=int, default=0)
    sshr.add_argument("--out", help="write the reproducer JSON here")

    slo = sub.add_parser(
        "slo",
        help="serving objectives: declared targets, multi-window burn "
             "rates and ok/warn/breach status (obs/slo.py)")
    slo.add_argument("--json", action="store_true", dest="as_json",
                     help="emit the structured summary instead of text")

    st = sub.add_parser(
        "status",
        help="HA replica status: role, lease epoch, journal replay "
             "lag, connected SSE clients (kueue_tpu/ha). Query a live "
             "replica with --endpoint, or inspect the lease/journal "
             "offline with --journal/--lease")
    st.add_argument("--endpoint",
                    help="base URL of a live replica "
                         "(e.g. http://127.0.0.1:8080): queries "
                         "/debug/ha")
    st.add_argument("--lease",
                    help="lease file for offline inspection "
                         "(default: <journal>.lease)")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw status dict")

    cl = sub.add_parser(
        "cells",
        help="federation cell status: per-cell health, breaker state, "
             "fence epoch and route-state counts (kueue_tpu/federation)."
             " Query a live dispatcher with --endpoint, or fold a "
             "dispatcher route journal offline with --journal")
    cl.add_argument("--endpoint",
                    help="base URL of a live federation dispatcher "
                         "(e.g. http://127.0.0.1:8080): queries /cells")
    cl.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw status dict")

    tr = sub.add_parser(
        "trace", help="span-tree operations (obs/)")
    trs = tr.add_subparsers(dest="trace_command")
    texp = trs.add_parser(
        "export",
        help="export span trees as Chrome/Perfetto trace-event JSON "
             "(open in ui.perfetto.dev or chrome://tracing)")
    texp.add_argument("--out", required=True, help="output JSON path")
    texp.add_argument("--input",
                      help="flight-recorder trace (.jsonl) to convert "
                           "offline; default: the live engine's "
                           "retained spans")
    texp.add_argument("--last", type=int, default=0,
                      help="export only the newest N cycle span trees")
    return p


def _sim_main(argv: list) -> int:
    """`kueuectl sim ...`: replay/check/shrink generated worlds.
    Exit codes: 0 clean, 2 usage, 3 invariant violation (or, with
    --repro, the reproducer's failure still reproducing)."""
    import json as _json

    args = build_parser().parse_args(argv)
    if args.sim_command == "run":
        from kueue_tpu.sim.oracle import check_world
        from kueue_tpu.sim.shrink import Reproducer, reproduce

        if args.repro:
            rep = Reproducer.load(args.repro)
            still = reproduce(rep)
            out = {"reproducer": rep.to_dict(), "reproduces": still}
            print(_json.dumps(out, indent=2, sort_keys=True)
                  if args.as_json else
                  f"{rep.command}\n  invariant {rep.invariant}: "
                  + ("STILL FAILING" if still else "no longer fails"))
            return 3 if still else 0
        horizon = args.horizon if args.horizon is not None else 240.0
        cycle = args.cycle if args.cycle is not None else 2.0
        if args.check:
            report = check_world(args.world_seed, args.traffic_seed,
                                 args.fault_seed, device=args.device,
                                 horizon_s=horizon, cycle_s=cycle)
            d = report.to_dict()
            if args.as_json:
                print(_json.dumps(d, indent=2, sort_keys=True))
            else:
                verdict = ("OK" if d["ok"]
                           else "FAIL " + ",".join(d["failed"]))
                print(f"world={args.world_seed} "
                      f"traffic={args.traffic_seed} "
                      f"fault={args.fault_seed}: {verdict}")
                for name, r in d["results"].items():
                    print(f"  {name}: "
                          f"{'ok' if r.get('ok') else 'VIOLATED'}")
            return 0 if d["ok"] else 3
        from kueue_tpu.sim.harness import run_sim
        from kueue_tpu.sim.worlds import generate_world

        spec = generate_world(args.world_seed, horizon_s=horizon,
                              cycle_s=cycle)
        res = run_sim(spec, args.traffic_seed, args.fault_seed,
                      device=args.device)
        d = res.to_dict()
        d.pop("admittedSet", None)
        print(_json.dumps(d, indent=2, sort_keys=True) if args.as_json
              else f"world={args.world_seed} cycles={res.cycles} "
                   f"offered={res.offered} admitted={res.admitted} "
                   f"digest={res.decision_digest:08x} "
                   f"virtual={res.virtual_s:.0f}s "
                   f"wall={res.wall_s:.2f}s "
                   f"({res.virtual_s / max(res.wall_s, 1e-9):.0f}x)")
        return 0
    if args.sim_command == "shrink":
        from kueue_tpu.sim.shrink import shrink_failure

        rep = shrink_failure(args.world_seed, args.traffic_seed,
                             args.fault_seed)
        if rep is None:
            print("triple does not fail any invariant; nothing to "
                  "shrink")
            return 1
        if args.out:
            rep.write(args.out)
        print(_json.dumps(rep.to_dict(), indent=2, sort_keys=True))
        return 0
    build_parser().parse_args(["sim", "--help"])
    return 2


def main(argv=None) -> None:
    """Console entry point: operate on a journal-backed engine
    (--journal points at the durable store; commands replay it, apply,
    and mutations are journaled back)."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "lint":
        # Passthrough to the static analyzer — no engine/journal needed.
        # graftlint ships in the repo's tools/ tree, not the installed
        # package, so degrade gracefully outside a checkout.
        try:
            from tools.graftlint.cli import main as lint_main
            from tools.graftlint.config import Config
        except ImportError:
            raise SystemExit(
                "kueuectl lint requires the repository checkout "
                "(tools/graftlint is not part of the installed package)")
        import os

        rest = argv[1:]
        # Decide whether the user gave explicit paths. Flags that take
        # a value consume the next token, so `--rule F1,S1` does not
        # read as a path; `--flag=value` forms carry their own value.
        value_flags = {"--json", "--sarif", "--rule", "--baseline",
                       "--explain", "--metrics", "--trace-json",
                       "--root", "--write-baseline"}
        has_paths = False
        skip = False
        for a in rest:
            if skip:
                skip = False
                continue
            if a.startswith("-"):
                if a in value_flags:
                    skip = True
                continue
            has_paths = True
            break
        if not has_paths:
            rest = [os.path.join(Config().root, "kueue_tpu"),
                    "--self-check"] + rest
        raise SystemExit(lint_main(rest))
    if argv and argv[0] == "sim":
        # Pre-engine like lint: the simulator regenerates worlds from
        # seeds and builds its own engines on the virtual clock.
        raise SystemExit(_sim_main(argv))
    journal = None
    if "--journal" in argv:
        i = argv.index("--journal")
        if i + 1 >= len(argv):
            raise SystemExit("--journal requires a path argument")
        journal = argv[i + 1]
        del argv[i:i + 2]
    if journal:
        from kueue_tpu.store.journal import rebuild_engine

        engine = rebuild_engine(journal)
    else:
        from kueue_tpu.controllers.engine import Engine

        engine = Engine()
    print(run(engine, argv))


def run(engine, argv: list[str]) -> str:
    """Entry point: returns rendered output."""
    ctl = Kueuectl(engine)
    args = build_parser().parse_args(argv)
    if args.command == "version":
        return ctl.version()
    if args.command == "list":
        fn = {
            "clusterqueues": ctl.list_cluster_queues,
            "localqueues": lambda: ctl.list_local_queues(args.namespace),
            "workloads": lambda: ctl.list_workloads(args.namespace),
            "resourceflavors": ctl.list_resource_flavors,
            "pods": lambda: ctl.list_pods(args.for_workload,
                                          args.namespace),
        }[args.kind]
        return json.dumps(fn(), indent=2)
    if args.command == "get":
        return json.dumps(ctl.get(args.kind, args.name, args.namespace),
                          indent=2)
    if args.command == "describe":
        key = f"{args.namespace}/{args.name}"
        fn = {
            "workload": lambda: ctl.describe_workload(key),
            "clusterqueue": lambda: ctl.describe_cluster_queue(args.name),
            "localqueue": lambda: ctl.describe_local_queue(key),
        }[args.kind]
        return json.dumps(fn(), indent=2)
    if args.command == "create":
        if args.dry_run != "none":
            return f"{args.kind}/{args.name} created (dry run)"
        if args.kind == "clusterqueue":
            ctl.create_cluster_queue(
                args.name, cohort=args.cohort,
                nominal_quota=_parse_quota_pairs(args.nominal_quota),
                queueing_strategy=args.queueing_strategy)
        elif args.kind == "localqueue":
            if not args.clusterqueue:
                raise SystemExit("--clusterqueue is required")
            ctl.create_local_queue(args.name, args.clusterqueue,
                                   namespace=args.namespace)
        else:
            labels = dict(pair.split("=", 1)
                          for pair in args.node_label)
            ctl.create_resource_flavor(args.name, node_labels=labels)
        return f"{args.kind}/{args.name} created"
    if args.command in ("stop", "resume"):
        key = f"{args.namespace}/{args.name}"
        table = {
            ("stop", "workload"): lambda: ctl.stop_workload(key),
            ("stop", "clusterqueue"): lambda: ctl.stop_cluster_queue(
                args.name, drain=args.drain),
            ("stop", "localqueue"): lambda: ctl.stop_local_queue(
                key, drain=args.drain),
            ("resume", "workload"): lambda: ctl.resume_workload(key),
            ("resume", "clusterqueue"): lambda: ctl.resume_cluster_queue(
                args.name),
            ("resume", "localqueue"): lambda: ctl.resume_local_queue(key),
        }
        table[(args.command, args.kind)]()
        return f"{args.kind}/{args.name} {args.command}ped" \
            if args.command == "stop" else f"{args.kind}/{args.name} resumed"
    if args.command == "record":
        from kueue_tpu.replay.recorder import FlightRecorder
        recorder = FlightRecorder(engine, args.out, bootstrap=True,
                                  label=args.label)
        ran = 0
        try:
            while True:
                result = engine.schedule_once()
                ran += 1
                if args.cycles and ran >= args.cycles:
                    break
                if not args.cycles and result is None:
                    break
        finally:
            recorder.close()
        return (f"recorded {ran} cycle(s) -> {args.out} "
                f"(digest {recorder.digest})")
    if args.command == "replay":
        from kueue_tpu.replay.replayer import replay_trace
        report = replay_trace(args.trace, mode=args.mode,
                              faults=args.faults,
                              stop_after_cycles=args.stop_after)
        if not report.ok:
            raise SystemExit(report.render())
        return report.render()
    if args.command == "explain":
        from kueue_tpu.obs import explain_workload, render_explain
        report = explain_workload(engine, f"{args.namespace}/{args.name}",
                                  probe=not args.no_probe)
        if args.as_json:
            return json.dumps(report, indent=2, default=str)
        return render_explain(report)
    if args.command == "slo":
        slo = getattr(engine, "slo", None)
        if slo is None:
            # Declarative objectives exist without an engine loop — show
            # the declared targets with empty windows rather than
            # refusing (a journal-rebuilt engine has no live history).
            from kueue_tpu.obs.slo import attach_slo
            slo = attach_slo(engine)
        summary = slo.summary()
        if args.as_json:
            return json.dumps(summary, indent=2)
        lines = [f"cycles observed: {summary['cyclesObserved']}",
                 "windows: " + ", ".join(
                     f"{w}={n} cycles"
                     for w, n in summary["windows"].items())]
        header = (f"{'OBJECTIVE':<24} {'KIND':<16} {'TARGET':>10} "
                  f"{'BURN(fast)':>11} {'BURN(slow)':>11} STATUS")
        lines.append(header)
        for name, ev in summary["objectives"].items():
            burns = ev["burn"]
            lines.append(
                f"{name:<24} {ev['kind']:<16} {ev['target']:>10.3g} "
                f"{burns.get('fast', 0.0):>11.3f} "
                f"{burns.get('slow', 0.0):>11.3f} {ev['statusName']}")
        return "\n".join(lines)
    if args.command == "status":
        if args.endpoint:
            # Live replica: /debug/ha is the authoritative view.
            import urllib.request
            url = _endpoint_url(args.endpoint, "/debug/ha")
            with urllib.request.urlopen(url, timeout=5) as resp:
                status = json.loads(resp.read())
            # Read replicas answer /debug/readplane with their
            # staleness envelope; HA replicas/leaders answer
            # {"enabled": false}. Either way the fetch is additive —
            # a pre-readplane server 404s and we show nothing.
            try:
                rp_url = _endpoint_url(args.endpoint, "/debug/readplane")
                with urllib.request.urlopen(rp_url, timeout=5) as resp:
                    rp = json.loads(resp.read())
                if rp.get("enabled"):
                    status["readplane"] = rp
            except (OSError, ValueError):
                pass
        elif getattr(engine, "ha", None) is not None:
            status = engine.ha.status()
        else:
            # Offline: read the lease file and the journal's last HA
            # checkpoint directly (no replica process required).
            status = {"role": "offline", "identity": ""}
            journal = getattr(engine, "journal", None)
            lease_path = args.lease or (
                journal.path + ".lease" if journal is not None else None)
            if lease_path:
                from kueue_tpu.ha.lease import FencedLease
                lease = FencedLease(lease_path).read()
                status["leaseHolder"] = lease.holder if lease else ""
                status["epoch"] = lease.epoch if lease else 0
            if journal is not None:
                from kueue_tpu.ha.digest import last_checkpoint
                records = list(journal.replay())
                _, ckpt = last_checkpoint(records)
                status["journalRecords"] = len(records)
                status["lastCheckpoint"] = (ckpt["obj"] if ckpt
                                            else None)
        if args.as_json:
            return json.dumps(status, indent=2)
        lines = [f"role: {status.get('role', 'unknown')}"]
        if status.get("identity"):
            lines.append(f"identity: {status['identity']}")
        lines.append(
            f"lease: holder={status.get('leaseHolder', '')!r} "
            f"epoch={status.get('epoch', 0)}")
        if "replayLag" in status:
            lines.append(f"replay lag: {status['replayLag']} record(s)")
        if "journalRecords" in status:
            lines.append(
                f"journal: {status['journalRecords']} record(s)")
        ckpt = status.get("lastCheckpoint") or (
            status.get("tailer") or {}).get("lastCheckpoint")
        if ckpt:
            lines.append(
                f"checkpoint: seq={ckpt.get('seq')} "
                f"epoch={ckpt.get('epoch')} chain={ckpt.get('chain')} "
                f"state={ckpt.get('state')}")
        if "sseClients" in status:
            sse = status.get("sse", {})
            lines.append(
                f"sse clients: {status['sseClients']} connected "
                f"({sse.get('dropped', 0)} dropped, "
                f"{sse.get('evicted', 0)} evicted)")
        if "decisionDigest" in status:
            lines.append(
                f"decision digest: {status['decisionDigest']} "
                f"@ seq {status.get('digestSeq')}")
        if status.get("shedder"):
            sh = status["shedder"]
            lines.append(
                f"shedder: accepted={sh['accepted']} shed={sh['shed']} "
                f"factor={sh['factor']}")
        rp = status.get("readplane")
        if rp:
            lines.append(f"read replica: {rp.get('replica', '?')} "
                         f"(journal={rp.get('journal', '?')}, "
                         f"queries={rp.get('queries', 0)})")
            st = rp.get("staleness")
            if st:
                pos = st.get("position") or {}
                lines.append(
                    f"  rebuilt @ lineage {pos.get('lineage', '?')} "
                    f"seg {pos.get('segment', '?')} "
                    f"offset {pos.get('offset', '?')} "
                    f"cid={st.get('cid') or '-'}")
                age = st.get("wallAgeSeconds")
                lines.append(
                    f"  staleness: lag={st.get('lagRecords', '?')} "
                    f"record(s), age="
                    + (f"{age:.3f}s" if age is not None else "?"))
            else:
                lines.append("  staleness: no rebuild yet")
            slo = rp.get("readSlo") or {}
            worst = None
            for name, ev in (slo.get("objectives") or {}).items():
                if worst is None or ev["status"] > worst[1]["status"]:
                    worst = (name, ev)
            if worst is not None:
                lines.append(
                    f"  read SLO worst: {worst[0]} "
                    f"{worst[1]['statusName']}")
        return "\n".join(lines)
    if args.command == "cells":
        if args.endpoint:
            # Live dispatcher: /cells is the authoritative view.
            import urllib.request
            url = _endpoint_url(args.endpoint, "/cells")
            with urllib.request.urlopen(url, timeout=5) as resp:
                status = json.loads(resp.read())
        else:
            # Offline: fold the dispatcher's route journal directly.
            # fed_route/fed_cell are EPHEMERAL_KINDS, so the engine
            # rebuild skipped them — replay the raw record stream.
            journal = getattr(engine, "journal", None)
            if journal is None:
                raise SystemExit(
                    "kueuectl cells needs --endpoint or --journal "
                    "pointed at a dispatcher route journal")
            routes: dict = {}
            epochs: dict = {}
            for rec in journal.replay():
                obj = rec.get("obj", {})
                if rec["kind"] == "fed_route":
                    if rec["op"] == "delete":
                        routes.pop(rec["key"], None)
                    else:
                        routes[obj["name"]] = obj
                elif rec["kind"] == "fed_cell" and rec["op"] != "delete":
                    epochs[obj["name"]] = obj
            per_cell: dict = {}
            route_counts: dict = {}
            for r in routes.values():
                d = per_cell.setdefault(r["cell"], {})
                d[r["state"]] = d.get(r["state"], 0) + 1
                route_counts[r["state"]] = (
                    route_counts.get(r["state"], 0) + 1)
            status = {
                "offline": True, "routes": route_counts,
                "cells": [dict(name=n, epoch=st.get("epoch", 1),
                               up=st.get("up"),
                               routes=per_cell.get(n, {}))
                          for n, st in sorted(epochs.items())]}
            for name in sorted(set(per_cell) - set(epochs)):
                status["cells"].append(
                    dict(name=name, epoch=1, up=None,
                         routes=per_cell[name]))
        if args.as_json:
            return json.dumps(status, indent=2)
        lines = []
        rc = status.get("routes", {})
        lines.append(
            "routes: " + (", ".join(
                f"{s}={rc[s]}" for s in sorted(rc)) or "none"))
        if "handoffs" in status:
            lines.append(
                f"handoffs: {status['handoffs']} "
                f"redispatches: {status.get('redispatches', 0)} "
                f"revocations: {status.get('revocations', 0)}")
        header = (f"{'CELL':<16} {'UP':<6} {'EPOCH':>6} "
                  f"{'BREAKER':<10} ROUTES")
        lines.append(header)
        for c in status.get("cells", []):
            up = {True: "yes", False: "no"}.get(c.get("up"), "?")
            breaker = (c.get("breaker") or {}).get("state", "-")
            rts = ", ".join(f"{s}={n}" for s, n in
                            sorted((c.get("routes") or {}).items()))
            lines.append(f"{c['name']:<16} {up:<6} "
                         f"{c.get('epoch', 1):>6} {breaker:<10} "
                         f"{rts or '-'}")
        return "\n".join(lines)
    if args.command == "trace":
        if args.trace_command != "export":
            raise SystemExit("usage: kueuectl trace export --out FILE")
        from kueue_tpu.obs import spans_from_flight_trace, write_perfetto
        if args.input:
            roots = spans_from_flight_trace(args.input)
        else:
            tracer = getattr(engine, "tracer", None)
            if tracer is None:
                raise SystemExit(
                    "no tracer attached to this engine and no --input "
                    "flight trace given (serve with --trace, or pass "
                    "--input RECORDING.jsonl)")
            roots = list(tracer.spans)
        if args.last:
            roots = roots[-args.last:]
        n = write_perfetto(roots, args.out)
        return (f"exported {n} trace event(s) from {len(roots)} "
                f"cycle span tree(s) -> {args.out}")
    if args.command == "delete":
        if args.dry_run != "none":
            return f"{args.kind}/{args.name} deleted (dry run)"
        key = f"{args.namespace}/{args.name}"
        {
            "workload": lambda: ctl.delete_workload(key),
            "clusterqueue": lambda: ctl.delete_cluster_queue(args.name),
            "localqueue": lambda: ctl.delete_local_queue(key),
            "resourceflavor": lambda: ctl.delete_resource_flavor(
                args.name),
        }[args.kind]()
        return f"{args.kind}/{args.name} deleted"
    return ""


if __name__ == "__main__":
    main()
