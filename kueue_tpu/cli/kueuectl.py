"""kueuectl-equivalent CLI for the standalone engine.

Reference: cmd/kueuectl (app/cmd.go:79): create {cq,lq,rf}, list
{clusterqueues,localqueues,workloads,resourceflavors}, stop/resume
{workload,clusterqueue,localqueue}, delete, version.

The CLI operates on an Engine instance (in-process) or on a state file; an
RPC transport can front the same command surface.
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    StopPolicy,
)
from kueue_tpu.webhooks.validators import (
    validate_cluster_queue,
    validate_resource_flavor,
)

VERSION = "kueue-tpu v0.1 (round 1)"


class Kueuectl:
    def __init__(self, engine):
        self.engine = engine

    # -- create --

    def create_cluster_queue(self, name: str, cohort: Optional[str] = None,
                             nominal_quota: Optional[dict] = None,
                             borrowing_limit: Optional[dict] = None,
                             lending_limit: Optional[dict] = None,
                             queueing_strategy: str = "BestEffortFIFO"
                             ) -> ClusterQueue:
        """kueuectl create cq."""
        nominal_quota = nominal_quota or {}
        flavors: dict[str, dict[str, ResourceQuota]] = {}
        for key, val in nominal_quota.items():
            flavor, res = key.split(":", 1)
            flavors.setdefault(flavor, {})[res] = ResourceQuota(
                nominal=val,
                borrowing_limit=(borrowing_limit or {}).get(key),
                lending_limit=(lending_limit or {}).get(key))
        covered = tuple(sorted({res for f in flavors.values()
                                for res in f}))
        # Pad every flavor to cover all resources of the group.
        for f in flavors.values():
            for res in covered:
                f.setdefault(res, ResourceQuota(0))
        cq = ClusterQueue(
            name=name, cohort=cohort,
            resource_groups=(ResourceGroup(
                covered,
                tuple(FlavorQuotas(fn, fr)
                      for fn, fr in flavors.items())),) if flavors else (),
        )
        errs = validate_cluster_queue(cq) if flavors else []
        if errs:
            raise ValueError("; ".join(errs))
        self.engine.create_cluster_queue(cq)
        return cq

    def create_local_queue(self, name: str, cluster_queue: str,
                           namespace: str = "default") -> LocalQueue:
        lq = LocalQueue(name, namespace, cluster_queue)
        self.engine.create_local_queue(lq)
        return lq

    def create_resource_flavor(self, name: str,
                               node_labels: Optional[dict] = None
                               ) -> ResourceFlavor:
        rf = ResourceFlavor(name, node_labels=node_labels or {})
        errs = validate_resource_flavor(rf)
        if errs:
            raise ValueError("; ".join(errs))
        self.engine.create_resource_flavor(rf)
        return rf

    # -- list --

    def list_cluster_queues(self) -> list[dict]:
        out = []
        for name, cq in sorted(self.engine.cache.cluster_queues.items()):
            pcq = self.engine.queues.cluster_queues.get(name)
            out.append({
                "name": name,
                "cohort": cq.cohort or "",
                "pending": pcq.pending() if pcq else 0,
                "admitted": self.engine.cache.admitted_count(name),
                "active": cq.stop_policy == StopPolicy.NONE,
            })
        return out

    def list_local_queues(self, namespace: Optional[str] = None
                          ) -> list[dict]:
        out = []
        for key, lq in sorted(self.engine.queues.local_queues.items()):
            if namespace and lq.namespace != namespace:
                continue
            out.append({"name": lq.name, "namespace": lq.namespace,
                        "clusterQueue": lq.cluster_queue})
        return out

    def list_workloads(self, namespace: Optional[str] = None) -> list[dict]:
        out = []
        for key, wl in sorted(self.engine.workloads.items()):
            if namespace and wl.namespace != namespace:
                continue
            status = "Pending"
            if wl.is_finished:
                status = "Finished"
            elif wl.is_admitted:
                status = "Admitted"
            elif wl.has_quota_reservation:
                status = "QuotaReserved"
            elif wl.is_evicted:
                status = "Evicted"
            out.append({
                "name": wl.name, "namespace": wl.namespace,
                "queue": wl.queue_name, "priority": wl.effective_priority,
                "status": status, "active": wl.active,
            })
        return out

    def list_resource_flavors(self) -> list[dict]:
        return [{"name": rf.name, "nodeLabels": dict(rf.node_labels)}
                for rf in sorted(
                    self.engine.cache.resource_flavors.values(),
                    key=lambda r: r.name)]

    # -- stop / resume --

    def stop_workload(self, key: str) -> None:
        wl = self.engine.workloads.get(key)
        if wl is None:
            raise KeyError(key)
        wl.active = False
        if wl.has_quota_reservation:
            self.engine.evict(wl, "WorkloadStopped", requeue=False)
        self.engine.queues.delete_workload(wl)

    def resume_workload(self, key: str) -> None:
        wl = self.engine.workloads.get(key)
        if wl is None:
            raise KeyError(key)
        wl.active = True
        self.engine.queues.add_or_update_workload(wl)

    def stop_cluster_queue(self, name: str,
                           drain: bool = False) -> None:
        cq = self.engine.cache.cluster_queues.get(name)
        if cq is None:
            raise KeyError(name)
        cq.stop_policy = (StopPolicy.HOLD_AND_DRAIN if drain
                          else StopPolicy.HOLD)
        if drain:
            for key, info in list(self.engine.cache.workloads.items()):
                if info.cluster_queue == name:
                    wl = self.engine.workloads.get(key)
                    if wl is not None:
                        self.engine.evict(wl, "ClusterQueueStopped")

    def resume_cluster_queue(self, name: str) -> None:
        cq = self.engine.cache.cluster_queues.get(name)
        if cq is None:
            raise KeyError(name)
        cq.stop_policy = StopPolicy.NONE
        self.engine.queues.queue_inadmissible_workloads({name})

    def delete_workload(self, key: str) -> None:
        wl = self.engine.workloads.pop(key, None)
        if wl is not None:
            self.engine.cache.delete_workload(key)
            self.engine.queues.delete_workload(wl)

    def version(self) -> str:
        return VERSION


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kueuectl")
    sub = p.add_subparsers(dest="command")
    sub.add_parser("version")
    lst = sub.add_parser("list")
    lst.add_argument("kind", choices=["clusterqueues", "localqueues",
                                      "workloads", "resourceflavors"])
    lst.add_argument("--namespace")
    return p


def run(engine, argv: list[str]) -> str:
    """Entry point: returns rendered output."""
    ctl = Kueuectl(engine)
    args = build_parser().parse_args(argv)
    if args.command == "version":
        return ctl.version()
    if args.command == "list":
        fn = {
            "clusterqueues": ctl.list_cluster_queues,
            "localqueues": lambda: ctl.list_local_queues(args.namespace),
            "workloads": lambda: ctl.list_workloads(args.namespace),
            "resourceflavors": ctl.list_resource_flavors,
        }[args.kind]
        return json.dumps(fn(), indent=2)
    return ""
