"""UID expectation store for in-flight asynchronous operations.

Reference: pkg/util/expectations/store.go (Store — per-key sets of UIDs we
are waiting to observe a change for through event handlers) and
pkg/scheduler/preemption/expectations/expectations.go (the preemption
instance). The scheduler uses it to avoid re-issuing a preemption for a
target whose eviction was already issued but not yet observed back through
the watch stream (preemption.go:216), and releases the expectation when the
target is admitted again (scheduler.go:882, kueue#11480) or the eviction
apply fails (preemption.go:240).
"""

from __future__ import annotations

import threading


class Store:
    """pkg/util/expectations/store.go:30."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._store: dict[str, set[str]] = {}

    def expect_uids(self, key: str, uids: list[str]) -> None:
        """Record UIDs whose observation we now await for ``key``."""
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                self._store[key] = set(uids)
            else:
                stored.update(uids)

    def observed_uid(self, key: str, uid: str) -> None:
        """An event handler saw the change for ``uid``; clean up the key
        once every expected UID has been observed."""
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                return
            stored.discard(uid)
            if not stored:
                del self._store[key]

    def observed_uids(self, items) -> None:
        """Batched :meth:`observed_uid`: one lock round trip for a whole
        admitted batch (``items`` is an iterable of ``(key, uid)``), and
        a free pass when nothing is expected — the steady serving shape."""
        with self._lock:
            store = self._store
            if not store:
                return
            for key, uid in items:
                stored = store.get(key)
                if stored is None:
                    continue
                stored.discard(uid)
                if not stored:
                    del store[key]

    def satisfied(self, key: str) -> bool:
        """True when nothing is pending for ``key``."""
        with self._lock:
            return key not in self._store

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)
