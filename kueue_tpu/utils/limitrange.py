"""LimitRange summarization, defaulting, and validation.

Reference: pkg/util/limitrange/limitrange.go (Summarize, ValidatePodSpec)
and pkg/workload/resources.go:78 (handlePodLimitRange — defaulting).
All per-namespace LimitRanges are folded into one Summary per limit type:
lowest Max, highest Min, first-seen Default/DefaultRequest.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.utils.podtemplate import (
    PodTemplate,
    merge_keep_first,
    merge_keep_max,
    merge_keep_min,
    pod_requests,
)

LIMIT_TYPE_POD = "Pod"
LIMIT_TYPE_CONTAINER = "Container"


@dataclass
class LimitRangeItem:
    """corev1.LimitRangeItem."""

    type: str = LIMIT_TYPE_CONTAINER
    max: dict[str, int] = field(default_factory=dict)
    min: dict[str, int] = field(default_factory=dict)
    default: dict[str, int] = field(default_factory=dict)  # default limits
    default_request: dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRange:
    """corev1.LimitRange (namespaced)."""

    name: str
    namespace: str = "default"
    limits: tuple[LimitRangeItem, ...] = ()


def summarize(ranges: list[LimitRange]) -> dict[str, LimitRangeItem]:
    """limitrange.go:38 Summarize: per limit type keep the lowest Max,
    highest Min, first-seen Default/DefaultRequest."""
    out: dict[str, LimitRangeItem] = {}
    for lr in ranges:
        for item in lr.limits:
            acc = out.setdefault(item.type, LimitRangeItem(type=item.type))
            acc.max = merge_keep_min(acc.max, item.max)
            acc.min = merge_keep_max(acc.min, item.min)
            acc.default = merge_keep_first(acc.default, item.default)
            acc.default_request = merge_keep_first(
                acc.default_request, item.default_request)
    return out


def apply_defaults(template: PodTemplate,
                   summary: dict[str, LimitRangeItem]) -> None:
    """resources.go:78 handlePodLimitRange: merge the Container-type
    Default into each container's limits and DefaultRequest into its
    requests (keep-first), Pod-type into pod-level resources."""
    citem = summary.get(LIMIT_TYPE_CONTAINER)
    if citem is not None:
        for c in template.init_containers + template.containers:
            c.limits = merge_keep_first(c.limits, citem.default)
            c.requests = merge_keep_first(c.requests, citem.default_request)
    pitem = summary.get(LIMIT_TYPE_POD)
    if pitem is not None and template.pod_requests is not None:
        template.pod_limits = merge_keep_first(
            template.pod_limits or {}, pitem.default)
        template.pod_requests = merge_keep_first(
            template.pod_requests, pitem.default_request)


def validate_template(template: PodTemplate,
                      summary: dict[str, LimitRangeItem]) -> list[str]:
    """limitrange.go:85 ValidatePodSpec: containers against the Container
    bounds (using max(requests, limits) vs Max and min(requests, limits)
    vs Min, as the reference does), the whole pod against the Pod bounds."""
    errs: list[str] = []
    citem = summary.get(LIMIT_TYPE_CONTAINER)
    if citem is not None:
        for c in template.init_containers + template.containers:
            hi = merge_keep_max(c.requests, c.limits)
            lo = merge_keep_min(c.requests, c.limits)
            above = [r for r, q in hi.items()
                     if r in citem.max and q > citem.max[r]]
            below = [r for r, q in citem.min.items()
                     if lo.get(r, 0) < q]
            if above:
                errs.append(f"container {c.name or '?'}: requests above "
                            f"limitRange max for {sorted(above)}")
            if below:
                errs.append(f"container {c.name or '?'}: requests below "
                            f"limitRange min for {sorted(below)}")
    pitem = summary.get(LIMIT_TYPE_POD)
    if pitem is not None:
        total = pod_requests(template)
        above = [r for r, q in total.items()
                 if r in pitem.max and q > pitem.max[r]]
        below = [r for r, q in pitem.min.items() if total.get(r, 0) < q]
        if above:
            errs.append(f"pod: requests above limitRange max "
                        f"for {sorted(above)}")
        if below:
            errs.append(f"pod: requests below limitRange min "
                        f"for {sorted(below)}")
    return errs
