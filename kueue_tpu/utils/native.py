"""ctypes bindings for the native (C++) runtime pieces.

The reference's control-plane runtime is native (Go); this rebuild keeps
the JAX/Pallas device path for the decision math and implements the
host-runtime hot structures in C++ (native/kueue_native.cpp), loaded here
via ctypes with a pure-Python fallback so the framework never hard-depends
on a toolchain at import time.

Currently bound: the indexed pending-queue heap (pkg/util/heap/heap.go;
ordering of pkg/cache/queue/cluster_queue.go's heap less).

Dispatch: `make_indexed_heap()` returns the native heap when the shared
library is present (built on demand with `make -C native`, cached) and
KUEUE_TPU_NATIVE != 0; else the Python implementation.
"""

from __future__ import annotations

import ctypes
import heapq
import os
import subprocess
import threading
import warnings
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
# Installed deployments (pip wheel/container) ship the .so outside the
# source tree and point at it with KUEUE_TPU_NATIVE_LIB. The env var is
# resolved ONCE, here; _SO_PATH_IS_ENV records how, so build decisions
# and dlopen always agree even if os.environ changes later.
_SO_PATH_IS_ENV = "KUEUE_TPU_NATIVE_LIB" in os.environ
_SO_PATH = os.environ.get(
    "KUEUE_TPU_NATIVE_LIB",
    os.path.join(_NATIVE_DIR, "build", "libkueue_native.so"))

_lib = None
_lib_failed = False
_build_thread: Optional[threading.Thread] = None
_build_lock = threading.Lock()


def _run_build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True,
                       timeout=120, check=True)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        global _lib_failed
        _lib_failed = True
        warnings.warn(f"kueue_tpu native build failed ({e!r}); "
                      "using the Python heap fallback")
        return False


def ensure_built(block: bool = True) -> bool:
    """Make sure the native library exists. With block=False, kick off a
    background build (once) and return immediately — callers get the
    Python fallback until the build lands, so the first scheduler touch
    never stalls on a compile."""
    global _build_thread
    if os.path.exists(_SO_PATH):
        return True
    if _SO_PATH_IS_ENV:
        # An explicit library path that doesn't exist: building the
        # source tree would produce a .so we'd never load.
        return False
    if _lib_failed or not os.path.exists(
            os.path.join(_NATIVE_DIR, "Makefile")):
        return False
    if block:
        return _run_build()
    with _build_lock:
        if _build_thread is None:
            _build_thread = threading.Thread(target=_run_build,
                                             daemon=True)
            _build_thread.start()
    return False


def _load_library() -> Optional[ctypes.CDLL]:
    """Load the native library; None if unavailable (a background build
    may still be in flight — later calls pick it up)."""
    global _lib
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    if os.environ.get("KUEUE_TPU_NATIVE", "1") in ("0", "false", ""):
        return None
    if not ensure_built(block=False):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    lib.kq_heap_new.restype = ctypes.c_void_p
    lib.kq_heap_free.argtypes = [ctypes.c_void_p]
    lib.kq_heap_push.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_double, ctypes.c_int64,
                                 ctypes.c_double, ctypes.c_int64]
    lib.kq_heap_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.kq_heap_remove.restype = ctypes.c_int
    lib.kq_heap_peek.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64)]
    lib.kq_heap_peek.restype = ctypes.c_int
    lib.kq_heap_pop.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_int64)]
    lib.kq_heap_pop.restype = ctypes.c_int
    lib.kq_heap_len.argtypes = [ctypes.c_void_p]
    lib.kq_heap_len.restype = ctypes.c_int64
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_library() is not None


class NativeIndexedHeap:
    """Min-heap over (usage, -priority, ts, seq) keyed by int id, with
    O(log n) push-or-update and remove-by-id."""

    def __init__(self):
        self._lib = _load_library()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.kq_heap_new()

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.kq_heap_free(h)
            self._h = None

    def push(self, id_: int, usage: float, neg_priority: int, ts: float,
             seq: int) -> None:
        self._lib.kq_heap_push(self._h, id_, usage, neg_priority, ts, seq)

    def remove(self, id_: int) -> bool:
        return bool(self._lib.kq_heap_remove(self._h, id_))

    def peek(self) -> Optional[int]:
        out = ctypes.c_int64()
        if self._lib.kq_heap_peek(self._h, ctypes.byref(out)):
            return out.value
        return None

    def pop(self) -> Optional[int]:
        out = ctypes.c_int64()
        if self._lib.kq_heap_pop(self._h, ctypes.byref(out)):
            return out.value
        return None

    def __len__(self) -> int:
        return int(self._lib.kq_heap_len(self._h))


class PyIndexedHeap:
    """Pure-Python fallback with identical semantics (lazy deletion)."""

    def __init__(self):
        self._heap: list = []
        self._live: dict[int, tuple] = {}

    def push(self, id_: int, usage: float, neg_priority: int, ts: float,
             seq: int) -> None:
        key = (usage, neg_priority, ts, seq)
        self._live[id_] = key
        heapq.heappush(self._heap, (key, id_))

    def remove(self, id_: int) -> bool:
        return self._live.pop(id_, None) is not None

    def _prune(self) -> None:
        while self._heap and self._live.get(
                self._heap[0][1]) != self._heap[0][0]:
            heapq.heappop(self._heap)

    def peek(self) -> Optional[int]:
        self._prune()
        return self._heap[0][1] if self._heap else None

    def pop(self) -> Optional[int]:
        self._prune()
        if not self._heap:
            return None
        _, id_ = heapq.heappop(self._heap)
        del self._live[id_]
        return id_

    def __len__(self) -> int:
        return len(self._live)


def make_indexed_heap():
    if native_available():
        return NativeIndexedHeap()
    return PyIndexedHeap()
