"""Leader election: single-active-scheduler HA.

Reference: the manager runs with controller-runtime lease-based leader
election (cmd/kueue main.go LeaderElection options, renew/lease
durations from the Configuration) and pkg/util/roletracker — only the
leader's scheduler admits; followers keep caches warm and take over when
the lease lapses.

Standalone design: a JSON lease file on shared storage is the Lease
object. ``LeaderElector.tick(now)`` drives acquire/renew against an
injected clock (tests use the engine clock; production passes
time.time). On acquire, the engine rebuilds from the shared journal (the
informer-resync a new leader performs); on lease loss it demotes and
stops scheduling.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass
from typing import Optional


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 Lease, the fields that matter."""

    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration_seconds: float = 15.0


class LeaseFile:
    """The durable lock object. Writes are atomic (tempfile + rename);
    the read-modify-write of an acquire/renew is serialized by an fcntl
    lock on a sidecar file (the CAS the reference gets from the API
    server's resourceVersion) — without it two standbys could both read
    an expired lease and both acquire."""

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"

    def locked(self):
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def _hold():
            with open(self._lock_path, "a+") as lock_fh:
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
        return _hold()

    def read(self) -> Optional[LeaseSpec]:
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None
        return LeaseSpec(**raw)

    def write(self, lease: LeaseSpec) -> None:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".")
        with os.fdopen(fd, "w") as f:
            json.dump(vars(lease), f)
        os.replace(tmp, self.path)


class LeaderElector:
    """client-go leaderelection.LeaderElector semantics: acquire when
    the lease is free or expired, renew while holding, demote when a
    renew discovers another holder."""

    def __init__(self, identity: str, lease: LeaseFile,
                 lease_duration_seconds: float = 15.0,
                 on_started_leading=None, on_stopped_leading=None):
        self.identity = identity
        self.lease = lease
        self.lease_duration = lease_duration_seconds
        self.is_leader = False
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading

    def tick(self, now: float) -> bool:
        """One acquire-or-renew attempt; returns leadership. The whole
        read-check-write runs under the lease's file lock so only one
        replica can win an expired lease."""
        with self.lease.locked():
            current = self.lease.read()
            expired = (current is None or not current.holder
                       or now - current.renew_time
                       > current.lease_duration_seconds)
            if current is not None and current.holder == self.identity:
                # Renew (or re-acquire our own expired lease).
                current.renew_time = now
                self.lease.write(current)
                self._set_leader(True)
                return True
            if expired:
                self.lease.write(LeaseSpec(
                    holder=self.identity, acquire_time=now,
                    renew_time=now,
                    lease_duration_seconds=self.lease_duration))
                self._set_leader(True)
                return True
        self._set_leader(False)
        return False

    def release(self) -> None:
        """Graceful handoff (ReleaseOnCancel)."""
        with self.lease.locked():
            current = self.lease.read()
            if current is not None and current.holder == self.identity:
                self.lease.write(LeaseSpec(
                    lease_duration_seconds=current
                    .lease_duration_seconds))
        self._set_leader(False)

    def _set_leader(self, leading: bool) -> None:
        if leading and not self.is_leader:
            self.is_leader = True
            if self.on_started_leading is not None:
                self.on_started_leading()
        elif not leading and self.is_leader:
            self.is_leader = False
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()


class HAEngine:
    """An engine replica under leader election: followers hold a warm
    standby; the winner rebuilds from the shared journal and schedules.

    The reference analog: every replica runs informers (cache warm), but
    the scheduler/controllers gate on the leadership role
    (roletracker)."""

    def __init__(self, identity: str, lease_path: str, journal_path: str,
                 lease_duration_seconds: float = 15.0):
        self.identity = identity
        self.journal_path = journal_path
        self.engine = None
        self.elector = LeaderElector(
            identity, LeaseFile(lease_path),
            lease_duration_seconds=lease_duration_seconds,
            on_started_leading=self._promote,
            on_stopped_leading=self._demote)

    def _promote(self) -> None:
        from kueue_tpu.store.journal import rebuild_engine

        if os.path.exists(self.journal_path):
            self.engine = rebuild_engine(self.journal_path)
        else:
            from kueue_tpu.controllers.engine import Engine
            from kueue_tpu.store.journal import attach_new_journal

            self.engine = Engine()
            attach_new_journal(self.engine, self.journal_path)

    def _demote(self) -> None:
        self.engine = None  # follower: no scheduling, no journal writes

    def tick(self, now: float) -> None:
        self.elector.tick(now)

    def schedule_once(self):
        """Scheduling is leader-only (the roletracker gate)."""
        if not self.elector.is_leader or self.engine is None:
            return None
        return self.engine.schedule_once()
