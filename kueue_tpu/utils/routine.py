"""Async routine wrappers for admission API calls.

Reference: pkg/util/routine/wrapper.go — ``Wrapper.Run(f)`` runs ``f`` in a
goroutine with optional before/after hooks; the scheduler issues its
admission status patches through it (scheduler.go:870) so a slow apiserver
never blocks the scheduling loop, and unit tests swap in a synchronous
wrapper (scheduler.go:220 setAdmissionRoutineWrapper) for determinism.

The rebuild's engine is single-threaded and lock-free by design (SURVEY §5
race detection), so the engine requires the synchronous wrapper — it is
both the deterministic test mode and the correct in-memory behavior (there
is no apiserver round-trip to hide; the admission closure mutates engine
state directly). ``ThreadWrapper`` provides the reference's asynchronous
form for OUT-OF-PROCESS appliers whose closures only do I/O (socket
replies, journal shipping) — never hand it to an in-process Engine.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class SyncWrapper:
    """Run inline. The analog of the test wrapper the reference injects
    via setAdmissionRoutineWrapper."""

    def __init__(self, before: Optional[Callable] = None,
                 after: Optional[Callable] = None) -> None:
        self.before = before
        self.after = after

    def run(self, f: Callable[[], None]) -> None:
        if self.before is not None:
            self.before()
        try:
            f()
        finally:
            if self.after is not None:
                self.after()


class ThreadWrapper:
    """routine.wrapper: before() inline, then f (and after()) on a thread.
    ``join()`` drains in-flight routines (shutdown). Finished threads are
    pruned on every run() so a long-lived wrapper does not accumulate
    one Thread object per call."""

    def __init__(self, before: Optional[Callable] = None,
                 after: Optional[Callable] = None) -> None:
        self.before = before
        self.after = after
        self._threads: list[threading.Thread] = []

    def run(self, f: Callable[[], None]) -> None:
        if self.before is not None:
            self.before()

        def _body() -> None:
            try:
                f()
            finally:
                if self.after is not None:
                    self.after()

        self._threads = [t for t in self._threads if t.is_alive()]
        t = threading.Thread(target=_body, daemon=True)
        self._threads.append(t)
        t.start()

    def join(self, timeout: Optional[float] = None) -> None:
        """Drain with ``timeout`` as a TOTAL deadline, not per-thread."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - _time.monotonic()))
        self._threads = [t for t in self._threads if t.is_alive()]
