"""Structured logging and profiling hooks.

Reference: the manager logs structured key-value records (zap via logr)
— scheduler.go:291-358 logs per-phase durations, controllers log
transitions with object keys; and Go pprof fills the profiling role.
SURVEY §5: the rebuild's analogs are JSON-lines structured logs and the
JAX profiler (xprof) for device traces.
"""

from __future__ import annotations

import io
import json
import sys
import time
from contextlib import contextmanager
from typing import Optional

LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


class StructuredLogger:
    """JSON-lines logger: one object per record, logr-style named
    hierarchy and key-value pairs."""

    def __init__(self, name: str = "kueue_tpu", stream=None,
                 level: str = "info", clock=None):
        self.name = name
        self.stream = stream if stream is not None else sys.stderr
        self.level = LEVELS.get(level, 20)
        self.clock = clock or time.time
        self._bound: dict = {}

    def with_name(self, suffix: str) -> "StructuredLogger":
        child = StructuredLogger(f"{self.name}.{suffix}", self.stream,
                                 clock=self.clock)
        child.level = self.level
        child._bound = dict(self._bound)
        return child

    def with_values(self, **kv) -> "StructuredLogger":
        child = self.with_name("")  # copy
        child.name = self.name
        child._bound.update(kv)
        return child

    def log(self, level: str, msg: str, **kv) -> None:
        if LEVELS.get(level, 20) < self.level:
            return
        record = {"ts": self.clock(), "level": level, "logger": self.name,
                  "msg": msg}
        record.update(self._bound)
        record.update(kv)
        self.stream.write(json.dumps(record, default=str) + "\n")

    def debug(self, msg: str, **kv) -> None:
        self.log("debug", msg, **kv)

    def info(self, msg: str, **kv) -> None:
        self.log("info", msg, **kv)

    def warning(self, msg: str, **kv) -> None:
        self.log("warning", msg, **kv)

    def error(self, msg: str, **kv) -> None:
        self.log("error", msg, **kv)


def attach_engine_logging(engine, stream=None,
                          level: str = "info") -> StructuredLogger:
    """Wire a structured event stream onto an engine: every EngineEvent
    becomes one JSON record (the controllers' transition logs + the
    events stream), and each cycle logs its phase durations
    (scheduler.go:291-358)."""
    logger = StructuredLogger("kueue_tpu.engine", stream=stream,
                              level=level, clock=lambda: engine.clock)

    def on_event(ev):
        logger.info(ev.kind, workload=ev.workload,
                    clusterQueue=ev.cluster_queue, detail=ev.detail)

    engine.event_listeners.append(on_event)

    original = engine.schedule_once

    def logged_schedule_once():
        result = original()
        if result is not None and engine.last_cycle_phases:
            logger.debug("cycle", **{
                f"phase_{k}_s": round(v, 6)
                for k, v in engine.last_cycle_phases.items()})
        return result

    engine.schedule_once = logged_schedule_once
    return logger


@contextmanager
def device_trace(log_dir: Optional[str] = None):
    """JAX profiler session (xprof) around a scheduling region — the
    pprof analog for the device path. No-ops when profiling is
    unavailable or log_dir is None."""
    if log_dir is None:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
    except Exception:  # noqa: BLE001 — profiling must never break serving
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass


def capture_to_buffer(engine, level: str = "info"
                      ) -> tuple[StructuredLogger, io.StringIO]:
    buf = io.StringIO()
    return attach_engine_logging(engine, stream=buf, level=level), buf
