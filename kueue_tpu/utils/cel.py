"""A CEL-subset compiler for DRA device selectors.

Reference: pkg/dra/claims.go compiles DeviceSelector CEL expressions with
the upstream k8s.io/dynamic-resource-allocation/cel compiler (claims
carry expressions like ``device.driver == "tpu.example.com" &&
device.attributes["example.com/memory"] >= 16``) and evaluates them per
device. This module implements the expression subset those selectors
use — no host ``eval``, a hand-written tokenizer + recursive-descent
parser compiled to closures, with a bounded compilation cache
(claims.go:41-43 celCache analog).

Grammar (CEL operator precedence):
  or:      and ("||" and)*
  and:     not ("&&" not)*
  not:     "!" not | cmp
  cmp:     add (("=="|"!="|"<"|"<="|">"|">="|"in") add)?
  add:     unary (("+"|"-") unary)*
  unary:   "-" unary | postfix
  postfix: primary ("." ident | "." ident "(" args ")" | "[" or "]")*
  primary: literal | ident | "(" or ")" | list

Supported calls: startsWith, endsWith, contains, matches (RE2-style via
``re``), size. Maps support membership (``"k" in device.attributes``)
and indexing; missing keys raise ``CelEvalError`` exactly like CEL's
no-such-key runtime error, which device matching treats as "no match"
(the upstream evaluator's error-per-device behavior).
"""

from __future__ import annotations

import re
from collections import OrderedDict
from typing import Any, Callable

__all__ = ["CelCompileError", "CelEvalError", "compile_cel", "evaluate",
           "evaluate_predicate"]


class CelCompileError(ValueError):
    """Syntax / structure error at compile time (claims.go:235
    validateCELSelectors surfaces these before quota admission)."""


class CelEvalError(RuntimeError):
    """Runtime evaluation error (missing key, type mismatch)."""


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<float>\d+\.\d+)
    | (?P<int>\d+)
    | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op>\|\||&&|==|!=|<=|>=|[!<>().,\[\]+-])
    )""", re.VERBOSE)

_KEYWORDS = {"true": True, "false": False, "null": None}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise CelCompileError(
                f"unexpected character {rest[0]!r} at offset {pos}")
        pos = m.end()
        for kind in ("float", "int", "string", "ident", "op"):
            tok = m.group(kind)
            if tok is not None:
                out.append((kind, tok))
                break
    out.append(("eof", ""))
    return out


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    return re.sub(r"\\(.)", lambda m: {
        "n": "\n", "t": "\t", "r": "\r"}.get(m.group(1), m.group(1)),
        body)


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, op: str) -> None:
        kind, tok = self.next()
        if kind != "op" or tok != op:
            raise CelCompileError(f"expected {op!r}, got {tok!r}")

    # -- precedence levels --

    def parse(self) -> Callable:
        e = self.or_()
        kind, tok = self.peek()
        if kind != "eof":
            raise CelCompileError(f"trailing input at {tok!r}")
        return e

    def or_(self) -> Callable:
        left = self.and_()
        while self.peek() == ("op", "||"):
            self.next()
            right = self.and_()
            left = (lambda lf, rf: lambda env:
                    _truthy(lf(env)) or _truthy(rf(env)))(left, right)
        return left

    def and_(self) -> Callable:
        left = self.not_()
        while self.peek() == ("op", "&&"):
            self.next()
            right = self.not_()
            left = (lambda lf, rf: lambda env:
                    _truthy(lf(env)) and _truthy(rf(env)))(left, right)
        return left

    def not_(self) -> Callable:
        if self.peek() == ("op", "!"):
            self.next()
            inner = self.not_()
            return lambda env, f=inner: not _truthy(f(env))
        return self.cmp()

    _CMP = {"==": lambda a, b: a == b, "!=": lambda a, b: a != b,
            "<": lambda a, b: _ordered(a, b) and a < b,
            "<=": lambda a, b: _ordered(a, b) and a <= b,
            ">": lambda a, b: _ordered(a, b) and a > b,
            ">=": lambda a, b: _ordered(a, b) and a >= b}

    def cmp(self) -> Callable:
        left = self.add()
        kind, tok = self.peek()
        if kind == "op" and tok in self._CMP:
            self.next()
            right = self.add()
            fn = self._CMP[tok]
            return lambda env, lf=left, rf=right, f=fn: f(lf(env), rf(env))
        if kind == "ident" and tok == "in":
            self.next()
            right = self.add()

            def member(env, lf=left, rf=right):
                container = rf(env)
                if isinstance(container, (dict, list, tuple, str)):
                    try:
                        return lf(env) in container
                    except TypeError as e:
                        raise CelEvalError(str(e)) from e
                raise CelEvalError("'in' needs a list, map or string")
            return member
        return left

    def add(self) -> Callable:
        left = self.unary()
        while True:
            kind, tok = self.peek()
            if kind == "op" and tok in ("+", "-"):
                self.next()
                right = self.unary()

                def arith(env, lf=left, rf=right, op=tok):
                    a, b = lf(env), rf(env)
                    if op == "+" and isinstance(a, str) \
                            and isinstance(b, str):
                        return a + b
                    if not isinstance(a, (int, float)) \
                            or not isinstance(b, (int, float)) \
                            or isinstance(a, bool) or isinstance(b, bool):
                        raise CelEvalError(f"bad operands for {op!r}")
                    return a + b if op == "+" else a - b
                left = arith
            else:
                return left

    def unary(self) -> Callable:
        if self.peek() == ("op", "-"):
            self.next()
            inner = self.unary()

            def neg(env, f=inner):
                v = f(env)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    raise CelEvalError("unary '-' needs a number")
                return -v
            return neg
        return self.postfix()

    _METHODS = {
        "startsWith": lambda s, a: _str(s).startswith(_str(a)),
        "endsWith": lambda s, a: _str(s).endswith(_str(a)),
        "contains": lambda s, a: _str(a) in _str(s),
        "matches": lambda s, a: _re_search(_str(s), _str(a)),
    }

    def postfix(self) -> Callable:
        e = self.primary()
        while True:
            kind, tok = self.peek()
            if (kind, tok) == ("op", "."):
                self.next()
                nk, name = self.next()
                if nk != "ident":
                    raise CelCompileError(f"expected member name, got "
                                          f"{name!r}")
                if self.peek() == ("op", "("):
                    self.next()
                    args = []
                    if self.peek() != ("op", ")"):
                        args.append(self.or_())
                        while self.peek() == ("op", ","):
                            self.next()
                            args.append(self.or_())
                    self.expect(")")
                    if name == "size":
                        if args:
                            raise CelCompileError("size() takes no args")
                        e = (lambda f: lambda env: _size(f(env)))(e)
                        continue
                    method = self._METHODS.get(name)
                    if method is None:
                        raise CelCompileError(f"unknown method {name!r}")
                    if len(args) != 1:
                        raise CelCompileError(
                            f"{name}() takes exactly one argument")
                    e = (lambda f, a, m: lambda env:
                         m(f(env), a(env)))(e, args[0], method)
                else:
                    e = (lambda f, n: lambda env:
                         _field(f(env), n))(e, name)
            elif (kind, tok) == ("op", "["):
                self.next()
                idx = self.or_()
                self.expect("]")
                e = (lambda f, ix: lambda env:
                     _index(f(env), ix(env)))(e, idx)
            else:
                return e

    def primary(self) -> Callable:
        kind, tok = self.next()
        if kind == "float":
            v = float(tok)
            return lambda env: v
        if kind == "int":
            v = int(tok)
            return lambda env: v
        if kind == "string":
            v = _unquote(tok)
            return lambda env: v
        if kind == "ident":
            if tok in _KEYWORDS:
                v = _KEYWORDS[tok]
                return lambda env: v
            name = tok
            return lambda env: _var(env, name)
        if (kind, tok) == ("op", "("):
            e = self.or_()
            self.expect(")")
            return e
        if (kind, tok) == ("op", "["):
            items = []
            if self.peek() != ("op", "]"):
                items.append(self.or_())
                while self.peek() == ("op", ","):
                    self.next()
                    items.append(self.or_())
            self.expect("]")
            return lambda env, fs=tuple(items): [f(env) for f in fs]
        raise CelCompileError(f"unexpected token {tok!r}")


def _re_search(s: str, pattern: str) -> bool:
    try:
        return re.search(pattern, s) is not None
    except re.error as e:
        raise CelEvalError(f"invalid regular expression: {e}") from e


def _truthy(v: Any) -> bool:
    if not isinstance(v, bool):
        raise CelEvalError(f"non-boolean in boolean context: {v!r}")
    return v


def _ordered(a: Any, b: Any) -> bool:
    num = (int, float)
    if isinstance(a, num) and not isinstance(a, bool) \
            and isinstance(b, num) and not isinstance(b, bool):
        return True
    if isinstance(a, str) and isinstance(b, str):
        return True
    raise CelEvalError(f"cannot order {a!r} and {b!r}")


def _str(v: Any) -> str:
    if not isinstance(v, str):
        raise CelEvalError(f"string method on non-string {v!r}")
    return v


def _size(v: Any) -> int:
    if isinstance(v, (str, list, tuple, dict)):
        return len(v)
    raise CelEvalError(f"size() of unsupported type {type(v).__name__}")


def _var(env: dict, name: str) -> Any:
    if name not in env:
        raise CelEvalError(f"undeclared reference {name!r}")
    return env[name]


def _field(obj: Any, name: str) -> Any:
    if isinstance(obj, dict):
        if name not in obj:
            raise CelEvalError(f"no such key {name!r}")
        return obj[name]
    raise CelEvalError(f"no such field {name!r}")


def _index(obj: Any, key: Any) -> Any:
    if isinstance(obj, dict):
        if key not in obj:
            raise CelEvalError(f"no such key {key!r}")
        return obj[key]
    if isinstance(obj, (list, tuple)):
        if not isinstance(key, int) or isinstance(key, bool):
            raise CelEvalError("list index must be an integer")
        if not 0 <= key < len(obj):
            raise CelEvalError("index out of range")
        return obj[key]
    raise CelEvalError(f"cannot index {type(obj).__name__}")


_CACHE_MAX = 256
_cache: OrderedDict[str, Callable] = OrderedDict()


def compile_cel(expression: str) -> Callable[[dict], Any]:
    """Compile once, cache up to 256 programs (claims.go celCache)."""
    fn = _cache.get(expression)
    if fn is not None:
        _cache.move_to_end(expression)
        return fn
    fn = _Parser(_tokenize(expression)).parse()
    _cache[expression] = fn
    if len(_cache) > _CACHE_MAX:
        _cache.popitem(last=False)
    return fn


def evaluate(expression: str, env: dict) -> Any:
    return compile_cel(expression)(env)


def evaluate_predicate(expression: str, env: dict) -> bool:
    """Evaluate a selector expression that MUST yield a boolean — the
    upstream DRA compiler type-checks selectors to bool; this subset
    has no type checker, so the bool requirement is enforced at first
    evaluation instead."""
    out = compile_cel(expression)(env)
    if not isinstance(out, bool):
        raise CelEvalError(
            f"selector expression must evaluate to a boolean, got "
            f"{type(out).__name__}")
    return out
