"""Bounded parallel fan-out for I/O-bound work pieces.

Reference: pkg/util/parallelize/parallelize.go — ``Until`` runs N work
pieces over at most 8 workers and surfaces the FIRST error (ErrorChannel
keeps one error, the rest are dropped); the reference uses it for API-call
fan-outs like issuing evictions (preemption.go:207 ParallelizeUntil) and
MultiKueue remote-object cleanup.

Only hand this I/O-bound closures that do not touch shared engine state:
the in-process Engine/QueueManager are lock-free single-threaded by design
(SURVEY §5), so engine mutation must stay on the caller's thread. Remote
clients (client/http_client.py), journal shipping, and socket replies are
the intended work pieces.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

MAX_PARALLELISM = 8


class ErrorChannel:
    """parallelize.go ErrorChannel: keeps at most one error."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._err: Optional[BaseException] = None

    def send_error(self, err: Optional[BaseException]) -> None:
        if err is None:
            return
        with self._lock:
            if self._err is None:
                self._err = err

    def receive(self) -> Optional[BaseException]:
        with self._lock:
            err, self._err = self._err, None
            return err


def until(pieces: int, do_work_piece: Callable[[int], None],
          max_workers: int = MAX_PARALLELISM,
          cancel: Optional[threading.Event] = None
          ) -> Optional[BaseException]:
    """Run ``do_work_piece(i)`` for i in [0, pieces) over at most
    ``max_workers`` threads; returns the first raised exception (or
    None). ``cancel`` stops handing out new pieces once set — started
    pieces run to completion, matching ParallelizeUntil's ctx-cancel
    semantics."""
    if pieces <= 0:
        return None
    err_ch = ErrorChannel()
    # First error stops handing out new pieces (ErrorChannel's
    # SendErrorWithCancel semantics); started pieces run to completion.
    stop = threading.Event()

    def cancelled() -> bool:
        return stop.is_set() or (cancel is not None and cancel.is_set())

    if pieces == 1 or max_workers <= 1:
        for i in range(pieces):
            if cancelled():
                break
            try:
                do_work_piece(i)
            except Exception as e:  # noqa: BLE001
                err_ch.send_error(e)
                stop.set()
        return err_ch.receive()

    next_i = [0]
    lock = threading.Lock()

    def worker() -> None:
        while True:
            if cancelled():
                return
            with lock:
                i = next_i[0]
                if i >= pieces:
                    return
                next_i[0] = i + 1
            try:
                do_work_piece(i)
            except BaseException as e:  # noqa: BLE001 — SystemExit etc.
                # raised in a worker thread would otherwise vanish
                # (Python swallows them off the main thread) and the run
                # would falsely report success.
                err_ch.send_error(e)
                stop.set()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(min(pieces, max_workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return err_ch.receive()
