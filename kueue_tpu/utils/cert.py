"""Serving-certificate management — the pkg/util/cert analog.

The reference manages its webhook/visibility serving certs internally
(self-signed CA, rotated, written to a cert dir watched by the servers).
This standalone analog generates a self-signed serving certificate and
writes the tls.crt / tls.key pair the HTTP endpoints load.
"""

from __future__ import annotations

import datetime
import ipaddress
import os


def generate_self_signed(host: str = "127.0.0.1",
                         days: int = 365) -> tuple[bytes, bytes]:
    """Returns (cert_pem, key_pem) for a self-signed serving cert whose
    SAN covers ``host`` (DNS name or IP literal)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, host)])
    try:
        san = x509.SubjectAlternativeName(
            [x509.IPAddress(ipaddress.ip_address(host))])
    except ValueError:
        san = x509.SubjectAlternativeName([x509.DNSName(host)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(san, critical=False)
            .sign(key, hashes.SHA256()))
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    return cert_pem, key_pem


def ensure_cert_dir(cert_dir: str, host: str = "127.0.0.1"
                    ) -> tuple[str, str]:
    """Write (or reuse) tls.crt / tls.key under ``cert_dir`` — the
    reference's cert-dir contract. Returns the two paths."""
    os.makedirs(cert_dir, exist_ok=True)
    crt = os.path.join(cert_dir, "tls.crt")
    key = os.path.join(cert_dir, "tls.key")
    if not (os.path.exists(crt) and os.path.exists(key)):
        cert_pem, key_pem = generate_self_signed(host)
        with open(crt, "wb") as fh:
            fh.write(cert_pem)
        with open(key, "wb") as fh:
            fh.write(key_pem)
        os.chmod(key, 0o600)
    return crt, key
