"""Pod template resource model and the pod-requests calculation.

The reference takes each PodSet's full pod template and derives the
per-pod effective requests with the upstream scheduler algorithm
(pkg/resources/requests.go:61 NewRequestsFromPodSpec, which delegates to
k8s.io/component-helpers/resource PodRequests): per resource,

    total = max(sum(app containers) + sum(restartable init containers),
                running-max over init containers)  + pod overhead,

optionally overridden by pod-level resources, and adjusted beforehand by
RuntimeClass overhead, LimitRange defaults and limits-as-missing-requests
(pkg/workload/resources.go:141 AdjustResources).

Quantities are plain ints (milli-units for cpu by repo convention).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


def merge_keep_first(dst: dict[str, int], src: dict[str, int]) -> dict[str, int]:
    """pkg/util/resource/resource.go:46 MergeResourceListKeepFirst."""
    out = dict(src)
    out.update(dst)
    return out


def merge_keep_max(a: dict[str, int], b: dict[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, v), v)
    return out


def merge_keep_min(a: dict[str, int], b: dict[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = min(out.get(k, v), v)
    return out


@dataclass
class ContainerSpec:
    """One container's resource stanza (corev1.Container.Resources)."""

    name: str = ""
    requests: dict[str, int] = field(default_factory=dict)
    limits: dict[str, int] = field(default_factory=dict)
    # Init containers with restartPolicy=Always are sidecars: they run for
    # the pod's whole lifetime and add to, rather than precede, the app
    # containers' requests.
    restart_always: bool = False


@dataclass
class PodTemplate:
    """The resource-bearing slice of a PodSet's pod template spec."""

    containers: list[ContainerSpec] = field(default_factory=list)
    init_containers: list[ContainerSpec] = field(default_factory=list)
    # RuntimeClass overhead (nodev1.RuntimeClass.Overhead.PodFixed); either
    # set directly or resolved from runtime_class_name at adjust time.
    overhead: dict[str, int] = field(default_factory=dict)
    runtime_class_name: Optional[str] = None
    # Pod-level resources (KEP-2837): when set, override the aggregated
    # container values for the resources they name.
    pod_requests: Optional[dict[str, int]] = None
    pod_limits: Optional[dict[str, int]] = None


def use_limits_as_missing_requests(template: PodTemplate) -> None:
    """pkg/workload/resources.go:127 UseLimitsAsMissingRequestsInPod."""
    for c in template.init_containers + template.containers:
        c.requests = merge_keep_first(c.requests, c.limits)
    if template.pod_limits is not None:
        template.pod_requests = merge_keep_first(
            template.pod_requests or {}, template.pod_limits)


def pod_requests(template: PodTemplate) -> dict[str, int]:
    """Effective per-pod requests (component-helpers PodRequests)."""
    names: set[str] = set()
    for c in template.containers + template.init_containers:
        names |= set(c.requests)
    if template.pod_requests:
        names |= set(template.pod_requests)
    names |= set(template.overhead)

    out: dict[str, int] = {}
    for res in names:
        app = sum(c.requests.get(res, 0) for c in template.containers)
        sidecars = 0
        init_max = 0
        for c in template.init_containers:
            if c.restart_always:
                sidecars += c.requests.get(res, 0)
                init_max = max(init_max, sidecars)
            else:
                init_max = max(init_max,
                               sidecars + c.requests.get(res, 0))
        total = max(app + sidecars, init_max)
        if template.pod_requests is not None \
                and res in template.pod_requests:
            total = template.pod_requests[res]
        total += template.overhead.get(res, 0)
        if total:
            out[res] = total
    return out


def validate_requests_under_limits(template: PodTemplate) -> list[str]:
    """pkg/workload/resources.go:178 ValidateResources: per container (and
    pod level), requests must not exceed limits."""
    errs = []
    for c in template.init_containers + template.containers:
        over = [r for r, q in c.requests.items()
                if r in c.limits and q > c.limits[r]]
        if over:
            errs.append(f"container {c.name or '?'}: requests exceed "
                        f"limits for {sorted(over)}")
    if template.pod_requests is not None and template.pod_limits is not None:
        over = [r for r, q in template.pod_requests.items()
                if r in template.pod_limits and q > template.pod_limits[r]]
        if over:
            errs.append(f"pod resources: requests exceed limits "
                        f"for {sorted(over)}")
    return errs
