"""Incremental workload row cache: the pending set as live tensors.

The reference keeps its pending world incrementally correct (heaps and
maps updated on every informer event, pkg/cache/queue/manager.go) and the
scheduler snapshots it per cycle. Round 1 re-encoded every pending
workload into dense arrays from scratch each serving cycle —
O(W) Python per cycle, which at the 50k north-star scale costs more than
the device solve itself. This module makes the tensor encoding itself
incremental: queue transitions (push / park / pop / delete) update rows
in O(1), and a cycle only pays for rows that changed since the last one.

Layout: one row per known pending workload (active in the heap, parked
inadmissible, or popped in-flight). Rows hold
  * world-independent fields captured at push time: priority, queue-order
    timestamp, the exact heap sort key (AFS usage frozen at push,
    cluster_queue.go:208), requeue-at, quota-reservation flag;
  * world-dependent fields (CQ index, request columns, fast-path
    eligibility, scheduling-equivalence hash id) recomputed lazily for
    dirty rows against the currently-bound world signature.

Scheduling-equivalence hash ids are refcounted so the dense id space
stays bounded by the row capacity (the cycle kernel scatters them into a
rows+1 sized mask, oracle/batched.py).

The cache is advisory: the engine bridge uses it when present, and the
from-scratch encoder (tensor/schema.encode_workloads) remains both the
fallback and the differential oracle (tests/test_rowcache.py).
"""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np

from kueue_tpu.obs import perf as _perf
from kueue_tpu.workload_info import WorkloadInfo

_INF_TS = np.inf


class _HashRegistry:
    """Dense, refcounted ids for scheduling-equivalence hash tuples.

    Ids are recycled when their refcount drops to zero, so the id space
    never outgrows the maximum number of concurrently-known rows."""

    def __init__(self) -> None:
        self._id_of: dict = {}
        self._count: dict = {}
        self._free: list[int] = []
        self._next = 0

    def acquire(self, h) -> int:
        hid = self._id_of.get(h)
        if hid is None:
            hid = heapq.heappop(self._free) if self._free else self._next
            if hid == self._next:
                self._next += 1
            self._id_of[h] = hid
            self._count[hid] = 0
        self._count[hid] += 1
        return hid

    def release(self, h) -> None:
        hid = self._id_of.get(h)
        if hid is None:
            return
        self._count[hid] -= 1
        if self._count[hid] <= 0:
            del self._count[hid]
            del self._id_of[h]
            heapq.heappush(self._free, hid)


class WorkloadRowCache:
    """Pending workloads as incrementally-maintained dense rows."""

    MIN_CAPACITY = 64

    def __init__(self) -> None:
        self._cap = self.MIN_CAPACITY
        self._row_of: dict[str, int] = {}
        self._free: list[int] = list(range(self._cap - 1, -1, -1))
        self.info_of: list[Optional[WorkloadInfo]] = [None] * self._cap
        self._hash_tuple: list = [None] * self._cap
        # Per-row TAS request signatures (tas/feasibility.request_
        # signature per pod set), computed lazily by tas_requests() and
        # carried across cycles like _hash_tuple — the batched TAS
        # planner re-reads only rows that re-encoded.
        self._tas_req: list = [None] * self._cap
        self._dirty: set[int] = set()
        self._hashes = _HashRegistry()
        # Monotone mutation counter: bumped on every structural change
        # (push/park/pop/remove/world-bind).  The pipelined cycle loop
        # folds it into its speculation token so a speculative encode is
        # only reused when the cache is bit-for-bit unchanged.
        self.mutation_seq = 0

        # world-independent columns
        self.priority = np.zeros(self._cap, np.int64)
        self.timestamp = np.zeros(self._cap, np.float64)
        self.has_qr = np.zeros(self._cap, bool)
        self.requeue_at = np.full(self._cap, -_INF_TS, np.float64)
        self.active = np.zeros(self._cap, bool)
        # heap sort key (afs usage, -priority, ts, seq) frozen at push
        self.key_afs = np.zeros(self._cap, np.float64)
        self.key_negpri = np.zeros(self._cap, np.int64)
        self.key_ts = np.zeros(self._cap, np.float64)
        self.key_seq = np.full(self._cap, np.int64(1) << 60, np.int64)

        # world-dependent columns (valid when row not dirty and the
        # bound signature matches)
        self._signature = None
        self.cq = np.full(self._cap, -1, np.int32)
        # [cap, P, S]: podset axis grows on demand (pow2, capped by
        # schema.MAX_FAST_PODSETS; larger workloads are ineligible).
        self.requests = np.zeros((self._cap, 1, 1), np.int64)
        self.eligible = np.zeros(self._cap, bool)
        self.hash_id = np.zeros(self._cap, np.int32)
        # Stable digest of the row's TAS request signatures (0 = not
        # computed / no pod sets): a cheap cross-cycle change marker
        # for diagnostics; decisions read the _tas_req tuples.
        self.tas_sig = np.zeros(self._cap, np.int64)
        # [cap, NF]: per-flavor eligibility (taints/selectors/affinity),
        # sized at bind_world.
        self.flavor_ok = None

    # -- queue transition hooks (O(1) amortized) --

    def on_push(self, info: WorkloadInfo, sort_key: tuple) -> None:
        """Workload entered (or re-entered) a pending heap."""
        self.mutation_seq += 1
        i = self._row_of.get(info.key)
        wl = info.obj
        if i is None:
            i = self._alloc()
            self._row_of[info.key] = i
            fresh = True
        else:
            # Re-push of the SAME info (requeue after eviction / NoFit):
            # the world-dependent fields are functions of the info's
            # immutable pod-set shape plus the mutable hash prefix
            # checked here (scheduling_hash elements 1-4) — when neither
            # changed, skip the dirty re-encode. Churn worlds requeue
            # thousands of rows per cycle.
            h = self._hash_tuple[i]
            fresh = (self.info_of[i] is not info or h is None
                     or h[1] != wl.priority
                     or h[2] != wl.allowed_resource_flavor
                     or h[3] != wl.has_closed_preemption_gate()
                     or h[4] != tuple(sorted(
                         wl.status.reclaimable_pods.items())))
        self.info_of[i] = info
        from kueue_tpu.workload_info import queue_order_timestamp
        self.priority[i] = wl.effective_priority
        # FIFO timestamp is the eviction-aware queue-order timestamp so
        # the device tiebreak can never diverge from the host heap.
        self.timestamp[i] = queue_order_timestamp(wl)
        self.has_qr[i] = wl.has_quota_reservation
        ra = wl.status.requeue_at
        self.requeue_at[i] = -_INF_TS if ra is None else ra
        self.key_afs[i], negpri, kts, kseq = sort_key
        self.key_negpri[i] = negpri
        self.key_ts[i] = kts
        self.key_seq[i] = kseq
        self.active[i] = True
        if fresh:
            self._dirty.add(i)

    def on_park(self, info: WorkloadInfo) -> None:
        """Workload moved to the inadmissible side map (row kept: a
        cluster event can re-activate it)."""
        self.mutation_seq += 1
        i = self._row_of.get(info.key)
        if i is None:  # parked without ever being pushed
            from kueue_tpu.workload_info import queue_order_timestamp
            self.on_push(info, (0.0, -info.obj.effective_priority,
                                queue_order_timestamp(info.obj),
                                np.int64(1) << 59))
        i = self._row_of[info.key]
        self.info_of[i] = info
        self.active[i] = False

    def on_pop(self, key: str) -> None:
        """Workload popped (in flight with the sequential path)."""
        self.mutation_seq += 1
        i = self._row_of.get(key)
        if i is not None:
            self.active[i] = False

    def on_remove(self, key: str) -> None:
        """Workload left the pending world (admitted / deleted)."""
        self.mutation_seq += 1
        i = self._row_of.pop(key, None)
        if i is None:
            return
        self.active[i] = False
        self.info_of[i] = None
        h = self._hash_tuple[i]
        if h is not None:
            self._hashes.release(h)
            self._hash_tuple[i] = None
        self._tas_req[i] = None
        self.tas_sig[i] = 0
        self.key_seq[i] = np.int64(1) << 60
        self.requeue_at[i] = -_INF_TS
        self._dirty.discard(i)
        self._free.append(i)

    def on_remove_batch(self, keys) -> None:
        """Batched :meth:`on_remove`: clear every departing row's
        columns in four vectorized writes instead of one
        row-at-a-time walk. The per-row Python that remains is only
        the bookkeeping numpy can't express (dict pop, flyweight
        release, free-list push); row order is preserved so the
        free-list matches the serial path exactly.
        """
        self.mutation_seq += 1
        rows = []
        row_pop = self._row_of.pop
        info_of = self.info_of
        hash_tuple = self._hash_tuple
        tas_req = self._tas_req
        dirty_discard = self._dirty.discard
        free_append = self._free.append
        append = rows.append
        # _HashRegistry.release, inlined: the per-key method call is
        # measurable at batch sizes (~1k keys/cycle in the serving
        # drain) and the registry's dicts are stable for the whole
        # batch.
        hashes = self._hashes
        id_of = hashes._id_of
        count = hashes._count
        hash_free = hashes._free
        heappush = heapq.heappush
        for key in keys:
            i = row_pop(key, None)
            if i is None:
                continue
            append(i)
            info_of[i] = None
            h = hash_tuple[i]
            if h is not None:
                hid = id_of.get(h)
                if hid is not None:
                    c = count[hid] - 1
                    if c <= 0:
                        del count[hid]
                        del id_of[h]
                        heappush(hash_free, hid)
                    else:
                        count[hid] = c
                hash_tuple[i] = None
            tas_req[i] = None
            dirty_discard(i)
            free_append(i)
        if not rows:
            return
        idx = np.asarray(rows, np.int64)
        self.active[idx] = False
        self.tas_sig[idx] = 0
        self.key_seq[idx] = np.int64(1) << 60
        self.requeue_at[idx] = -_INF_TS

    # -- capacity management --

    def _alloc(self) -> int:
        if not self._free:
            self._grow(self._cap * 2)
        return self._free.pop()

    def _grow(self, new_cap: int) -> None:
        old = self._cap
        self._cap = new_cap
        for name in ("priority", "timestamp", "has_qr", "requeue_at",
                     "active", "key_afs", "key_negpri", "key_ts",
                     "key_seq", "cq", "eligible", "hash_id", "tas_sig"):
            arr = getattr(self, name)
            fill = {"requeue_at": -_INF_TS, "cq": -1,
                    "key_seq": np.int64(1) << 60}.get(name, 0)
            grown = np.full(new_cap, fill, arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        reqs = np.zeros((new_cap,) + self.requests.shape[1:], np.int64)
        reqs[:old] = self.requests
        self.requests = reqs
        if self.flavor_ok is not None:
            fo = np.ones((new_cap, self.flavor_ok.shape[1]), bool)
            fo[:old] = self.flavor_ok
            self.flavor_ok = fo
        self.info_of.extend([None] * (new_cap - old))
        self._hash_tuple.extend([None] * (new_cap - old))
        self._tas_req.extend([None] * (new_cap - old))
        self._free.extend(range(new_cap - 1, old - 1, -1))

    def maybe_compact(self) -> None:
        """Shrink after a drain: keep the dense-row invariant cheap. Runs
        only between cycles (row indices change)."""
        used = len(self._row_of)
        if self._cap <= self.MIN_CAPACITY or used * 4 > self._cap:
            return
        keep = sorted(self._row_of.values())
        new_cap = max(self.MIN_CAPACITY, 1 << (max(used * 2, 1) - 1)
                      .bit_length())
        remap = {old: new for new, old in enumerate(keep)}
        for name in ("priority", "timestamp", "has_qr", "requeue_at",
                     "active", "key_afs", "key_negpri", "key_ts",
                     "key_seq", "cq", "eligible", "hash_id", "tas_sig"):
            arr = getattr(self, name)
            fill = {"requeue_at": -_INF_TS, "cq": -1,
                    "key_seq": np.int64(1) << 60}.get(name, 0)
            grown = np.full(new_cap, fill, arr.dtype)
            if keep:
                grown[:used] = arr[keep]
            setattr(self, name, grown)
        reqs = np.zeros((new_cap,) + self.requests.shape[1:], np.int64)
        if keep:
            reqs[:used] = self.requests[keep]
        self.requests = reqs
        if self.flavor_ok is not None:
            fo = np.ones((new_cap, self.flavor_ok.shape[1]), bool)
            if keep:
                fo[:used] = self.flavor_ok[keep]
            self.flavor_ok = fo
        self.info_of = [self.info_of[i] for i in keep] + \
            [None] * (new_cap - used)
        self._hash_tuple = [self._hash_tuple[i] for i in keep] + \
            [None] * (new_cap - used)
        self._tas_req = [self._tas_req[i] for i in keep] + \
            [None] * (new_cap - used)
        self._row_of = {k: remap[i] for k, i in self._row_of.items()}
        self._dirty = {remap[i] for i in self._dirty if i in remap}
        self._cap = new_cap
        self._free = list(range(new_cap - 1, used - 1, -1))
        # Re-index hash ids: id values are bounded by the peak row count
        # between rebuilds, and the kernel scatters them into a
        # rows+1-sized mask — after shrinking, rebuild the registry so
        # ids fit the new capacity again.
        self._hashes = _HashRegistry()
        for i in range(used):
            h = self._hash_tuple[i]
            if h is not None:
                self.hash_id[i] = self._hashes.acquire(h)

    # -- per-cycle encoding --

    @staticmethod
    def world_signature(world) -> tuple:
        """Everything the world-dependent row fields depend on: the CQ
        index space, the resource column space, and per-CQ resource
        coverage (drives implicit-pods and uncovered-resource
        eligibility)."""
        return (tuple(world.cq_names), tuple(world.resource_names),
                world.group_of_res.tobytes(),
                world.flavor_spec_token())

    def bind_world(self, world) -> None:
        sig = self.world_signature(world)
        if sig == self._signature:
            return
        self.mutation_seq += 1
        self._signature = sig
        S = max(world.num_resources, 1)
        if S != self.requests.shape[2]:
            self.requests = np.zeros(
                (self._cap, self.requests.shape[1], S), np.int64)
        NF = max(world.num_flavors, 1)
        if self.flavor_ok is None or NF != self.flavor_ok.shape[1]:
            self.flavor_ok = np.ones((self._cap, NF), bool)
        self._dirty.update(self._row_of.values())

    def _encode_row(self, i: int, world, cq_idx: dict,
                    s_idx: dict) -> None:
        """World-dependent fields for one row — the single-row form of
        tensor/schema.encode_workloads."""
        from kueue_tpu.cache.queues import scheduling_hash

        info = self.info_of[i]
        wl = info.obj
        old_h = self._hash_tuple[i]
        h = scheduling_hash(wl, info.cluster_queue)
        if h != old_h:
            if old_h is not None:
                self._hashes.release(old_h)
            self.hash_id[i] = self._hashes.acquire(h)
            self._hash_tuple[i] = h
        ci = cq_idx.get(info.cluster_queue, -1)
        self.cq[i] = ci
        self.requests[i] = 0
        # A re-encode means the info (and so its pod-set requests) may
        # have changed; the TAS side table recomputes on next use.
        self._tas_req[i] = None
        self.tas_sig[i] = 0
        from kueue_tpu.tensor.schema import (
            flavor_eligibility_mask,
            pow2_bucket,
            serving_shape_eligible,
        )
        # Serving rows use the RELAXED predicate: node filters become a
        # per-flavor mask consumed by the cycle kernel instead of
        # demoting the row (round-4 verdict ask #4: head-ineligible),
        # and topology requests stay on device when the batched TAS
        # planner is on (it nominates placements pre-kernel).
        eligible = ci >= 0 and serving_shape_eligible(info)
        if eligible and self.flavor_ok is not None:
            mask = flavor_eligibility_mask(info, world)
            if mask is None:
                eligible = False  # pod sets disagree: host path
            else:
                self.flavor_ok[i] = mask
        if eligible:
            n_ps = len(info.total_requests)
            if n_ps > self.requests.shape[1]:
                # Grow the podset axis (pow2-bucketed so recurring worlds
                # reuse one compiled program per bucket).
                newP = pow2_bucket(n_ps, 1)
                reqs = np.zeros((self._cap, newP,
                                 self.requests.shape[2]), np.int64)
                reqs[:, :self.requests.shape[1]] = self.requests
                self.requests = reqs
            from kueue_tpu.tensor.schema import encode_podset_requests
            if not encode_podset_requests(info, ci, world, s_idx,
                                          self.requests[i]):
                eligible = False
        self.eligible[i] = eligible

    def flush(self, world) -> None:
        """Re-encode every dirty row against the bound world."""
        self.bind_world(world)
        if not self._dirty:
            return
        _pt = _perf.begin()
        cq_idx = {n: i for i, n in enumerate(world.cq_names)}
        s_idx = {n: i for i, n in enumerate(world.resource_names)}
        for i in self._dirty:
            if self.info_of[i] is not None:
                self._encode_row(i, world, cq_idx, s_idx)
        self._dirty.clear()
        _perf.end("encode.rowcache_flush", _pt)

    def refresh_held(self, now: float) -> None:
        """Re-read requeue-at for rows currently held back: eviction
        backoff is the one field controllers touch without a queue
        transition."""
        held = np.nonzero(self.requeue_at > now)[0]
        for i in held:
            info = self.info_of[i]
            if info is None:
                continue
            ra = info.obj.status.requeue_at
            self.requeue_at[i] = -_INF_TS if ra is None else ra

    def tas_requests(self, i: int) -> tuple:
        """Per-podset TAS request tuples for a row — (pod_set_name,
        request_signature, single_pod_requests, count, group_name) per
        pod set — computed once and carried across cycles with the row
        (invalidated by _encode_row / on_remove, remapped on compact).
        The batched TAS planner's collect phase becomes incremental:
        unchanged retried heads cost a list lookup, not a signature
        rebuild."""
        ent = self._tas_req[i]
        if ent is None:
            info = self.info_of[i]
            if info is None:
                return ()
            from kueue_tpu.tas.feasibility import request_signature
            out = []
            for p, psr in enumerate(info.total_requests):
                ps = info.obj.pod_sets[p]
                single = psr.single_pod_requests()
                tr = ps.topology_request
                out.append((ps.name,
                            request_signature(ps, single, psr.count),
                            single, psr.count,
                            tr.pod_set_group_name if tr is not None
                            else None))
            ent = tuple(out)
            self._tas_req[i] = ent
            import zlib
            self.tas_sig[i] = zlib.crc32(repr(
                [(e[0], e[1], e[4]) for e in ent]).encode())
        return ent

    # -- views --

    def info_for(self, key: str) -> Optional[WorkloadInfo]:
        """The WorkloadInfo currently holding this key's row (None when
        the key has no row) — the queue manager uses it to keep the
        one-ClusterQueue-per-pending-workload invariant."""
        i = self._row_of.get(key)
        return None if i is None else self.info_of[i]

    @property
    def num_rows(self) -> int:
        return self._cap

    def tensors(self, world):
        """A WorkloadTensors over the full row space (flush first).
        ``keys`` stays empty — consumers hold ``info_of`` and a per-row
        key list would cost O(rows) Python every cycle."""
        from kueue_tpu.tensor.schema import WorkloadTensors

        self.flush(world)
        return WorkloadTensors(
            num_workloads=self._cap, keys=[], cq=self.cq,
            priority=self.priority, timestamp=self.timestamp,
            requests=self.requests, has_quota_reservation=self.has_qr,
            eligible=self.eligible, hash_id=self.hash_id,
            num_podsets=self.requests.shape[1],
            flavor_ok=self.flavor_ok)

    def head_ranks(self) -> np.ndarray:
        """Global rank by the stored heap sort keys — by construction the
        order the host heaps pop (AFS usage included)."""
        order = np.lexsort((self.key_seq, self.key_ts, self.key_negpri,
                            self.key_afs))
        rank = np.empty(self._cap, np.int64)
        rank[order] = np.arange(self._cap)
        return rank

    def commit_ranks(self) -> np.ndarray:
        """FIFO commit tiebreak: queue-order timestamp, then push
        sequence (scheduler.go:1001)."""
        order = np.lexsort((self.key_seq, self.timestamp))
        rank = np.empty(self._cap, np.int64)
        rank[order] = np.arange(self._cap)
        return rank


class AdmittedRows:
    """Incremental admitted-side tensors for the device preemption
    kernels: the AdmittedTensors encode (tensor/schema.encode_admitted)
    maintained as live rows updated from the scheduler cache's
    admitted-change log (Cache.admitted_dirty) instead of re-encoded
    O(A) every cycle — churn worlds change a handful of admitted rows
    per cycle while A is thousands.

    Holes (freed rows) keep cq=-1 / zero usage, so they can never
    classify as preemption candidates; `info_of` is aligned with rows
    for victim-id mapping. The uid rank (CandidatesOrdering tiebreak,
    common/ordering.go:42) is recomputed vectorized over a fixed-width
    string array whenever any row changed."""

    MIN_CAPACITY = 64
    _HOLE_UID = "￿"  # sorts above every real uid

    def __init__(self, world) -> None:
        self.signature = (WorkloadRowCache.world_signature(world),
                          tuple(world.flavor_names))
        self._cq_idx = {n: i for i, n in enumerate(world.cq_names)}
        self._fl_idx = {n: i for i, n in enumerate(world.flavor_names)}
        self._s_idx = {n: i for i, n in enumerate(world.resource_names)}
        self._S = world.num_resources
        self._R = max(world.num_flavors * world.num_resources, 1)
        self._cap = self.MIN_CAPACITY
        self._row_of: dict[str, int] = {}
        self._free = list(range(self._cap - 1, -1, -1))
        self.info_of: list = [None] * self._cap
        self.cq = np.full(self._cap, -1, np.int32)
        self.priority = np.zeros(self._cap, np.int64)
        self.timestamp = np.zeros(self._cap, np.float64)
        self.qr_time = np.zeros(self._cap, np.float64)
        self.evicted = np.zeros(self._cap, bool)
        self.usage = np.zeros((self._cap, self._R), np.int64)
        self._uids = np.full(self._cap, self._HOLE_UID, dtype="U96")
        self._built = False
        self._epoch = 0
        self._tensors = None

    def _grow(self, new_cap: int) -> None:
        old = self._cap
        self._cap = new_cap
        for name, fill in (("cq", -1), ("priority", 0), ("timestamp", 0),
                           ("qr_time", 0), ("evicted", False)):
            arr = getattr(self, name)
            grown = np.full(new_cap, fill, arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        usage = np.zeros((new_cap, self._R), np.int64)
        usage[:old] = self.usage
        self.usage = usage
        uids = np.full(new_cap, self._HOLE_UID, dtype="U96")
        uids[:old] = self._uids
        self._uids = uids
        self.info_of.extend([None] * (new_cap - old))
        self._free.extend(range(new_cap - 1, old - 1, -1))

    def _encode(self, i: int, info, now: float) -> None:
        wl = info.obj
        self.info_of[i] = info
        self.cq[i] = self._cq_idx.get(info.cluster_queue, -1)
        self.priority[i] = wl.effective_priority
        self.timestamp[i] = wl.creation_time
        self.qr_time[i] = wl.quota_reservation_time(now)
        self.evicted[i] = wl.is_evicted
        self._uids[i] = wl.uid
        row = self.usage[i]
        row[:] = 0
        S = self._S
        from kueue_tpu.api.types import INF
        for fr, v in info.usage().items():
            fi = self._fl_idx.get(fr.flavor)
            si = self._s_idx.get(fr.resource)
            if fi is not None and si is not None:
                # INF saturation (see schema.encode_podset_requests).
                row[fi * S + si] = v if v < INF else INF

    def sync(self, cache, now: float):
        """Apply the cache's admitted-change log; returns the (possibly
        unchanged — identity matters, downstream pads are memoized on
        it) AdmittedTensors view."""
        from kueue_tpu.tensor.schema import AdmittedTensors

        epoch = getattr(cache, "admitted_dirty_epoch", 0)
        if not self._built or epoch != self._epoch:
            # First build, or the cache capped/dropped its change log:
            # full resync (stale rows freed below via the key union).
            dirty = set(cache.workloads.keys())
            dirty.update(self._row_of.keys())
            dirty.update(cache.admitted_dirty)
            self._built = True
            self._epoch = epoch
        elif cache.admitted_dirty:
            dirty = set(cache.admitted_dirty)
        else:
            dirty = None
        cache.admitted_dirty.clear()
        if dirty is None and self._tensors is not None:
            return self._tensors
        _pt = _perf.begin()
        if dirty:
            for key in dirty:
                info = cache.workloads.get(key)
                i = self._row_of.get(key)
                if info is None:
                    if i is not None:
                        del self._row_of[key]
                        self.info_of[i] = None
                        self.cq[i] = -1
                        self.usage[i] = 0
                        self.evicted[i] = False
                        self._uids[i] = self._HOLE_UID
                        self._free.append(i)
                    continue
                if i is None:
                    if not self._free:
                        self._grow(self._cap * 2)
                    i = self._free.pop()
                    self._row_of[key] = i
                self._encode(i, info, now)
        uid_rank = np.empty(self._cap, np.int64)
        uid_rank[np.argsort(self._uids, kind="stable")] = \
            np.arange(self._cap)
        self._tensors = AdmittedTensors(
            num_admitted=self._cap, keys=[], cq=self.cq,
            priority=self.priority, timestamp=self.timestamp,
            qr_time=self.qr_time, uid_rank=uid_rank,
            evicted=self.evicted, usage=self.usage,
            live=len(self._row_of))
        _perf.end("encode.admitted_sync", _pt)
        return self._tensors

    @property
    def live(self) -> int:
        return len(self._row_of)
