"""Dense tensor encoding of the scheduling world — the real API between the
control plane and the TPU oracle.

Mirrors (in array form) the reference's snapshot structures:
  * cohort forest → parent-index / ancestor arrays (depth-capped, padded)
    [pkg/cache/hierarchy, pkg/cache/scheduler/snapshot.go:51]
  * per-node quota knobs → [N, R] arrays over flavor-resource pairs
    [resource_node.go:30]
  * per-CQ resource-group flavor orderings → [C, G, F] index arrays
    [clusterqueue_snapshot.go ResourceGroups]
  * workloads → request matrix [W, S] + priority/timestamp/cq vectors
    [workload.Info, pkg/workload/workload.go:215]

Layout conventions:
  * Nodes 0..C-1 are ClusterQueues, C..N-1 are Cohorts. -1 = "none".
  * A flavor-resource index is fl * S + s (dense NF x S grid); quotas
    default to nominal 0, no borrowing beyond, nothing lendable... i.e.
    nominal=0, borrowing_limit=INF, lending_limit=INF for undefined pairs
    (matching map-miss semantics of the Go code: missing quota = zero
    nominal, nil limits).

All quantity arrays are int64 (milli-units, INF sentinel = api.types.INF).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kueue_tpu.api.types import (
    INF,
    BorrowWithinCohortPolicy,
    ClusterQueue,
    FungibilityPolicy,
    FungibilityPreference,
    PreemptionPolicy,
)
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.workload_info import WorkloadInfo


@dataclass
class WorldTensors:
    """The dense snapshot. All numpy here; ops/ moves them to device."""

    # -- dimensions --
    num_cqs: int
    num_nodes: int
    num_flavors: int
    num_resources: int
    max_flavors_per_group: int
    max_groups: int
    depth: int  # max ancestor-chain length

    # -- name maps (host-only) --
    cq_names: list
    cohort_names: list
    flavor_names: list
    resource_names: list

    # -- cohort forest --
    parent: np.ndarray  # int32[N] node index, -1 = root
    ancestors: np.ndarray  # int32[N, depth], padded -1, [i,0] = parent
    height: np.ndarray  # int32[N] subtree height (cohorts; CQs = 0)

    # -- quotas [N, R] where R = NF * S --
    nominal: np.ndarray  # int64
    borrow_limit: np.ndarray  # int64, INF = unlimited
    lend_limit: np.ndarray  # int64, INF = everything lendable
    usage: np.ndarray  # int64 — CQ rows only; cohort rows derived in ops

    # -- per-CQ config --
    group_of_res: np.ndarray  # int32[C, S] resource-group id, -1 = uncovered
    group_flavors: np.ndarray  # int32[C, G, F] flavor ids in try order, -1 pad
    # static policy flags for the kernel
    no_preemption: np.ndarray  # bool[C] — all preemption policies Never
    can_preempt_while_borrowing: np.ndarray  # bool[C]
    can_always_reclaim: np.ndarray  # bool[C] reclaimWithinCohort == Any
    best_effort: np.ndarray  # bool[C] BestEffortFIFO (parks NoFit heads)
    fung_borrow_try_next: np.ndarray  # bool[C] whenCanBorrow == TryNextFlavor
    fung_preempt_try_next: np.ndarray  # bool[C] whenCanPreempt == TryNextFlavor
    fung_pref_preempt_first: np.ndarray  # bool[C] PreemptionOverBorrowing
    fair_weight: np.ndarray  # float64[N]

    # -- root grouping (commit parallelism) --
    # Admissions only interact within a root subtree (all quota math stays
    # under the root cohort), so the sequential-equivalent commit runs as a
    # short scan per root, vmapped across roots (ops/commit.commit_grouped).
    num_roots: int = 1
    root_members: np.ndarray = None  # int32[Rn, M] CQ ids per root, -1 pad
    root_nodes: np.ndarray = None  # int32[Rn, K] subtree node ids, -1 pad
    local_chain: np.ndarray = None  # int32[C, depth+1] chain positions
    #   into root_nodes[root_of(cq)], -1 pad
    root_parent_local: np.ndarray = None  # int32[Rn, K] parent position
    #   within the same root row, -1 = root/pad (victim-removal bubbling)
    root_of_cq: np.ndarray = None  # int32[C] root row per ClusterQueue
    child_rank: np.ndarray = None  # int64[N] position within the parent's
    #   ordered child list (cohorts first, then CQs — the fair tournament's
    #   first-candidate-wins tiebreak, fair_sharing_iterator.go:125)
    local_depth: np.ndarray = None  # int32[Rn, K] chain distance from the
    #   root row (root = 0, -1 pad) for the hierarchical fair tournament
    # Host-only: ResourceFlavor objects aligned with flavor_names (the
    # row encoders evaluate taint/selector/affinity flavor eligibility
    # against nodeLabels/taints/tolerations); referenced-but-undefined
    # flavors carry None.
    flavor_objects: list = None

    def flavor_spec_token(self) -> tuple:
        """Identity of the flavor axis AND each flavor's node-matching
        spec: the per-workload flavor masks are only reusable while
        this is unchanged. Cached on the instance — WorldTensors are
        rebuilt on spec changes, and the row cache consults the token
        on EVERY row encode (hot in churn worlds)."""
        cached = getattr(self, "_flavor_token", None)
        if cached is not None:
            return cached
        out = []
        for name, rf in zip(self.flavor_names, self.flavor_objects
                            or [None] * len(self.flavor_names)):
            if rf is None:
                out.append((name,))
            else:
                out.append((name,
                            tuple(sorted(rf.node_labels.items())),
                            tuple(rf.node_taints),
                            tuple(rf.tolerations),
                            rf.topology_name))
        self._flavor_token = tuple(out)
        return self._flavor_token

    def fr_index(self, flavor: str, resource: str) -> int:
        return (self.flavor_names.index(flavor) * self.num_resources
                + self.resource_names.index(resource))


@dataclass
class WorkloadTensors:
    """Pending workloads on the fast path. The pod-set axis is padded to
    ``num_podsets`` (P, a power of two ≤ MAX_FAST_PODSETS); padding rows
    carry zero requests and never affect nomination or commit."""

    num_workloads: int
    keys: list  # host-side workload keys, aligned with rows
    cq: np.ndarray  # int32[W] CQ index
    priority: np.ndarray  # int64[W] effective priority
    timestamp: np.ndarray  # float64[W] queue-order timestamp
    requests: np.ndarray  # int64[W, P, S] count-scaled totals per podset
    has_quota_reservation: np.ndarray  # bool[W]
    eligible: np.ndarray  # bool[W] — encodable on the fast path
    # Scheduling-equivalence hash id (workload.go:236 SchedulingHash),
    # dense-coded: equal ids => identical admission verdicts.
    hash_id: np.ndarray = None  # int32[W]
    num_podsets: int = 1  # P
    # bool[W, NF] per-flavor eligibility (taints/selectors/affinity —
    # flavor_eligibility_mask); None = every flavor eligible everywhere.
    flavor_ok: np.ndarray = None


# Pod-set cap for the dense path: the kernel scans podsets sequentially
# (flavorassigner.go:707 walks podsets in order), so the pad is a compile
# -time constant; workloads beyond it take the host path.
MAX_FAST_PODSETS = 8


def pow2_bucket(n: int, floor: int) -> int:
    """Power-of-two bucket for a dynamic axis length: repeated launches
    with drifting sizes reuse one compiled program per bucket."""
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


def pad_axis0(arr: np.ndarray, target: int, fill) -> np.ndarray:
    """Pad axis 0 to ``target`` rows with a sentinel fill. The sentinel
    must match the kernel's masking semantics (e.g. cq=-1 rows never
    classify, rank=BIG rows never win heads)."""
    a = np.asarray(arr)
    if a.shape[0] >= target:
        return a
    return np.concatenate(
        [a, np.full((target - a.shape[0],) + a.shape[1:], fill, a.dtype)])


# Workload-axis sentinel fills shared by every bucket-padding site:
# rank/commit_rank BIG (never a head), cq 0 with pending=False.
WL_PAD_FILLS = dict(rank=np.int64(1) << 40, commit_rank=np.int64(1) << 40,
                    wl_cq=0, wl_req=0, wl_priority=0, wl_has_qr=False,
                    wl_hash=0, wl_ts=0.0, wl_flavor_ok=True)


def build_root_grouping(parent: np.ndarray, ancestors: np.ndarray,
                        num_cqs: int, max_depth: int):
    """Group the cohort forest by root subtree for the parallel commit
    (ops/commit.commit_grouped). Nodes 0..num_cqs-1 must be the CQ rows.

    Returns (num_roots, root_members int32[Rn, M], root_nodes
    int32[Rn, K], local_chain int32[C, max_depth+1])."""
    N = parent.shape[0]
    C = num_cqs
    root_of = np.arange(N, dtype=np.int32)
    for i in range(N):
        a = i
        while parent[a] >= 0:
            a = parent[a]
        root_of[i] = a
    roots = sorted(set(int(r) for r in root_of))
    root_idx = {r: i for i, r in enumerate(roots)}
    Rn = len(roots)
    members_of = [[] for _ in range(Rn)]
    nodes_of = [[] for _ in range(Rn)]
    for i in range(N):
        ri = root_idx[int(root_of[i])]
        nodes_of[ri].append(i)
        if i < C:
            members_of[ri].append(i)
    M = max((len(m) for m in members_of), default=1) or 1
    K = max((len(n) for n in nodes_of), default=1) or 1
    root_members = np.full((Rn, M), -1, np.int32)
    root_nodes = np.full((Rn, K), -1, np.int32)
    node_pos = {}
    for ri in range(Rn):
        for j, m in enumerate(members_of[ri]):
            root_members[ri, j] = m
        for j, nd in enumerate(nodes_of[ri]):
            root_nodes[ri, j] = nd
            node_pos[nd] = j
    local_chain = np.full((C, max_depth + 1), -1, np.int32)
    for ci in range(C):
        local_chain[ci, 0] = node_pos[ci]
        for d in range(max_depth):
            a = ancestors[ci, d]
            if a < 0:
                break
            local_chain[ci, d + 1] = node_pos[int(a)]
    root_parent_local = np.full((Rn, K), -1, np.int32)
    for ri in range(Rn):
        for j, nd in enumerate(nodes_of[ri]):
            p = parent[nd]
            if p >= 0:
                root_parent_local[ri, j] = node_pos[int(p)]
    root_of_cq = np.zeros(max(C, 1), np.int32)
    for ri in range(Rn):
        for m in members_of[ri]:
            root_of_cq[m] = ri
    local_depth = np.full((Rn, K), -1, np.int32)
    for ri in range(Rn):
        for j, nd in enumerate(nodes_of[ri]):
            d, a = 0, j
            while root_parent_local[ri, a] >= 0:
                a = int(root_parent_local[ri, a])
                d += 1
            local_depth[ri, j] = d
    return (Rn, root_members, root_nodes, local_chain, root_parent_local,
            root_of_cq, local_depth)


def encode_snapshot(snap: Snapshot, max_depth: int = 8) -> WorldTensors:
    """Flatten a Snapshot into dense arrays."""
    cq_names = sorted(snap.cluster_queues)
    cohort_names = sorted(snap.cohorts)
    cq_idx = {n: i for i, n in enumerate(cq_names)}
    cohort_idx = {n: len(cq_names) + i for i, n in enumerate(cohort_names)}
    C = len(cq_names)
    N = C + len(cohort_names)

    flavor_names = sorted(snap.resource_flavors)
    resource_names = sorted({
        fr.resource
        for cqs in snap.cluster_queues.values()
        for fr in cqs.node.quotas
    } | {
        fr.resource
        for cs in snap.cohorts.values()
        for fr in cs.node.quotas
    })
    # Flavors referenced in quotas but not registered as ResourceFlavor
    # objects still need ids (reference logs "flavor not found").
    referenced = {
        fr.flavor
        for node in list(snap.cluster_queues.values()) + list(
            snap.cohorts.values())
        for fr in node.node.quotas
    }
    for f in sorted(referenced - set(flavor_names)):
        flavor_names.append(f)
    fl_idx = {n: i for i, n in enumerate(flavor_names)}
    s_idx = {n: i for i, n in enumerate(resource_names)}
    NF, S = len(flavor_names), len(resource_names)
    R = max(NF * S, 1)

    parent = np.full(N, -1, np.int32)
    fair_weight = np.ones(N, np.float64)

    def node_of(obj) -> int:
        from kueue_tpu.cache.snapshot import ClusterQueueSnapshot
        if isinstance(obj, ClusterQueueSnapshot):
            return cq_idx[obj.name]
        return cohort_idx[obj.name]

    all_nodes = [snap.cluster_queues[n] for n in cq_names] + \
                [snap.cohorts[n] for n in cohort_names]
    for i, node in enumerate(all_nodes):
        if node.parent is not None:
            parent[i] = node_of(node.parent)
        fair_weight[i] = node.fair_weight

    ancestors = np.full((N, max_depth), -1, np.int32)
    for i in range(N):
        a, d = parent[i], 0
        while a >= 0 and d < max_depth:
            ancestors[i, d] = a
            a = parent[a]
            d += 1

    height = np.zeros(N, np.int32)
    for name, cs in snap.cohorts.items():
        height[cohort_idx[name]] = cs.height()

    nominal = np.zeros((N, R), np.int64)
    borrow_limit = np.full((N, R), INF, np.int64)
    lend_limit = np.full((N, R), INF, np.int64)
    usage = np.zeros((N, R), np.int64)
    for i, node in enumerate(all_nodes):
        for fr, q in node.node.quotas.items():
            if fr.flavor not in fl_idx or fr.resource not in s_idx:
                continue
            r = fl_idx[fr.flavor] * S + s_idx[fr.resource]
            nominal[i, r] = q.nominal
            if q.borrowing_limit is not None:
                borrow_limit[i, r] = q.borrowing_limit
            if q.lending_limit is not None:
                lend_limit[i, r] = q.lending_limit
        for fr, u in node.node.usage.items():
            if i >= C:
                continue  # cohort usage is derived
            if fr.flavor not in fl_idx or fr.resource not in s_idx:
                continue
            usage[i, fl_idx[fr.flavor] * S + s_idx[fr.resource]] = u

    G = max((len(snap.cluster_queues[n].spec.resource_groups)
             for n in cq_names), default=1) or 1
    F = 1
    for n in cq_names:
        for rg in snap.cluster_queues[n].spec.resource_groups:
            F = max(F, len(rg.flavors))

    group_of_res = np.full((C, S), -1, np.int32)
    group_flavors = np.full((C, G, F), -1, np.int32)
    no_preemption = np.zeros(C, bool)
    can_pwb = np.zeros(C, bool)
    can_always_reclaim = np.zeros(C, bool)
    best_effort = np.zeros(C, bool)
    fung_b_try = np.zeros(C, bool)
    fung_p_try = np.zeros(C, bool)
    fung_pref_p = np.zeros(C, bool)
    for ci, n in enumerate(cq_names):
        spec = snap.cluster_queues[n].spec
        for gi, rg in enumerate(spec.resource_groups):
            for res in rg.covered_resources:
                if res in s_idx:
                    group_of_res[ci, s_idx[res]] = gi
            for fi, fq in enumerate(rg.flavors):
                # Quotas naming an unregistered ResourceFlavor are
                # unusable slots ("flavor not found" errors to NoFit in
                # flavorassigner.go): leave -1 so the kernel's flavor
                # scan can never choose them. Their fr columns still
                # exist (usage bookkeeping), but no nomination path
                # reaches them.
                if fq.name in snap.resource_flavors:
                    group_flavors[ci, gi, fi] = fl_idx[fq.name]
        from kueue_tpu.api.types import QueueingStrategy
        best_effort[ci] = (spec.queueing_strategy
                           == QueueingStrategy.BEST_EFFORT_FIFO)
        p = spec.preemption
        can_always_reclaim[ci] = (p.reclaim_within_cohort
                                  == PreemptionPolicy.ANY)
        no_preemption[ci] = (
            p.within_cluster_queue == PreemptionPolicy.NEVER
            and p.reclaim_within_cohort == PreemptionPolicy.NEVER)
        can_pwb[ci] = (
            (p.borrow_within_cohort is not None
             and p.borrow_within_cohort.policy
             != BorrowWithinCohortPolicy.NEVER)
            or (snap.cluster_queues[n].fair_sharing_enabled
                and p.reclaim_within_cohort != PreemptionPolicy.NEVER))
        fung = spec.flavor_fungibility
        fung_b_try[ci] = (fung.when_can_borrow
                          == FungibilityPolicy.TRY_NEXT_FLAVOR)
        fung_p_try[ci] = (fung.when_can_preempt
                          == FungibilityPolicy.TRY_NEXT_FLAVOR)
        fung_pref_p[ci] = (fung.preference
                           == FungibilityPreference.PREEMPTION_OVER_BORROWING)

    (Rn, root_members, root_nodes, local_chain, root_parent_local,
     root_of_cq, local_depth) = build_root_grouping(parent, ancestors, C,
                                                    max_depth)

    # Fair-tournament tiebreak: the reference iterates child cohorts then
    # child CQs in list order, first candidate winning exact ties
    # (fair_sharing_iterator.go:125).
    child_rank = np.zeros(N, np.int64)
    for name, cs in snap.cohorts.items():
        children = list(cs.child_cohorts) + list(cs.child_cqs)
        for j, ch in enumerate(children):
            child_rank[node_of(ch)] = j

    return WorldTensors(
        num_cqs=C, num_nodes=N, num_flavors=NF, num_resources=S,
        max_flavors_per_group=F, max_groups=G, depth=max_depth,
        cq_names=cq_names, cohort_names=cohort_names,
        flavor_names=flavor_names, resource_names=resource_names,
        parent=parent, ancestors=ancestors, height=height,
        nominal=nominal, borrow_limit=borrow_limit, lend_limit=lend_limit,
        usage=usage, group_of_res=group_of_res, group_flavors=group_flavors,
        no_preemption=no_preemption, can_preempt_while_borrowing=can_pwb,
        can_always_reclaim=can_always_reclaim, best_effort=best_effort,
        fung_borrow_try_next=fung_b_try, fung_preempt_try_next=fung_p_try,
        fung_pref_preempt_first=fung_pref_p, fair_weight=fair_weight,
        num_roots=Rn, root_members=root_members, root_nodes=root_nodes,
        local_chain=local_chain, root_parent_local=root_parent_local,
        root_of_cq=root_of_cq, child_rank=child_rank,
        local_depth=local_depth,
        flavor_objects=[snap.resource_flavors.get(n)
                        for n in flavor_names],
    )


@dataclass
class AdmittedTensors:
    """Admitted workloads (preemption candidate pool)."""

    num_admitted: int  # ROW-SPACE size (== array length; the
    #   incremental AdmittedRows keeps holes, so this can exceed `live`)
    keys: list  # host-side workload keys, aligned with rows
    cq: np.ndarray  # int32[A]
    priority: np.ndarray  # int64[A]
    timestamp: np.ndarray  # float64[A] creation time
    qr_time: np.ndarray  # float64[A] quota-reservation timestamp
    uid_rank: np.ndarray  # int64[A] rank of uid (CandidatesOrdering tiebreak)
    evicted: np.ndarray  # bool[A]
    usage: np.ndarray  # int64[A, R] on the flavor-resource grid
    live: int = None  # live admitted count (None = num_admitted)


def encode_admitted(world: WorldTensors, infos: list,
                    now: float = 0.0) -> AdmittedTensors:
    """Encode admitted workloads for the device preemption kernel."""
    A = len(infos)
    R = max(world.num_flavors * world.num_resources, 1)
    cq_idx = {n: i for i, n in enumerate(world.cq_names)}
    fl_idx = {n: i for i, n in enumerate(world.flavor_names)}
    s_idx = {n: i for i, n in enumerate(world.resource_names)}
    S = world.num_resources

    cq = np.full(A, -1, np.int32)
    priority = np.zeros(A, np.int64)
    timestamp = np.zeros(A, np.float64)
    qr_time = np.zeros(A, np.float64)
    evicted = np.zeros(A, bool)
    usage = np.zeros((A, R), np.int64)
    keys = []
    uids = []
    for i, info in enumerate(infos):
        keys.append(info.key)
        uids.append(info.obj.uid)
        cq[i] = cq_idx.get(info.cluster_queue, -1)
        priority[i] = info.obj.effective_priority
        timestamp[i] = info.obj.creation_time
        qr_time[i] = info.obj.quota_reservation_time(now)
        evicted[i] = info.obj.is_evicted
        for fr, v in info.usage().items():
            if fr.flavor in fl_idx and fr.resource in s_idx:
                # INF saturation, like encode_podset_requests: unbounded
                # host ints would overflow the int64 grid.
                usage[i, fl_idx[fr.flavor] * S + s_idx[fr.resource]] = \
                    v if v < INF else INF
    uid_rank = np.empty(A, np.int64)
    uid_rank[np.argsort(np.asarray(uids, dtype=object))] = np.arange(A)
    return AdmittedTensors(
        num_admitted=A, keys=keys, cq=cq, priority=priority,
        timestamp=timestamp, qr_time=qr_time, uid_rank=uid_rank,
        evicted=evicted, usage=usage)


def encode_podset_requests(info, ci: int, world, s_idx: dict,
                           out) -> bool:
    """Fill one workload's [P, S] request rows (implicit pods resource
    when the CQ covers it). Returns False when a positive request names
    a resource outside the world's column space (host-path-only).
    Shared by the batch encoder and the incremental row cache so the
    two can never desynchronize."""
    pods_si = s_idx.get("pods")
    covers_pods = (pods_si is not None
                   and world.group_of_res[ci, pods_si] >= 0)
    ok = True
    for p, psr in enumerate(info.total_requests):
        reqs = dict(psr.requests)
        if covers_pods:
            reqs["pods"] = psr.count
        for res, q in reqs.items():
            si = s_idx.get(res)
            if si is None:
                if q > 0:
                    ok = False
                continue
            # Saturate at the INF sentinel: unbounded host-side ints
            # would wrap in the kernels' int64 arithmetic (the
            # reference's MaxInt64 overflow guards), flipping an
            # impossible request into a negative fitting one.
            out[p, si] = q if q < INF else INF
    return ok


def dense_path_eligible(info) -> bool:
    """Whether a pending workload can be decided on the dense device
    path. Shared by the batch encoder below and the incremental row
    cache (tensor/rowcache.py) so the two can never desynchronize.

    The kernel handles up to MAX_FAST_PODSETS pod sets per workload
    (flavorassigner.go:707/932 walks podsets in order; the kernel scans
    the padded podset axis with within-workload usage accumulation).
    Ineligible: more podsets than the cap, partial admission
    (min_count), topology requests, node selectors/affinity,
    tolerations, explicit zero-quantity requests (Go assigns
    flavors/borrow levels to those; the dense encoding cannot
    distinguish explicit-zero from absent), and elastic workload-slice
    replacements (the host path owns ReplacedWorkloadSlice's freed-usage
    fit and old-slice finish, scheduler.go:765)."""
    cached = getattr(info, "_dense_elig", None)
    if cached is not None:
        return cached
    info._dense_elig = out = _dense_path_eligible(info)
    return out


def _dense_path_eligible(info) -> bool:
    # Pure in the info's immutable shape (pod sets, derived requests,
    # slice replacement), so dense_path_eligible memoizes per info —
    # churn worlds re-encode the same rows thousands of times.
    if not _dense_shape_eligible(info):
        return False
    for ps in info.obj.pod_sets:
        if ps.node_selector or ps.node_affinity or ps.tolerations:
            return False
    return True


def _dense_shape_eligible(info) -> bool:
    """The SHAPE part of fast-path eligibility (podset cap, partial
    admission, topology, zero-quantity, slice replacement). Node
    filters (selectors/affinity/tolerations) are NOT a shape problem —
    the serving row cache encodes them as per-flavor eligibility masks
    (flavor_eligibility_mask) the cycle kernel consumes; the whole-drain
    paths, which don't thread masks, keep the strict predicate above.
    Memoized per info like dense_path_eligible (churn worlds re-encode
    the same rows thousands of times)."""
    cached = getattr(info, "_dense_shape_elig", None)
    if cached is not None:
        return cached
    info._dense_shape_elig = out = _dense_shape_eligible_impl(info)
    return out


def _dense_shape_eligible_impl(info) -> bool:
    if len(info.total_requests) > MAX_FAST_PODSETS:
        return False
    if info.obj.replaced_workload_slice is not None:
        return False
    for p, psr in enumerate(info.total_requests):
        ps = info.obj.pod_sets[p]
        if ps.min_count is not None or ps.topology_request is not None:
            return False
        if any(q == 0 for q in psr.requests.values()):
            return False
    return True


def serving_shape_eligible(info) -> bool:
    """Shape eligibility for SERVING rows (tensor/rowcache.py). Same as
    _dense_shape_eligible, except a topology request no longer demotes
    the row when the batched TAS planner (tas/batched.py) is on: the
    planner nominates a placement per head before the cycle kernel and
    demotes — per head, with a reason — only what it cannot express.
    Whole-drain encoders keep the strict predicate: they don't run the
    planner, so a topology row there would admit without a placement.
    Memoized per (info, planner-enabled) — KUEUE_TPU_TAS_BATCH toggles
    between engine builds in tests."""
    from kueue_tpu.tas.batched import enabled
    flag = enabled()
    cached = getattr(info, "_serving_shape_elig", None)
    if cached is not None and cached[0] == flag:
        return cached[1]
    if not flag:
        out = _dense_shape_eligible(info)
    else:
        out = _serving_shape_eligible_impl(info)
    info._serving_shape_elig = (flag, out)
    return out


def _serving_shape_eligible_impl(info) -> bool:
    if len(info.total_requests) > MAX_FAST_PODSETS:
        return False
    if info.obj.replaced_workload_slice is not None:
        return False
    for p, psr in enumerate(info.total_requests):
        ps = info.obj.pod_sets[p]
        if ps.min_count is not None:
            return False
        if any(q == 0 for q in psr.requests.values()):
            return False
    return True


def flavor_eligibility_mask(info, world):
    """bool[num_flavors] — which of the world's flavors this workload's
    pod sets can match (flavorassigner.flavor_matches_podset: taints vs
    tolerations, selectors/affinity vs the flavor's nodeLabels). Returns
    None when the pod sets DISAGREE (the [W, F] encoding has no podset
    axis; those rows stay host-path) or when a referenced flavor has no
    registered object. Memoized per info against the world's
    flavor-spec token."""
    import numpy as np

    token = world.flavor_spec_token()
    cached = getattr(info, "_flavor_mask", None)
    if cached is not None and cached[0] == token:
        return cached[1]
    from kueue_tpu.scheduler.flavorassigner import flavor_matches_podset

    NF = max(world.num_flavors, 1)
    filtered = [ps for ps in info.obj.pod_sets
                if ps.node_selector or ps.node_affinity or ps.tolerations]
    if not filtered:
        mask = np.ones(NF, bool)
        info._flavor_mask = (token, mask)
        return mask
    mask = None
    for ps in info.obj.pod_sets:
        row = np.zeros(NF, bool)
        for i, rf in enumerate(world.flavor_objects or ()):
            if rf is None:
                # Referenced-but-undefined flavor: the sequential path
                # can't match it either; leave ineligible.
                continue
            row[i] = flavor_matches_podset(rf, ps) is None
        if mask is None:
            mask = row
        elif not np.array_equal(mask, row):
            info._flavor_mask = (token, None)
            return None
    info._flavor_mask = (token, mask)
    return mask


def encode_workloads(world: WorldTensors,
                     infos: list[WorkloadInfo]) -> WorkloadTensors:
    """Encode pending workloads. Workloads beyond the fast-path shape
    (dense_path_eligible) are marked ineligible; the host fallback
    handles them."""
    W = len(infos)
    S = world.num_resources
    cq_idx = {n: i for i, n in enumerate(world.cq_names)}
    s_idx = {n: i for i, n in enumerate(world.resource_names)}

    cq = np.full(W, -1, np.int32)
    priority = np.zeros(W, np.int64)
    timestamp = np.zeros(W, np.float64)
    has_qr = np.zeros(W, bool)
    eligible = np.ones(W, bool)
    hash_id = np.zeros(W, np.int32)
    hash_codes: dict = {}
    keys = []
    from kueue_tpu.cache.queues import scheduling_hash
    from kueue_tpu.workload_info import queue_order_timestamp

    P = 1
    for info in infos:
        n = len(info.total_requests)
        if 1 < n and dense_path_eligible(info):
            P = max(P, n)
    P = pow2_bucket(P, 1)
    requests = np.zeros((W, P, S), np.int64)

    for i, info in enumerate(infos):
        keys.append(info.key)
        h = scheduling_hash(info.obj, info.cluster_queue)
        hash_id[i] = hash_codes.setdefault(h, len(hash_codes))
        cq[i] = cq_idx.get(info.cluster_queue, -1)
        priority[i] = info.obj.effective_priority
        # Eviction-aware FIFO timestamp (workload.go:1087) — must match
        # the host heap's ordering exactly.
        timestamp[i] = queue_order_timestamp(info.obj)
        has_qr[i] = info.obj.has_quota_reservation
        if cq[i] < 0 or not dense_path_eligible(info):
            eligible[i] = False
            continue
        if not encode_podset_requests(info, int(cq[i]), world, s_idx,
                                      requests[i]):
            eligible[i] = False
    return WorkloadTensors(
        num_workloads=W, keys=keys, cq=cq, priority=priority,
        timestamp=timestamp, requests=requests,
        has_quota_reservation=has_qr, eligible=eligible, hash_id=hash_id,
        num_podsets=P)
