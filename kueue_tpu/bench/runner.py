"""Performance runner: generate a scenario, drive the engine with a
workload-execution mimic, and check results against a rangespec.

Reference: test/performance/scheduler — the runner generates
CQs/cohorts/workloads from generator.yaml, mimics execution by finishing
workloads after runtimeMs (no pods), and a checker asserts wall time /
utilization / time-to-admission classes against rangespec.yaml
(SURVEY.md §4, BASELINE.md).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.types import (
    ClusterQueue,
    Cohort,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_tpu.controllers.engine import Engine

CPU = "cpu"


@dataclass
class WorkloadClass:
    """generator.yaml class: count of quota units, share, runtime."""

    name: str
    units: int  # quota units (1 unit = 1000 milli)
    share: float
    runtime_s: float


@dataclass
class GeneratorConfig:
    """configs/baseline/generator.yaml shape."""

    n_cohorts: int = 5
    cqs_per_cohort: int = 6
    nominal_units_per_cq: int = 20
    n_workloads: int = 1500
    classes: tuple[WorkloadClass, ...] = (
        WorkloadClass("small", 1, 0.70, 5.0),
        WorkloadClass("medium", 5, 0.20, 10.0),
        WorkloadClass("large", 20, 0.10, 15.0),
    )
    seed: int = 0


@dataclass
class RangeSpec:
    """configs/baseline/rangespec.yaml shape."""

    max_wall_time_s: Optional[float] = None
    min_avg_cq_utilization: Optional[float] = None
    max_avg_time_to_admission_s: dict[str, float] = field(
        default_factory=dict)


@dataclass
class RunStats:
    wall_time_s: float = 0.0
    sim_time_s: float = 0.0
    admitted: int = 0
    cycles: int = 0
    avg_cq_utilization: float = 0.0
    avg_time_to_admission_s: dict[str, float] = field(default_factory=dict)


def generate(engine: Engine, cfg: GeneratorConfig) -> dict[str, str]:
    """Create the scenario objects; returns workload key -> class name."""
    rng = random.Random(cfg.seed)
    engine.create_resource_flavor(ResourceFlavor("default"))
    n_cqs = cfg.n_cohorts * cfg.cqs_per_cohort
    for i in range(cfg.n_cohorts):
        engine.create_cohort(Cohort(f"cohort-{i}"))
    for i in range(n_cqs):
        engine.create_cluster_queue(ClusterQueue(
            name=f"cq-{i}", cohort=f"cohort-{i % cfg.n_cohorts}",
            resource_groups=(ResourceGroup(
                (CPU,),
                (FlavorQuotas("default", {CPU: ResourceQuota(
                    cfg.nominal_units_per_cq * 1000)}),)),),
        ))
        engine.create_local_queue(LocalQueue(f"lq-{i}", "default", f"cq-{i}"))

    class_of: dict[str, str] = {}
    for i in range(cfg.n_workloads):
        r = rng.random()
        acc = 0.0
        cls = cfg.classes[-1]
        for c in cfg.classes:
            acc += c.share
            if r < acc:
                cls = c
                break
        wl = Workload(
            name=f"wl-{i}", queue_name=f"lq-{rng.randrange(n_cqs)}",
            creation_time=0.0,
            pod_sets=(PodSet("main", 1, {CPU: cls.units * 1000}),))
        engine.submit(wl)
        class_of[wl.key] = cls.name
    return class_of


def run(engine: Engine, cfg: GeneratorConfig,
        tick_s: float = 1.0, max_sim_s: float = 100_000.0) -> RunStats:
    """Drive scheduling with the execution mimic: admitted workloads
    finish after their class runtime (simulated clock)."""
    class_of = generate(engine, cfg)
    runtime_of = {c.name: c.runtime_s for c in cfg.classes}
    finish_at: dict[str, float] = {}
    admitted_at: dict[str, float] = {}
    total = len(class_of)
    utilization_samples: list[float] = []
    n_cqs = cfg.n_cohorts * cfg.cqs_per_cohort
    capacity = n_cqs * cfg.nominal_units_per_cq * 1000

    t_start = time.perf_counter()
    stats = RunStats()
    while len(finish_at) < total and engine.clock < max_sim_s:
        # Scheduling until quiescent at this instant.
        while True:
            result = engine.schedule_once()
            stats.cycles += 1
            if result is None or not result.assumed:
                break
            for e in result.assumed:
                key = e.obj.key
                admitted_at[key] = engine.clock
                finish_at[key] = engine.clock + runtime_of[class_of[key]]
        # Sample utilization.
        used = sum(sum(info.usage().values())
                   for info in engine.cache.workloads.values())
        utilization_samples.append(used / capacity if capacity else 0.0)
        # Advance to the next finish event (or tick).
        pending_finishes = [t for k, t in finish_at.items()
                            if t > engine.clock]
        if pending_finishes:
            next_t = min(min(pending_finishes), engine.clock + tick_s)
        else:
            next_t = engine.clock + tick_s
        engine.tick(next_t - engine.clock)
        for key, t in list(finish_at.items()):
            if t <= engine.clock and key in engine.workloads \
                    and not engine.workloads[key].is_finished:
                engine.finish(key)
        if not engine.queues.has_pending() and len(admitted_at) == total:
            # Everything admitted; fast-forward the remaining finishes.
            for key, t in finish_at.items():
                if t > engine.clock:
                    engine.clock = t
                    engine.finish(key)
            break

    stats.wall_time_s = time.perf_counter() - t_start
    stats.sim_time_s = engine.clock
    stats.admitted = len(admitted_at)
    if utilization_samples:
        stats.avg_cq_utilization = (sum(utilization_samples)
                                    / len(utilization_samples))
    sums: dict[str, float] = {}
    counts: dict[str, int] = {}
    for key, t in admitted_at.items():
        cls = class_of[key]
        sums[cls] = sums.get(cls, 0.0) + t
        counts[cls] = counts.get(cls, 0) + 1
    stats.avg_time_to_admission_s = {
        cls: sums[cls] / counts[cls] for cls in sums}
    return stats


def check(stats: RunStats, spec: RangeSpec) -> list[str]:
    """The rangespec checker (test/performance/scheduler checker)."""
    errs = []
    if (spec.max_wall_time_s is not None
            and stats.sim_time_s > spec.max_wall_time_s):
        errs.append(f"wall time {stats.sim_time_s:.1f}s > "
                    f"{spec.max_wall_time_s}s")
    if (spec.min_avg_cq_utilization is not None
            and stats.avg_cq_utilization < spec.min_avg_cq_utilization):
        errs.append(
            f"utilization {stats.avg_cq_utilization:.2f} < "
            f"{spec.min_avg_cq_utilization}")
    for cls, limit in spec.max_avg_time_to_admission_s.items():
        got = stats.avg_time_to_admission_s.get(cls)
        if got is not None and got > limit:
            errs.append(f"time-to-admission[{cls}] {got:.1f}s > {limit}s")
    return errs
