"""Benchmark scenario generation — the equivalent of the reference perf
runner's generator configs (test/performance/scheduler/configs/*/
generator.yaml): cohorts x ClusterQueues with borrowing, and a pending
workload population in small/medium/large classes.

The baseline-like scenario mirrors the shape of the reference baseline
(5 cohorts x 6 CQs, 15k workloads in 3 size classes) scaled up to the
north-star size (1k CQs, 50k workloads)."""

from __future__ import annotations

import random
from dataclasses import dataclass

from kueue_tpu.api.types import (
    ClusterQueue,
    Cohort,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_tpu.workload_info import WorkloadInfo

CPU = "cpu"


@dataclass
class Scenario:
    cluster_queues: list
    cohorts: list
    flavors: list
    local_queues: list
    workloads: list  # api Workloads (pending)

    def pending_infos(self):
        lq_to_cq = {lq.name: lq.cluster_queue for lq in self.local_queues}
        return [WorkloadInfo.from_workload(w, lq_to_cq[w.queue_name])
                for w in self.workloads]


def baseline_like(n_cohorts: int = 200, cqs_per_cohort: int = 5,
                  n_workloads: int = 50_000, nominal_per_cq: int = 5_000,
                  seed: int = 0, sized_to_fit: bool = True) -> Scenario:
    """5-cohorts-x-6-CQs shape scaled: each CQ has nominal quota and can
    borrow within its cohort; workloads come in 1/5/20-unit classes
    (reference baseline generator.yaml:4-33).

    With ``sized_to_fit`` the total demand stays within total capacity so
    a drain admits everything (pure decision-throughput measurement).
    """
    rng = random.Random(seed)
    n_cqs = n_cohorts * cqs_per_cohort
    cohorts = [Cohort(f"cohort-{i}") for i in range(n_cohorts)]
    flavors = [ResourceFlavor("default")]

    # Size classes in milli-units: small=1, medium=5, large=20 units
    # (reference baseline generator.yaml class mix).
    classes = [(1000, 0.70), (5000, 0.20), (20000, 0.10)]
    sizes = []
    for _ in range(n_workloads):
        r = rng.random()
        acc = 0.0
        size = classes[-1][0]
        for sz, frac in classes:
            acc += frac
            if r < acc:
                size = sz
                break
        sizes.append(size)
    if sized_to_fit:
        # Capacity sized so the cohort-borrowing drain can admit ~all of
        # the population (slack for uneven per-cohort demand).
        nominal_per_cq = max(nominal_per_cq,
                             int(sum(sizes) / (n_cqs * 0.85)) + 1)

    cqs, lqs = [], []
    for i in range(n_cqs):
        name = f"cq-{i}"
        cqs.append(ClusterQueue(
            name=name, cohort=f"cohort-{i % n_cohorts}",
            resource_groups=(ResourceGroup(
                (CPU,),
                (FlavorQuotas("default",
                              {CPU: ResourceQuota(nominal_per_cq)}),)),),
        ))
        lqs.append(LocalQueue(f"lq-{i}", "default", name))

    workloads = [
        Workload(
            name=f"wl-{i}", queue_name=f"lq-{rng.randrange(n_cqs)}",
            priority=rng.choice([0, 0, 0, 50, 100]),
            creation_time=float(i),
            pod_sets=(PodSet("main", 1, {CPU: size}),))
        for i, size in enumerate(sizes)
    ]
    return Scenario(cqs, cohorts, flavors, lqs, workloads)


def hierarchical_fair(n_roots: int = 50, mids_per_root: int = 2,
                      cqs_per_mid: int = 5, n_workloads: int = 20_000,
                      nominal_per_cq: int = 4_000, seed: int = 1,
                      oversubscribe: float = 1.5) -> Scenario:
    """BASELINE.json config 3: 3-level cohort tree (root -> mid -> CQs)
    with fair-sharing weights at every level and demand oversubscribed so
    the DRS tournament ordering decides who gets capacity.

    Workload sizes scale to the tree's capacity so the scenario really
    contains ``n_workloads`` workloads (the round-2 form silently capped
    the count at the capacity budget — a 674-workload 13 ms "bench")."""
    from kueue_tpu.api.types import FairSharing

    rng = random.Random(seed)
    cohorts, cqs, lqs = [], [], []
    ci = 0
    for r in range(n_roots):
        cohorts.append(Cohort(
            f"root-{r}", resource_groups=(ResourceGroup(
                (CPU,), (FlavorQuotas("default",
                                      {CPU: ResourceQuota(
                                          nominal_per_cq * 2)}),)),)))
        for m in range(mids_per_root):
            cohorts.append(Cohort(
                f"mid-{r}-{m}", parent=f"root-{r}",
                fair_sharing=FairSharing(
                    weight=rng.choice([0.5, 1.0, 2.0]))))
            for _ in range(cqs_per_mid):
                name = f"cq-{ci}"
                cqs.append(ClusterQueue(
                    name=name, cohort=f"mid-{r}-{m}",
                    fair_sharing=FairSharing(
                        weight=rng.choice([0.5, 1.0, 1.0, 2.0])),
                    resource_groups=(ResourceGroup(
                        (CPU,),
                        (FlavorQuotas("default",
                                      {CPU: ResourceQuota(
                                          nominal_per_cq)}),)),)))
                lqs.append(LocalQueue(f"lq-{ci}", "default", name))
                ci += 1
    n_cqs = ci
    capacity = n_roots * nominal_per_cq * 2 \
        + n_cqs * nominal_per_cq
    budget = int(capacity * oversubscribe)
    avg = max(1, budget // n_workloads)
    sizes = [max(1, avg // 2), avg, avg * 2]
    workloads = []
    for i in range(n_workloads):
        workloads.append(Workload(
            name=f"wl-{i}", queue_name=f"lq-{rng.randrange(n_cqs)}",
            priority=rng.choice([0, 0, 10]), creation_time=float(i),
            pod_sets=(PodSet("main", 1, {CPU: rng.choice(sizes)}),)))
    return Scenario(cqs, cohorts, [ResourceFlavor("default")], lqs,
                    workloads)
