"""Multi-cell federation: a dispatcher tier routing workloads across N
independent HA cells (each its own journal/lease/checkpoint/oracle
domain), with an at-least-once handoff protocol made exactly-once by
the workload-name dedup at each cell's front door (ha/replica.py).

The reference analog is the MultiKueue layer (admissionchecks/
multikueue + the workload dispatcher): one control plane nominates a
remote cluster, hands the workload off, and reconciles the remote
admission status back. Here the cells are kueue_tpu HA cells and the
correctness claim is a robustness claim: kill an entire cell
mid-admission and no workload is lost or admitted twice, globally
(tools/federation_smoke.py proves it under seeded multi-fault chains).
"""

from kueue_tpu.federation.cells import (  # noqa: F401
    CellBreaker,
    CellHandle,
    CellTransportError,
    HTTPCellTransport,
)
from kueue_tpu.federation.dispatcher import (  # noqa: F401
    FederationDispatcher,
)
