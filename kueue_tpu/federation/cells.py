"""Cell-facing plumbing for the federation dispatcher: the HTTP
transport to a cell's serving endpoint, and the per-cell health
machinery (probe backoff + circuit breaker).

The breaker mirrors the oracle supervisor's shape
(oracle/supervisor.py): CLOSED/OPEN/HALF_OPEN, demotion after
``threshold`` consecutive probe failures, cooldown measured in
dispatcher ticks with doubling capped at 8x, one half-open probe per
window. Probe pacing uses the same deterministic CRC jitter — every
dispatcher in a fleet decorrelates without a PRNG, and a replayed
dispatcher probes on the same schedule.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
import zlib
from typing import Optional

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
_STATE_CODE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


def _jitter01(*parts) -> float:
    """Deterministic uniform-ish fraction in [0, 1): CRC-32 of the
    probe coordinates (the supervisor's jitter, not a PRNG — no hidden
    state, no draw-order coupling)."""
    raw = zlib.crc32(":".join(str(p) for p in parts).encode("utf-8"))
    return (raw & 0xFFFFFFFF) / 4294967296.0


class CellTransportError(Exception):
    """The cell is unreachable (connection refused/reset, timeout) —
    the ONLY signal that feeds the breaker. An HTTP-level refusal
    (503 not-leader, 429 shed) is a healthy cell saying no."""


class HTTPCellTransport:
    """urllib transport to one cell's serving endpoint (serve --ha)."""

    def __init__(self, base_url: str, timeout: float = 5.0,
                 auth_token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = float(timeout)
        self.auth_token = auth_token

    @property
    def events_url(self) -> str:
        return self.base_url + "/events"

    def _request(self, path: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        hdrs = {"Content-Type": "application/json"}
        if self.auth_token:
            hdrs["Authorization"] = f"Bearer {self.auth_token}"
        hdrs.update(headers or {})
        req = urllib.request.Request(
            self.base_url + path, data=data, headers=hdrs,
            method="POST" if data is not None else "GET")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, json.loads(r.read() or b"null")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {}
            return e.code, body
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise CellTransportError(
                f"{self.base_url}{path}: {e}") from None

    def submit(self, wl_jsonable: dict,
               route_epoch: Optional[int] = None) -> dict:
        """POST /workloads with the fencing epoch. Returns the cell's
        verdict dict with ``code`` re-attached (the handler pops it
        into the HTTP status)."""
        headers = {}
        if route_epoch is not None:
            headers["X-Route-Epoch"] = str(int(route_epoch))
        code, body = self._request(
            "/workloads", data=json.dumps(wl_jsonable).encode(),
            headers=headers)
        body = body if isinstance(body, dict) else {}
        body["code"] = code
        return body

    def health(self) -> dict:
        """GET /debug/ha: role, epoch, state digest, shedder posture —
        the probe payload the router scores against."""
        _, body = self._request("/debug/ha")
        return body if isinstance(body, dict) else {}

    def workloads(self) -> list:
        """GET /workloads: the cell's registered workload list, used
        for admission confirmation and zombie reconciliation."""
        _, body = self._request("/workloads")
        return body if isinstance(body, list) else []

    def revoke(self, keys: list, epoch: int) -> dict:
        """POST /federation/revoke: fence + delete the given workload
        keys on the cell (zombie reconciliation)."""
        code, body = self._request(
            "/federation/revoke",
            data=json.dumps({"keys": list(keys),
                             "epoch": int(epoch)}).encode())
        body = body if isinstance(body, dict) else {}
        body["code"] = code
        return body


class CellBreaker:
    """Per-cell circuit breaker over health-probe outcomes, the
    supervisor's state machine re-keyed on dispatcher ticks."""

    def __init__(self, metrics=None, cell: str = "",
                 threshold: int = 3, cooldown_ticks: int = 8):
        self.metrics = metrics
        self.cell = cell
        self.threshold = max(1, int(threshold))
        self.cooldown_ticks = max(1, int(cooldown_ticks))
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opens = 0
        self.closes = 0
        self._cooldown = self.cooldown_ticks
        self._reopen_at: Optional[int] = None

    def allow_probe(self, tick: int) -> bool:
        """Gate in front of a probe attempt. False = stay demoted;
        True from OPEN means this probe is the half-open trial."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._reopen_at is not None and tick >= self._reopen_at:
                self._transition(HALF_OPEN, "probe window")
                return True
            return False
        return True  # HALF_OPEN: the probe itself

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != CLOSED:
            self.closes += 1
            self._cooldown = self.cooldown_ticks
            self._transition(CLOSED, "probe succeeded")

    def record_failure(self, tick: int) -> bool:
        """Returns True when this failure OPENS the breaker (the
        dispatcher drains the cell exactly once per open)."""
        self.consecutive_failures += 1
        if self.state == HALF_OPEN:
            self._cooldown = min(self._cooldown * 2,
                                 self.cooldown_ticks * 8)
            self._reopen_at = tick + self._cooldown
            self._transition(OPEN, "probe failed")
            return False  # already drained when it first opened
        if (self.state == CLOSED
                and self.consecutive_failures >= self.threshold):
            self.opens += 1
            self._reopen_at = tick + self._cooldown
            self._transition(OPEN,
                             f"{self.consecutive_failures} consecutive "
                             f"probe failures")
            return True
        return False

    def _transition(self, to: str, reason: str) -> None:
        if to == self.state:
            return
        if self.metrics is not None:
            try:
                self.metrics.counter(
                    "federation_breaker_transitions_total").inc(
                    (self.cell, self.state, to))
                self.metrics.gauge(
                    "federation_cell_breaker_state").set(
                    (self.cell,), _STATE_CODE[to])
            except KeyError:
                pass
        self.state = to

    def status(self) -> dict:
        return {"state": self.state,
                "consecutiveFailures": self.consecutive_failures,
                "opens": self.opens, "closes": self.closes,
                "cooldownTicks": self._cooldown,
                "reopenAt": self._reopen_at}


class CellHandle:
    """One federated cell as the dispatcher sees it: transport +
    breaker + fencing epoch + the last probe's scoring inputs."""

    def __init__(self, name: str, transport, zone: str = "",
                 metrics=None, probe_interval_ticks: int = 2,
                 breaker_threshold: int = 3,
                 breaker_cooldown_ticks: int = 8):
        self.name = name
        self.zone = zone
        self.transport = transport
        self.metrics = metrics
        self.breaker = CellBreaker(
            metrics=metrics, cell=name, threshold=breaker_threshold,
            cooldown_ticks=breaker_cooldown_ticks)
        # Fencing epoch: bumped (and journaled) every time the cell's
        # breaker opens. Handoffs carry it; the cell refuses revoked
        # keys at stale epochs, so a zombie cannot double-admit.
        self.epoch = 1
        self.up = False          # probe succeeded AND role == leader
        self.last_probe: dict = {}
        self.last_probe_tick = -1
        self.probe_interval_ticks = max(1, int(probe_interval_ticks))
        self._next_probe = 0

    def probe_due(self, tick: int) -> bool:
        return tick >= self._next_probe and self.breaker.allow_probe(tick)

    def schedule_next_probe(self, tick: int, failed: bool) -> None:
        """Decorrelated-jitter pacing: healthy cells re-probe every
        interval +- jitter; a failing cell backs off toward the
        breaker's cooldown so a dead cell costs one connect timeout
        per window, not per tick."""
        base = self.probe_interval_ticks
        if failed:
            base = max(base, min(self.breaker._cooldown,
                                 self.breaker.cooldown_ticks * 8))
        span = max(1, int(base * (0.5 + _jitter01(self.name, tick))))
        self._next_probe = tick + span

    def status(self) -> dict:
        return {"name": self.name, "zone": self.zone,
                "epoch": self.epoch, "up": self.up,
                "breaker": self.breaker.status(),
                "lastProbeTick": self.last_probe_tick,
                "role": self.last_probe.get("role", ""),
                "stateDigest": self.last_probe.get("stateDigest", "")}
