"""Aggregated cross-cell SSE view: one tailer thread per cell follows
the cell's /events stream and republishes every event into the
dispatcher's FanoutHub, tagged with the cell name. Browsers/CLIs watch
ONE endpoint (the dispatcher's /events) and see the whole federation.

Liveness under failure is structural, not best-effort: a dead cell
kills only its own tailer's connection — the thread reconnects with
capped backoff while every other cell's events (and the dispatcher's
own federation_route / federation_cell events) keep flowing through
the hub. The bench's federation_failover scenario asserts exactly
this: the aggregated stream stays live across a whole-cell SIGKILL.
"""

from __future__ import annotations

import json
import threading
import urllib.request


class CellEventTailer:
    """Follows one cell's SSE stream; republishes into ``hub``."""

    def __init__(self, cell_name: str, events_url: str, hub,
                 reconnect_seconds: float = 1.0):
        self.cell_name = cell_name
        self.events_url = events_url
        self.hub = hub
        self.reconnect_seconds = float(reconnect_seconds)
        self.events_relayed = 0
        self.reconnects = 0
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"fed-tail-{cell_name}", daemon=True)

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._follow()
            except (OSError, ValueError):
                pass  # connection refused/reset: the cell is down
            if self._stop.wait(self.reconnect_seconds):
                return
            self.reconnects += 1

    def _follow(self) -> None:
        # Short read timeout so a stalled stream re-checks _stop; the
        # cell's SSE heartbeat (~15 s) keeps healthy streams alive.
        with urllib.request.urlopen(self.events_url, timeout=30) as resp:
            kind = ""
            for raw in resp:
                if self._stop.is_set():
                    return
                line = raw.decode("utf-8", "replace").rstrip("\n")
                if line.startswith("event:"):
                    kind = line[len("event:"):].strip()
                elif line.startswith("data:"):
                    data = line[len("data:"):].strip()
                    self._relay(kind or "message", data)
                    kind = ""

    def _relay(self, kind: str, data: str) -> None:
        try:
            body = json.loads(data)
            if isinstance(body, dict):
                body.setdefault("cell", self.cell_name)
                data = json.dumps(body)
        except ValueError:
            pass  # non-JSON payload: relay verbatim
        self.hub.publish(kind, data)
        self.events_relayed += 1


class CellReadAggregator:
    """Aggregated per-cell reads over the global read plane: one
    ReadFrontend per cell (each routing to that cell's replicas, never
    its leader), fanned out per query and merged with per-cell
    staleness envelopes intact. A dead cell degrades to an explicit
    per-cell error entry — the federation answer never silently drops
    a cell, and the caller sees exactly which cell answered from which
    journal position at what age."""

    def __init__(self, frontends: dict):
        """``frontends``: {cell_name: kueue_tpu.readplane.ReadFrontend}."""
        self.frontends = dict(frontends)
        self.queries = 0

    def query(self, kind: str, arg: str = None) -> dict:
        self.queries += 1
        cells: dict = {}
        for name in sorted(self.frontends):
            try:
                cells[name] = self.frontends[name].query(kind, arg)
            except Exception as e:  # noqa: BLE001 — cell-wide outage
                cells[name] = {"error": str(e), "staleness": None}
        return {"kind": kind, "cells": cells,
                "staleness": {
                    name: (ans.get("staleness") or {}).get(
                        "wallAgeSeconds")
                    for name, ans in cells.items()}}

    def status(self) -> dict:
        return {"queries": self.queries,
                "cells": {name: fe.status()
                          for name, fe in sorted(self.frontends.items())}}


class EventAggregator:
    """Owns one tailer per cell; lifecycle matches the dispatcher."""

    def __init__(self, cells: list, hub,
                 reconnect_seconds: float = 1.0):
        self.tailers = [
            CellEventTailer(c.name, c.transport.events_url, hub,
                            reconnect_seconds=reconnect_seconds)
            for c in cells
            if hasattr(c.transport, "events_url")]

    def start(self) -> None:
        for t in self.tailers:
            t.start()

    def stop(self) -> None:
        for t in self.tailers:
            t.stop()

    def stats(self) -> dict:
        return {t.cell_name: {"relayed": t.events_relayed,
                              "reconnects": t.reconnects}
                for t in self.tailers}
