"""FederationDispatcher: the routing tier in front of N HA cells.

Protocol (the exactly-once argument, ARCHITECTURE.md "Federation"):

  1. ``submit`` picks a cell (quota headroom + SLO burn + zone
     locality), journals a ``fed_route`` INTENT record carrying the
     full workload body, and fsyncs it BEFORE the handoff leaves the
     process. A dispatcher crash at any later point replays to a
     consistent routing state: every unacked intent is re-sent.
  2. The handoff POST is at-least-once: re-sends are deduplicated by
     workload name at the cell's front door (ha/replica.py submit —
     200 deduplicated vs 201 fresh), so at-least-once sends compose to
     exactly-once admission per cell.
  3. Health probes feed a per-cell circuit breaker (cells.py, the
     oracle supervisor's shape). The breaker OPENING fences the cell:
     its epoch is bumped and journaled (``fed_cell``), and every route
     on it not yet CONFIRMED admitted is re-routed to survivors.
  4. A zombie cell rejoining (half-open probe succeeds) is reconciled
     before it re-enters rotation: any workload it admitted whose
     route now points elsewhere is revoked — deleted cell-side under
     the bumped fence epoch, so a late handoff replay at the old epoch
     is refused (409) and the zombie cannot double-admit.

Route states: intent -> acked -> admitted (terminal). State changes
are journaled so a crashed dispatcher never re-routes a workload it
already confirmed. The journal kinds are declared ephemeral in
store/journal.py (graftlint R1): they fold into THIS dispatcher's
routing table, never into an engine.
"""

from __future__ import annotations

import json
from typing import Optional

from kueue_tpu.api.serde import to_jsonable
from kueue_tpu.federation.cells import (
    OPEN,
    CellHandle,
    CellTransportError,
)

# Test hook (the MAINTENANCE_CRASH_HOOK idiom): called with the handoff
# ordinal and workload key AFTER the route intent is durable and BEFORE
# the transport send — the nastiest point for a dispatcher crash.
HANDOFF_CRASH_HOOK = None

INTENT, ACKED, ADMITTED = "intent", "acked", "admitted"
_CONFIRMED_STATUSES = ("Admitted", "QuotaReserved", "Finished")


class FederationDispatcher:
    """Routes workloads across ``cells``; owns the durable route
    journal at ``journal_path`` (store/journal.py segments)."""

    def __init__(self, journal_path: str, cells: list,
                 metrics=None, hub=None, zone: str = "",
                 confirm_interval_ticks: int = 2,
                 locality_label: str = "kueue.tpu/zone",
                 fsync: bool = True):
        from kueue_tpu.store.journal import Journal

        self.cells: dict[str, CellHandle] = {c.name: c for c in cells}
        self.metrics = metrics
        self.hub = hub
        self.zone = zone
        self.locality_label = locality_label
        self.confirm_interval_ticks = max(1, int(confirm_interval_ticks))
        self.tick_seq = 0
        self.handoffs = 0
        self.redispatches = 0
        self.revocations = 0
        for c in self.cells.values():
            if c.metrics is None:
                c.metrics = metrics
                c.breaker.metrics = metrics
        # key -> route record (the fold of the journal's fed_route
        # stream; the journal is the source of truth across crashes).
        self.routes: dict[str, dict] = {}
        self.journal = Journal(journal_path, fsync=fsync)
        self._replay()

    # -- crash recovery --

    def _replay(self) -> None:
        """Fold the route journal: last record wins per key. Unacked
        intents go back on the wire (at-least-once; the cells dedup)."""
        cell_state: dict[str, dict] = {}
        for rec in self.journal.replay():
            obj = rec.get("obj", {})
            if rec["kind"] == "fed_route":
                if rec["op"] == "delete":
                    self.routes.pop(rec["key"], None)
                else:
                    self.routes[obj["name"]] = dict(obj)
            elif rec["kind"] == "fed_cell" and rec["op"] != "delete":
                cell_state[obj["name"]] = obj
        for name, st in cell_state.items():
            cell = self.cells.get(name)
            if cell is not None:
                # Epochs only move forward: a replayed fence must
                # still dominate anything the old process handed out.
                cell.epoch = max(cell.epoch, int(st.get("epoch", 1)))
                if not st.get("up", True):
                    # Last journaled word on this cell was a fence with
                    # no reconcile after it: a dispatcher that crashed
                    # in that window must still treat the cell's next
                    # successful probe as a zombie rejoin.
                    cell.needs_reconcile = True

    # -- routing --

    def _headroom_score(self, cell: CellHandle, wl_zone: str) -> float:
        """Lower is better. Quota headroom proxy (the cell's own
        registered+in-flight load), SLO burn (the cell shedder's
        SLO-coupled factor: 1.0 = budget intact), topology locality."""
        load = float(cell.last_probe.get("workloads", 0))
        load += sum(1 for r in self.routes.values()
                    if r["cell"] == cell.name and r["state"] != ADMITTED)
        shed = cell.last_probe.get("shedder") or {}
        burn = 1.0 - float(shed.get("factor", 1.0))
        locality = 0.0 if (wl_zone and wl_zone == cell.zone) else 4.0
        if not wl_zone:
            locality = 0.0
        return load + 8.0 * burn + locality

    def _pick_cell(self, workload=None,
                   exclude: tuple = ()) -> Optional[CellHandle]:
        wl_zone = ""
        if workload is not None:
            labels = getattr(workload, "labels", None) or {}
            wl_zone = labels.get(self.locality_label, "")
        best, best_score = None, None
        for name in sorted(self.cells):
            cell = self.cells[name]
            if name in exclude or not cell.up:
                continue
            score = self._headroom_score(cell, wl_zone)
            if best_score is None or score < best_score:
                best, best_score = cell, score
        return best

    # -- the write front door (serve.py --federate POSTs land here) --

    def submit(self, workload, now: float) -> dict:
        key = workload.key
        existing = self.routes.get(key)
        if existing is not None:
            # Idempotent retry across the whole federation: the route
            # journal is the dedup surface, exactly like the cell-side
            # workload-name dedup one layer down.
            return {"accepted": True, "code": 200, "workload": key,
                    "deduplicated": True, "cell": existing["cell"],
                    "state": existing["state"]}
        cell = self._pick_cell(workload)
        if cell is None:
            return {"accepted": False, "code": 503,
                    "reason": "no healthy cell",
                    "retryAfter": 1.0,
                    "cells": [c.status() for c in self.cells.values()]}
        rec = {"name": key, "cell": cell.name, "state": INTENT,
               "epoch": cell.epoch, "attempt": 1,
               "wl": to_jsonable(workload), "ts": now}
        # Intent durable BEFORE the handoff leaves the process: the
        # crash-honesty half of the exactly-once story.
        self.journal.apply("fed_route", rec, ts=now)
        self.journal.sync()
        self.routes[key] = rec
        verdict = self._handoff(rec, now)
        code = 201 if verdict.get("code") == 201 else (
            200 if verdict.get("code") == 200 else 202)
        return {"accepted": True, "code": code, "workload": key,
                "cell": cell.name, "state": rec["state"]}

    def _handoff(self, rec: dict, now: float) -> dict:
        """One at-least-once send of a route intent to its cell."""
        global HANDOFF_CRASH_HOOK
        cell = self.cells[rec["cell"]]
        self.handoffs += 1
        if HANDOFF_CRASH_HOOK is not None:
            HANDOFF_CRASH_HOOK(self.handoffs, rec["name"])
        try:
            # graftlint: allow[F1] at-least-once handoff of an already-durable intent: every caller journals+fsyncs the route record before invoking _handoff; the ACK can only be journaled after the RPC returns
            verdict = cell.transport.submit(rec["wl"],
                                            route_epoch=rec["epoch"])
        except CellTransportError as e:
            self._count("federation_dispatch_total",
                        (cell.name, "unreachable"))
            return {"code": 0, "error": str(e)}
        code = verdict.get("code", 0)
        if code in (200, 201):
            rec["state"] = ACKED
            self.journal.apply("fed_route", rec, ts=now)
            self._count("federation_dispatch_total", (cell.name, "acked"))
            self._observe("federation_handoff_latency_seconds",
                          (cell.name,), max(0.0, now - rec["ts"]))
            self._publish("federation_route",
                          {"workload": rec["name"], "cell": cell.name,
                           "state": ACKED})
        elif code == 409:
            # Fenced: the cell saw this key revoked at our epoch or
            # newer — a newer route owns it. Leave the record for the
            # resend loop to re-route under a fresh epoch.
            self._count("federation_dispatch_total", (cell.name, "fenced"))
        else:
            # 503 (mid-election) / 429 (shed): healthy refusal, the
            # resend loop retries next tick.
            self._count("federation_dispatch_total",
                        (cell.name, f"http{code}"))
        return verdict

    # -- the drive loop --

    def tick(self, now: float) -> None:
        """One dispatcher cycle: probe due cells, drain newly-opened
        breakers, re-send pending intents, confirm admissions."""
        self.tick_seq += 1
        for name in sorted(self.cells):
            cell = self.cells[name]
            if not cell.probe_due(self.tick_seq):
                continue
            self._probe(cell, now)
        self._resend(now)
        if self.tick_seq % self.confirm_interval_ticks == 0:
            self._confirm(now)
        self.journal.sync()
        self._export()

    def _probe(self, cell: CellHandle, now: float) -> None:
        cell.last_probe_tick = self.tick_seq
        try:
            payload = cell.transport.health()
        except CellTransportError:
            was_up = cell.up
            cell.up = False
            opened = cell.breaker.record_failure(self.tick_seq)
            cell.schedule_next_probe(self.tick_seq, failed=True)
            if opened:
                self._drain(cell, now)
            elif was_up:
                # graftlint: allow[F1] pure health notification: probe transitions are transient cell state, never journaled — there is nothing for durability to order against
                self._publish("federation_cell",
                              {"cell": cell.name, "up": False,
                               "reason": "probe failed"})
            return
        cell.breaker.record_success()
        cell.last_probe = payload
        cell.schedule_next_probe(self.tick_seq, failed=False)
        is_leader = payload.get("role") == "leader"
        if not is_leader:
            # Reachable but mid-election: healthy refusal, not a fault.
            cell.up = False
            return
        if getattr(cell, "needs_reconcile", False):
            # Zombie rejoin: reconcile BEFORE re-entering rotation.
            if not self._reconcile(cell, now):
                return
        if not cell.up:
            cell.up = True
            # graftlint: allow[F1] pure health notification: probe transitions are transient cell state, never journaled — there is nothing for durability to order against
            self._publish("federation_cell",
                          {"cell": cell.name, "up": True,
                           "epoch": cell.epoch})

    def _drain(self, cell: CellHandle, now: float) -> None:
        """Whole-cell failure path: fence the cell (epoch bump,
        journaled), then re-route everything on it not yet CONFIRMED
        admitted. Confirmed admissions stay — they are durable in the
        cell's own journal and come back with it."""
        cell.up = False
        cell.needs_reconcile = True
        cell.epoch += 1
        self.journal.apply("fed_cell",
                           {"name": cell.name, "epoch": cell.epoch,
                            "up": False}, ts=now)
        self.journal.sync()
        moved = 0
        for key in sorted(self.routes):
            rec = self.routes[key]
            if rec["cell"] != cell.name or rec["state"] == ADMITTED:
                continue
            target = self._pick_cell(exclude=(cell.name,))
            if target is None:
                continue  # no survivors yet; _resend keeps trying
            rec.update(cell=target.name, state=INTENT,
                       epoch=target.epoch,
                       attempt=rec.get("attempt", 1) + 1)
            self.journal.apply("fed_route", rec, ts=now)
            self._count("federation_redispatch_total",
                        (cell.name, target.name))
            self._handoff(rec, now)
            moved += 1
        self.redispatches += moved
        self.journal.sync()
        self._publish("federation_cell",
                      {"cell": cell.name, "up": False,
                       "epoch": cell.epoch, "drained": moved,
                       "reason": "breaker open"})

    def _resend(self, now: float) -> None:
        """At-least-once delivery of pending intents. Intents stranded
        on a down cell are re-routed as capacity appears."""
        for key in sorted(self.routes):
            rec = self.routes[key]
            if rec["state"] != INTENT:
                continue
            cell = self.cells.get(rec["cell"])
            if cell is not None and cell.up:
                self._handoff(rec, now)
            elif cell is None or cell.breaker.state == OPEN:
                target = self._pick_cell(exclude=(rec["cell"],))
                if target is None:
                    continue
                rec.update(cell=target.name, state=INTENT,
                           epoch=target.epoch,
                           attempt=rec.get("attempt", 1) + 1)
                self.journal.apply("fed_route", rec, ts=now)
                self._count("federation_redispatch_total",
                            (rec["cell"], target.name))
                self._handoff(rec, now)

    def _confirm(self, now: float) -> None:
        """Poll each live cell's workload list and promote acked
        routes to ADMITTED (terminal) once the cell reports the
        admission. Confirmed routes are never re-routed by a drain."""
        for name in sorted(self.cells):
            cell = self.cells[name]
            if not cell.up:
                continue
            try:
                listed = cell.transport.workloads()
            except CellTransportError:
                continue  # the probe path owns failure accounting
            confirmed = {f"{w['namespace']}/{w['name']}"
                         for w in listed
                         if w.get("status") in _CONFIRMED_STATUSES}
            for key, rec in self.routes.items():
                if (rec["cell"] == name and rec["state"] != ADMITTED
                        and key in confirmed):
                    rec["state"] = ADMITTED
                    self.journal.apply("fed_route", rec, ts=now)
                    self._publish("federation_route",
                                  {"workload": key, "cell": name,
                                   "state": ADMITTED})

    def _reconcile(self, cell: CellHandle, now: float) -> bool:
        """Zombie-rejoin fencing: before the cell re-enters rotation,
        revoke every workload it admitted whose route now points at a
        survivor (it was drained away while the cell was dark). The
        revocation carries the post-drain fence epoch, so the zombie
        also refuses any late handoff replay at the old epoch."""
        try:
            listed = cell.transport.workloads()
        except CellTransportError:
            return False
        present = {f"{w['namespace']}/{w['name']}": w.get("status")
                   for w in listed}
        revoke = []
        for key, status in sorted(present.items()):
            rec = self.routes.get(key)
            if rec is None or rec["cell"] == cell.name:
                continue
            revoke.append(key)
        if revoke:
            try:
                # graftlint: allow[F1] reconcile revokes keys whose re-route is already journaled+fsynced (the drain fence); the zombie's tombstones are the RPC's outcome, journaled after it returns
                cell.transport.revoke(revoke, epoch=cell.epoch)
            except CellTransportError:
                return False
            self.revocations += len(revoke)
            self._count("federation_revocations_total", (cell.name,),
                        n=len(revoke))
            # The tombstones fence everything AT OR BELOW the
            # revocation epoch; move the cell past it so a future
            # legitimate re-route of a once-revoked key back here
            # (its survivor died too) dominates the fence instead of
            # 409ing forever.
            cell.epoch += 1
        # Routes still pointing at the zombie (drained with no
        # survivor, or confirmed there pre-crash) that it durably
        # admitted are good: adopt the admission.
        for key, rec in self.routes.items():
            if (rec["cell"] == cell.name and rec["state"] != ADMITTED
                    and present.get(key) in _CONFIRMED_STATUSES):
                rec["state"] = ADMITTED
                self.journal.apply("fed_route", rec, ts=now)
        cell.needs_reconcile = False
        self.journal.apply("fed_cell",
                           {"name": cell.name, "epoch": cell.epoch,
                            "up": True}, ts=now)
        self.journal.sync()
        self._publish("federation_cell",
                      {"cell": cell.name, "up": True,
                       "epoch": cell.epoch, "revoked": len(revoke),
                       "reason": "reconciled"})
        return True

    # -- observability --

    def _count(self, family: str, labels: tuple, n: int = 1) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.counter(family).inc(labels, n)
        except KeyError:
            pass

    def _observe(self, family: str, labels: tuple, v: float) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.histogram(family).observe(v, labels)
        except KeyError:
            pass

    def _publish(self, kind: str, body: dict) -> None:
        if self.hub is not None:
            self.hub.publish(kind, json.dumps(body))

    def _export(self) -> None:
        if self.metrics is None:
            return
        counts = self.route_counts()
        try:
            for state in (INTENT, ACKED, ADMITTED):
                self.metrics.gauge("federation_routes").set(
                    (state,), float(counts.get(state, 0)))
            for cell in self.cells.values():
                self.metrics.gauge("federation_cell_up").set(
                    (cell.name,), 1.0 if cell.up else 0.0)
        except KeyError:
            pass

    def route_counts(self) -> dict:
        counts: dict[str, int] = {}
        for rec in self.routes.values():
            counts[rec["state"]] = counts.get(rec["state"], 0) + 1
        return counts

    def status(self) -> dict:
        per_cell: dict[str, dict] = {}
        for rec in self.routes.values():
            d = per_cell.setdefault(rec["cell"], {})
            d[rec["state"]] = d.get(rec["state"], 0) + 1
        return {
            "tick": self.tick_seq,
            "handoffs": self.handoffs,
            "redispatches": self.redispatches,
            "revocations": self.revocations,
            "routes": self.route_counts(),
            "cells": [dict(self.cells[n].status(),
                           routes=per_cell.get(n, {}))
                      for n in sorted(self.cells)],
        }

    def close(self) -> None:
        self.journal.sync()
        self.journal.close()
