"""Admitted-side live cache: the in-memory world model the scheduler
snapshots each cycle.

Reference: pkg/cache/scheduler/cache.go:129 (Cache) — CQ/cohort/flavor
registries, admitted-workload usage, assume/forget, snapshotting.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api.types import (
    ClusterQueue,
    Cohort,
    ResourceFlavor,
    StopPolicy,
    Workload,
)
from kueue_tpu.cache.snapshot import Snapshot, build_snapshot
from kueue_tpu.workload_info import WorkloadInfo


class Cache:
    """pkg/cache/scheduler/cache.go:129."""

    def __init__(self) -> None:
        self.cluster_queues: dict[str, ClusterQueue] = {}
        self.cohorts: dict[str, Cohort] = {}
        self.resource_flavors: dict[str, ResourceFlavor] = {}
        self.topologies: dict[str, object] = {}  # api.Topology
        self.nodes: dict[str, object] = {}  # tas.Node
        # key -> admitted/assumed WorkloadInfo
        self.workloads: dict[str, WorkloadInfo] = {}
        # Incremental admitted-side accounting (cache.go keeps usage live
        # and Snapshot() clones it; round 1 recomputed it per cycle from
        # every admitted workload — O(A) Python per snapshot). The exact
        # quantities ADDED are remembered per workload so removal
        # subtracts what was added even if the live object mutated
        # (reclaimable pods shrink usage in place).
        self.cq_usage: dict[str, dict] = {}  # cq -> FlavorResource -> int
        self.cq_workloads: dict[str, dict[str, WorkloadInfo]] = {}
        # Bumped on every admitted-set change: consumers (the bridge's
        # admitted-tensor cache) key their encodes on it.
        self.admitted_version = 0
        # Admitted-change log: keys whose admitted-side encoding may
        # have changed (upsert/delete/evict-flag). Drained by the
        # bridge's incremental AdmittedRows (tensor/rowcache.py). With
        # no consumer attached the set is CAPPED: on overflow it is
        # dropped and the epoch bumped, which tells a (future) consumer
        # to full-resync instead of trusting the log.
        self.admitted_dirty: set[str] = set()
        self.admitted_dirty_epoch = 0
        # Bumped on every CQ/cohort spec change (views memoize on it).
        self.spec_version = 0
        # flavor -> domain values tuple -> {resource: total}
        self.tas_usage_agg: dict[str, dict[tuple, dict[str, int]]] = {}
        self._wl_usage: dict[str, tuple] = {}  # key -> (cq, usage dict)
        self._wl_tas: dict[str, list] = {}  # key -> tas_domains tuples
        # workload_info.InfoOptions, set by the engine.
        self.info_options = None
        # Hook returning the set of defined AdmissionCheck names
        # (installed by AdmissionCheckManager); None = no check registry.
        self.admission_check_names = None
        # Cached TAS forest prototypes (see tas_prototypes()).
        self._tas_protos = None
        # Non-TAS pod usage (tas_non_tas_pod_cache.go): per-node totals
        # subtracted from TAS leaf capacity at prototype build.
        from kueue_tpu.tas.non_tas_usage import NonTASUsageCache
        self.non_tas_usage = NonTASUsageCache()

    # -- object lifecycle --

    def add_or_update_cluster_queue(self, cq: ClusterQueue) -> None:
        is_new = cq.name not in self.cluster_queues
        if self.cluster_queues.get(cq.name) is not cq:
            # Identity check keeps no-op resyncs of the same object from
            # invalidating spec-keyed memos (world tensors, views).
            self.spec_version += 1
        self.cluster_queues[cq.name] = cq
        if is_new:
            # Workloads admitted while their CQ was absent were excluded
            # from the aggregates (_account guards on CQ liveness).
            self.rebuild_accounting()

    def delete_cluster_queue(self, name: str) -> None:
        if self.cluster_queues.pop(name, None) is not None:
            self.spec_version += 1
            # Drop the deleted CQ's contributions — TAS aggregates are
            # flavor-keyed, so without this its still-registered
            # workloads would keep occupying shared topology leaves that
            # the from-scratch encoder (which filters by live CQs) frees.
            self.rebuild_accounting()

    def add_or_update_cohort(self, cohort: Cohort) -> None:
        if self.cohorts.get(cohort.name) is not cohort:
            self.spec_version += 1
        self.cohorts[cohort.name] = cohort

    def delete_cohort(self, name: str) -> None:
        if self.cohorts.pop(name, None) is not None:
            self.spec_version += 1

    def _invalidate_tas_prototypes(self) -> None:
        self._tas_protos = None

    def add_or_update_resource_flavor(self, rf: ResourceFlavor) -> None:
        was_tas = self._tas_flavor_names()
        self.resource_flavors[rf.name] = rf
        self.spec_version += 1
        self._invalidate_tas_prototypes()
        if was_tas != self._tas_flavor_names():
            self.rebuild_accounting()

    def delete_resource_flavor(self, name: str) -> None:
        rf = self.resource_flavors.pop(name, None)
        self.spec_version += 1
        self._invalidate_tas_prototypes()
        if rf is not None and rf.topology_name:
            self.rebuild_accounting()

    def add_or_update_topology(self, topology) -> None:
        self.topologies[topology.name] = topology
        self.spec_version += 1
        self._invalidate_tas_prototypes()

    def delete_topology(self, name: str) -> None:
        self.topologies.pop(name, None)
        self.spec_version += 1
        self._invalidate_tas_prototypes()

    def add_or_update_node(self, node) -> None:
        self.nodes[node.name] = node
        self._invalidate_tas_prototypes()

    def delete_node(self, name: str) -> None:
        self.nodes.pop(name, None)
        self._invalidate_tas_prototypes()

    def set_node_ready(self, name: str, ready: bool) -> bool:
        """In-place readiness flip WITH prototype invalidation — the
        one sanctioned way to mutate a registered node (mutating the
        object directly would leave stale TAS forests serving)."""
        node = self.nodes.get(name)
        if node is None:
            return False
        node.ready = ready
        self._invalidate_tas_prototypes()
        return True

    def tas_prototypes(self):
        """Cached per-flavor TAS forest prototypes (the tas_cache.go
        node-forest cache): rebuilt only when nodes/topologies/flavors
        change; snapshots fork them instead of re-adding every node."""
        if self._tas_protos is None:
            from kueue_tpu.tas.snapshot import TASFlavorSnapshot

            protos = {}
            for rf in self.resource_flavors.values():
                topo = self.topologies.get(rf.topology_name) \
                    if rf.topology_name else None
                if topo is None:
                    continue
                snap = TASFlavorSnapshot(
                    topo, flavor_tolerations=tuple(rf.tolerations))
                for node in self.nodes.values():
                    if all(node.labels.get(k) == v
                           for k, v in rf.node_labels.items()):
                        snap.add_node(
                            node,
                            non_tas_usage=self.non_tas_usage.node_usage(
                                node.name))
                protos[rf.name] = snap
            # Prototypes carry the LIVE admitted usage from birth;
            # _account_tas/_unaccount write commits through from here on
            # (snapshots share the forest under an undo scope instead of
            # forking it — tas/snapshot.py begin_cycle).
            for name, proto in protos.items():
                for values, totals in self.tas_usage_agg.get(name,
                                                             {}).items():
                    if any(totals.values()):
                        proto.install_usage(values, totals)
            self._tas_protos = protos
        return self._tas_protos

    def mark_admitted_dirty(self, key: str) -> None:
        if len(self.admitted_dirty) > 100_000:
            # Nobody is draining the log (no oracle bridge attached):
            # drop it and signal full-resync via the epoch.
            self.admitted_dirty.clear()
            self.admitted_dirty_epoch += 1
        self.admitted_dirty.add(key)

    # -- workloads (cache.go:766 AddOrUpdateWorkload / assume) --

    def _tas_flavor_names(self) -> set:
        return {rf.name for rf in self.resource_flavors.values()
                if rf.topology_name}

    def _account(self, key: str, info: WorkloadInfo) -> None:
        if info.cluster_queue not in self.cluster_queues:
            # Mirrors the from-scratch encoder's live-CQ filter; the
            # CQ-(re)add path rebuilds accounting to pick these up.
            return
        usage = info.usage()
        cq_usage = self.cq_usage.setdefault(info.cluster_queue, {})
        for fr, v in usage.items():
            cq_usage[fr] = cq_usage.get(fr, 0) + v
        self.cq_workloads.setdefault(info.cluster_queue, {})[key] = info
        tas = info.tas_domains(self._tas_flavor_names())
        self._account_tas(tas)
        self._wl_usage[key] = (info.cluster_queue, usage)
        self._wl_tas[key] = tas

    def _account_tas(self, tas) -> None:
        protos = self._tas_protos
        for flavor, values, single, count in tas:
            by_values = self.tas_usage_agg.setdefault(flavor, {})
            totals = by_values.setdefault(values, {})
            for res, per_pod in single.items():
                totals[res] = totals.get(res, 0) + per_pod * count
            # Pod slots (tas_flavor_snapshot.go:321).
            totals["pods"] = totals.get("pods", 0) + count
            if protos is not None:
                proto = protos.get(flavor)
                if proto is not None:
                    deltas = {res: per_pod * count
                              for res, per_pod in single.items()}
                    deltas["pods"] = deltas.get("pods", 0) + count
                    proto.commit_usage(values, deltas)

    def _unaccount(self, key: str) -> None:
        entry = self._wl_usage.pop(key, None)
        if entry is not None:
            cq_name, usage = entry
            cq_usage = self.cq_usage.get(cq_name, {})
            for fr, v in usage.items():
                left = cq_usage.get(fr, 0) - v
                if left:
                    cq_usage[fr] = left
                else:
                    cq_usage.pop(fr, None)
            wls = self.cq_workloads.get(cq_name)
            if wls is not None:
                wls.pop(key, None)
        protos = self._tas_protos
        for flavor, values, single, count in self._wl_tas.pop(key, ()):
            totals = self.tas_usage_agg.get(flavor, {}).get(values)
            if totals is None:
                continue
            for res, per_pod in single.items():
                left = totals.get(res, 0) - per_pod * count
                if left:
                    totals[res] = left
                else:
                    totals.pop(res, None)
            left = totals.get("pods", 0) - count
            if left:
                totals["pods"] = left
            else:
                totals.pop("pods", None)
            if protos is not None:
                proto = protos.get(flavor)
                if proto is not None:
                    deltas = {res: -per_pod * count
                              for res, per_pod in single.items()}
                    deltas["pods"] = deltas.get("pods", 0) - count
                    proto.commit_usage(values, deltas)

    def rebuild_accounting(self) -> None:
        """Recompute the incremental aggregates from the workload
        registry — the recovery path after flavor/topology registry
        changes reclassify which flavors are TAS."""
        # Live prototypes carry the old aggregates — drop them so the
        # rebuild's _account write-throughs can't double-install (the
        # next tas_prototypes() call re-installs the fresh aggregates).
        self._invalidate_tas_prototypes()
        self.cq_usage = {}
        self.cq_workloads = {}
        self.tas_usage_agg = {}
        self._wl_usage = {}
        self._wl_tas = {}
        self.admitted_version += 1
        self.admitted_dirty.update(self.workloads.keys())
        for key, info in self.workloads.items():
            self._account(key, info)

    def add_or_update_workload(self, wl: Workload,
                               info: Optional[WorkloadInfo] = None) -> bool:
        """``info``: reuse an already-derived WorkloadInfo (the
        scheduler's entry info, with the admission applied) — deriving
        one from scratch runs the whole effective-requests pipeline and
        was the dominant per-admission cost at scale."""
        if wl.status.admission is None:
            return False
        if (info is None or info.obj is not wl
                or info.cluster_queue != wl.status.admission.cluster_queue
                or wl.status.reclaimable_pods):
            # Reclaimable pods interleave with admission count scaling in
            # a path-dependent way — re-derive so every accounting path
            # agrees with the canonical from-scratch pipeline.
            info = WorkloadInfo.from_workload(
                wl, wl.status.admission.cluster_queue,
                options=self.info_options)
        if info.cluster_queue not in self.cluster_queues:
            return False
        self._unaccount(wl.key)
        self.workloads[wl.key] = info
        self._account(wl.key, info)
        self.admitted_version += 1
        self.mark_admitted_dirty(wl.key)
        return True

    def delete_workload(self, key: str) -> bool:
        self._unaccount(key)
        removed = self.workloads.pop(key, None) is not None
        if removed:
            # Only an actual admitted-set change invalidates consumers'
            # encodes (this is called for never-admitted keys too).
            self.admitted_version += 1
            self.mark_admitted_dirty(key)
        return removed

    def is_assumed(self, key: str) -> bool:
        return key in self.workloads

    # -- status / metrics inputs --

    def usage_for_cq(self, name: str):
        return dict(self.cq_usage.get(name, {}))

    def admitted_count(self, name: str) -> int:
        return len(self.cq_workloads.get(name, {}))

    # -- snapshot (cache.go Snapshot / snapshot.go:161) --

    def cq_inactive_reasons(self, cq) -> list[tuple[str, str]]:
        """clusterqueue.go:300 (inactiveReason): why this CQ can't admit.
        The single source of truth shared by scheduling (CQs with any
        reason are excluded from the snapshot) and the status controller
        (the Active condition). ``admission_check_names`` is a hook set
        by the AdmissionCheckManager."""
        reasons: list[tuple[str, str]] = []
        if cq.stop_policy != StopPolicy.NONE:
            reasons.append(("Stopped", "is stopped"))
        missing = [fq.name for rg in cq.resource_groups
                   for fq in rg.flavors
                   if fq.name not in self.resource_flavors]
        if missing:
            reasons.append((
                "FlavorNotFound",
                f"references missing ResourceFlavor(s): {missing}"))
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                rf = self.resource_flavors.get(fq.name)
                topo = getattr(rf, "topology_name", None) if rf else None
                if topo and topo not in self.topologies:
                    reasons.append((
                        "TopologyNotFound",
                        f"there is no Topology {topo!r} for TAS flavor "
                        f"{fq.name!r}"))
        if self.admission_check_names is not None and cq.admission_checks:
            known = self.admission_check_names()
            missing_checks = [c for c in cq.admission_checks
                              if c not in known]
            if missing_checks:
                reasons.append((
                    "AdmissionCheckNotFound",
                    f"references missing AdmissionCheck(s): "
                    f"{missing_checks}"))
        return reasons

    def inactive_cluster_queues(self) -> set[str]:
        return {name for name, cq in self.cluster_queues.items()
                if self.cq_inactive_reasons(cq)}

    def snapshot(self) -> Snapshot:
        return build_snapshot(
            list(self.cluster_queues.values()),
            list(self.cohorts.values()),
            list(self.resource_flavors.values()),
            None,
            inactive_cluster_queues=self.inactive_cluster_queues(),
            topologies=list(self.topologies.values()),
            nodes=list(self.nodes.values()),
            tas_prototypes=self.tas_prototypes(),
            cq_usage=self.cq_usage,
            cq_workloads=self.cq_workloads,
            tas_usage_agg=self.tas_usage_agg,
        )
